"""Checkpoint / resume.

The reference only saves a final `state_dict` (reference:
CommEfficient/cv_train.py:418-421 via the FedModel.__getattr__ hack at
fed_aggregator.py:372-376) and HF `save_pretrained` for GPT2
(fed_aggregator.py:208-211); there is no mid-run resume anywhere
(SURVEY.md §5). Here checkpointing is a first-class subsystem: the
full training state — PS weights, server momentum/error state, round
counter, per-client persistent state, scheduler step — round-trips
through one .npz file, enabling both the reference's end-of-training
save and true mid-run resume.

Preemption safety (the ROADMAP north-star environment is preemptible
TPU pods):

  * every write is ATOMIC — the bytes go to `<path>.tmp` and only a
    successful flush is `os.replace`d over the real name, so a
    preemption mid-write can never corrupt the previous checkpoint;
  * `save_rotating` keeps the newest `keep_last` round-stamped files
    plus a `<prefix>.latest` JSON manifest; `load_latest` resumes from
    the manifest (falling back to a glob, then to the legacy fixed
    `<prefix>.npz` name);
  * each checkpoint embeds a config FINGERPRINT
    (mode/grad_size/num_clients/error_type); `load_checkpoint`
    validates it against the resuming run and raises
    `CheckpointMismatchError` naming the offending field — instead of
    the opaque KeyError/broadcast error a shape mismatch used to
    surface as.
"""
from __future__ import annotations

import errno
import glob as _glob
import json
import os
import queue
import shutil
import threading
import zipfile
import zlib
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.federated.round import ClientState, ServerState
from commefficient_tpu.parallel import multihost as mh
from commefficient_tpu.telemetry.trace import TRACE

# the config fields a checkpoint must agree on to be loadable into a
# run (order fixed; all serialized as strings in the .npz)
FINGERPRINT_FIELDS = ("mode", "grad_size", "num_clients", "error_type")


class CheckpointMismatchError(ValueError):
    """A checkpoint written under a different config was loaded into
    this run. Carries the first offending fingerprint field so the
    error is actionable ('grad_size: checkpoint has 7840, this run
    expects 122570') rather than an opaque broadcast failure."""

    def __init__(self, path: str, field: str, found, expected):
        self.field, self.found, self.expected = field, found, expected
        super().__init__(
            f"checkpoint {path!r} does not match this run's config: "
            f"{field}: checkpoint has {found!r}, this run expects "
            f"{expected!r}. Point --checkpoint_path at a checkpoint "
            f"written with the same mode/model/client-count, or start "
            f"fresh without --resume.")


def config_fingerprint(cfg, num_clients: Optional[int] = None) -> dict:
    """The compatibility fingerprint embedded in every checkpoint."""
    return {
        "mode": cfg.mode,
        "grad_size": int(cfg.grad_size),
        "num_clients": int(num_clients if num_clients is not None
                           else (cfg.num_clients or 0)),
        "error_type": cfg.error_type,
    }


def validate_fingerprint(found: dict, expected: dict,
                         path: str) -> None:
    """Raise CheckpointMismatchError on the first FINGERPRINT_FIELDS
    entry where `found` disagrees with `expected`. Fields absent from
    `found` (legacy partial fingerprints) are skipped; values compare
    as strings (the .npz round-trips them that way)."""
    for k in FINGERPRINT_FIELDS:
        if k in found and str(found[k]) != str(expected[k]):
            raise CheckpointMismatchError(path, k, found[k], expected[k])


class AsyncCheckpointWriter:
    """Bounded-queue writer thread for off-critical-path checkpoint
    persistence (ISSUE 10, Config.pipeline).

    The device->host state GATHER stays on the caller's thread (it is
    collective in multi-controller runs and must block on span
    completion anyway); what moves off the critical path is the
    SERIALIZATION — np.savez + flush + fsync + atomic rename, plus the
    manifest/prune bookkeeping — which at checkpoint-every-span
    cadence otherwise stalls the round loop for the full disk write.
    Jobs run strictly FIFO on one thread, so the stamped file always
    lands before its manifest entry and rotation order is preserved;
    the atomic `.tmp` + os.replace discipline is unchanged (the
    closures are the same code, just executed later).

    The queue is BOUNDED (default: one write in flight plus one
    queued): a slow disk back-pressures the training loop instead of
    accumulating unbounded dirty state in memory. `drain()` blocks
    until every submitted write is durable and re-raises the first
    writer-side failure on the caller's thread — callers drain before
    any synchronous save (ordering) and in their crash/finally paths,
    so an InjectedFault drill flushes exactly like a clean shutdown."""

    _SENTINEL = object()

    def __init__(self, max_pending: int = 2,
                 drain_timeout: float = 0.0,
                 name: str = "checkpoint"):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(max_pending, 1))
        # deferred writer failure: stored on the writer thread,
        # consumed (cleared) on the caller's thread at drain/submit —
        # cross-thread state, guarded (graftsync SY001,
        # analysis/domains.SHARED_STATE) so a failure landing while
        # the caller swaps the slot is never lost
        self._exc: Optional[BaseException] = None
        self._exc_lock = threading.Lock()
        self._closed = False
        # writer-thread watchdog (ISSUE 12 satellite): drain()/close()
        # deadline in seconds (0 = wait forever); `name` labels the
        # TimeoutError so a hung spill queue reads "state-spill
        # writer", not "checkpoint writer"
        self._drain_timeout = float(drain_timeout)
        self._name = str(name)
        # graftscope correlation (ISSUE 13): per-writer submission
        # sequence — the producer-side `<name>_enqueue` instant and
        # this item's writer-thread `<name>_qwait`/`<name>_write`
        # spans share a `seq`, stitching the deferred write back to
        # the round that produced it
        self._seq = 0
        self._thread = threading.Thread(
            target=self._run, name=f"{name}-writer", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        import time as _time
        while True:
            item = self._q.get()
            try:
                if item is self._SENTINEL:
                    return
                job, enq_mono, seq, tags = item
                if enq_mono is not None:
                    TRACE.record(f"{self._name}_qwait", enq_mono,
                                 _time.monotonic(), seq=seq, **tags)
                try:
                    if enq_mono is not None:
                        with TRACE.span(f"{self._name}_write",
                                        seq=seq, **tags):
                            job()
                    else:
                        job()
                except BaseException as e:  # graftlint: disable=GL005 -- not swallowed: deferred re-raise on the caller's thread at drain()/submit() (_raise_pending); jobs are write closures, never fault-harness code
                    with self._exc_lock:
                        if self._exc is None:
                            self._exc = e
            finally:
                self._q.task_done()

    def _raise_pending(self) -> None:
        with self._exc_lock:
            exc, self._exc = self._exc, None
        if exc is not None:
            raise exc

    def submit(self, job: Callable[[], None]) -> None:
        """Queue one write closure; blocks when the queue is full (the
        bounded-memory back-pressure). A failure from an EARLIER job
        re-raises here so write errors surface at the next save, not
        silently at shutdown."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointWriter is closed")
        self._raise_pending()
        if TRACE.enabled:
            import time as _time
            seq, self._seq = self._seq, self._seq + 1
            # the enqueue instant runs on the PRODUCER thread inside
            # whatever stage span is open there (checkpoint, or the
            # tier_spill chunk), so its inherited round tag — carried
            # into the queue item — labels the writer-thread spans
            tags = TRACE.current_tags()
            TRACE.instant(f"{self._name}_enqueue", seq=seq,
                          q=self._q.qsize(), **tags)
            self._q.put((job, _time.monotonic(), seq, tags))
        else:
            self._q.put((job, None, 0, {}))

    def drain(self) -> None:
        """Block until every submitted write is durable; re-raise the
        first writer-side failure (an ENOSPC from a queued save
        surfaces HERE, on the caller's thread, not silently at
        shutdown). With a drain_timeout, a hung fsync raises
        TimeoutError naming this writer (utils/watchdog)."""
        from commefficient_tpu.utils.watchdog import drain_queue
        drain_queue(self._q, self._drain_timeout, self._name)
        self._raise_pending()

    def close(self) -> None:
        """Drain, then stop the thread. Idempotent. Honors the
        drain_timeout watchdog like drain()."""
        if self._closed:
            return
        from commefficient_tpu.utils.watchdog import drain_queue
        drain_queue(self._q, self._drain_timeout, self._name)
        self._closed = True
        self._q.put(self._SENTINEL)
        self._thread.join()
        self._raise_pending()


class Checkpoint(NamedTuple):
    """Loaded training state; accounting state rides along so resumed
    runs keep cumulative comm totals correct, the per-client
    throughput-tracker state (telemetry/clients.py) so measured
    client speeds survive preemption bit-exactly, and the round
    scheduler's counters (commefficient_tpu/scheduler, `sched_*`
    keys) for the same reason."""
    server: ServerState
    clients: Optional[ClientState]
    scheduler_step: int
    accountant_state: Optional[dict] = None
    prev_change_words: Optional[np.ndarray] = None
    fingerprint: Optional[dict] = None
    throughput: Optional[dict] = None
    scheduler: Optional[dict] = None
    # FedSampler stream state (data/sampler.py state_dict, `smp_*`
    # keys): rng + mid-epoch cursor/permutations, so a non-uniform
    # (throughput-aware) mid-epoch resume replays the exact same data
    # stream instead of re-drawing the epoch head
    sampler: Optional[dict] = None
    # O(cohort) client-state rows (ISSUE 9, `crows_*` keys): the
    # touched-row ids, per-block rows for exactly those ids, and the
    # init-weights base untouched topk_down rows reconstruct from —
    # checkpoint bytes scale with clients-ever-sampled, not the
    # population (FedModel.client_rows_payload / load_state). When
    # present, `clients` above is None: the two formats are exclusive.
    # Under Config.state_tier=host (ISSUE 11) the same dict also
    # carries `lru_ids`/`lru_slots` — the working set's recency order
    # and slot map, drained-spill-queue consistent — so a resumed run
    # replays the exact eviction stream; a device-tier loader ignores
    # them (row values are tier-independent).
    client_rows: Optional[dict] = None
    # pending async-admission entries (ISSUE 10, `asyb_*` keys):
    # deferred straggler contributions not yet admitted
    # (federated/async_agg.AsyncAdmitBuffer.state_dict), so a resumed
    # run admits exactly what the uninterrupted one would have
    async_admit: Optional[dict] = None


def save_checkpoint(path: str, server: ServerState,
                    clients: Optional[ClientState] = None,
                    scheduler_step: int = 0,
                    include_clients: bool = True,
                    accountant=None,
                    prev_change_words: Optional[np.ndarray] = None,
                    chunk_rows: int = 256,
                    fingerprint: Optional[dict] = None,
                    throughput: Optional[dict] = None,
                    scheduler: Optional[dict] = None,
                    sampler: Optional[dict] = None,
                    client_rows: Optional[dict] = None,
                    async_admit: Optional[dict] = None,
                    writer: Optional[AsyncCheckpointWriter] = None
                    ) -> str:
    """Write training state to `path` (.npz appended if absent).
    Per-client state can be excluded (include_clients=False) to keep
    files small when clients are stateless (error_type != local and
    no local momentum). Pass the FedModel's CommAccountant (and its
    _prev_change_words bitset) so resumed runs continue download
    accounting instead of restarting from 'round 1 is free'.

    The write is ATOMIC on the coordinator: bytes land in
    `<path>.tmp` and are `os.replace`d over the final name only after
    a successful flush, so a preemption mid-write leaves the previous
    checkpoint intact (a stray .tmp at most). Pass `fingerprint`
    (config_fingerprint(...)) so load_checkpoint can reject a resume
    under an incompatible config with an actionable error."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if not path.endswith(".npz"):
        path = path + ".npz"
    # gather_host: per-client state is cross-process sharded in
    # multi-controller runs. The gathers are collective — every process
    # must reach this call — but only the coordinator writes the file
    # (guard below), the reference's rank-0-saves discipline. The big
    # [num_clients, D] blocks go through the CHUNKED gather so
    # non-coordinator hosts never materialize them whole (multihost.
    # zeros' own no-host-global-materialization rule).
    arrays = {
        "ps_weights": mh.gather_host(server.ps_weights),
        "Vvelocity": mh.gather_host(server.Vvelocity),
        "Verror": mh.gather_host(server.Verror),
        "round_idx": mh.gather_host(server.round_idx),
        "scheduler_step": np.asarray(scheduler_step),
    }
    if include_clients and client_rows is not None:
        # O(cohort) format (ISSUE 9): persist ONLY the touched rows
        # (FedModel.client_rows_payload) — checkpoint bytes stay flat
        # while the population grows. Takes precedence over the dense
        # `clients` blocks; the loader reconstructs init + rows.
        for k, v in client_rows.items():
            arrays[f"crows_{k}"] = np.asarray(v)
    elif include_clients and clients is not None:
        arrays["client_errors"] = _gather_rows(clients.errors, chunk_rows)
        arrays["client_velocities"] = _gather_rows(clients.velocities,
                                                   chunk_rows)
        arrays["client_weights"] = _gather_rows(clients.weights, chunk_rows)
    if accountant is not None:
        for k, v in accountant.state_dict().items():
            arrays[f"acct_{k}"] = v
    if prev_change_words is not None:
        arrays["acct_prev_change_words"] = np.asarray(prev_change_words)
    if throughput is not None:
        # per-client throughput-tracker state (telemetry/clients.py
        # state_dict()); plain arrays, so the resume is bit-exact
        for k, v in throughput.items():
            arrays[f"thr_{k}"] = np.asarray(v)
    if scheduler is not None:
        # round-scheduler counters (scheduler.RoundScheduler
        # state_dict()); same bit-exact-resume contract as thr_*
        for k, v in scheduler.items():
            arrays[f"sched_{k}"] = np.asarray(v)
    if sampler is not None:
        # FedSampler stream state (data/sampler.py state_dict());
        # restores the exact mid-epoch data stream under non-uniform
        # sampling — same bit-exact-resume contract as thr_*/sched_*
        for k, v in sampler.items():
            arrays[f"smp_{k}"] = np.asarray(v)
    if async_admit is not None:
        # pending async-admission entries (ISSUE 10): deferred
        # straggler contributions awaiting their admit round — same
        # bit-exact-resume contract as thr_*/sched_*/smp_*
        for k, v in async_admit.items():
            arrays[f"asyb_{k}"] = np.asarray(v)
    if fingerprint is not None:
        for k in FINGERPRINT_FIELDS:
            arrays[f"fp_{k}"] = np.asarray(str(fingerprint[k]))

    def _write():
        # the atomic .tmp + os.replace write — unchanged whether it
        # runs inline or (writer given) on the persistence thread
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            if e.errno == errno.ENOSPC:
                # actionable disk-full error (ISSUE 12 satellite):
                # names the checkpoint rather than surfacing as a bare
                # "No space left on device" from deep inside numpy —
                # under the async writer this re-raises on the
                # caller's thread at the next submit()/drain()
                raise OSError(
                    e.errno,
                    f"checkpoint write to {path!r} failed: disk full "
                    "(ENOSPC). Free space on the checkpoint "
                    "filesystem or point --checkpoint_path at a "
                    "volume with room; the previous checkpoint is "
                    "intact (atomic .tmp+replace).") from e
            raise

    if mh.is_coordinator():
        if writer is None:
            _write()
        else:
            # off-critical-path serialization (Config.pipeline): the
            # gathers above already completed on this thread (they are
            # collective and block on device state anyway); only the
            # coordinator-local disk write is deferred. Durability is
            # writer.drain()'s contract — callers drain before any
            # synchronous save and at shutdown/crash.
            writer.submit(_write)
    mh.sync_processes("checkpoint-written")
    return path


def _gather_rows(x, chunk_rows: int = 256):
    """Gather a clients-sharded [rows, D] block to the COORDINATOR's
    host in bounded chunks: every process participates in each chunk's
    collective gather, but only the coordinator accumulates the full
    array — non-coordinators' transient peak is one chunk. Returns the
    full array on the coordinator, an empty placeholder elsewhere."""
    if (not mh.is_multihost() or getattr(x, "ndim", 1) < 2
            or x.shape[0] <= chunk_rows):
        return mh.gather_host(x)
    rows = x.shape[0]
    out = (np.empty(x.shape, np.dtype(x.dtype))
           if mh.is_coordinator() else None)
    for lo in range(0, rows, chunk_rows):
        hi = min(lo + chunk_rows, rows)
        block = mh.gather_host(x[lo:hi])
        if out is not None:
            out[lo:hi] = block
        del block
    return out if out is not None else np.zeros((0,), np.float32)


def load_checkpoint(path: str,
                    expect_fingerprint: Optional[dict] = None
                    ) -> Checkpoint:
    """Read training state back.

    `expect_fingerprint`: the resuming run's config_fingerprint(...) /
    FedModel.checkpoint_fingerprint. A checkpoint carrying a
    different fingerprint raises CheckpointMismatchError naming the
    offending field. Legacy checkpoints without a fingerprint get a
    best-effort grad_size check from the stored ps_weights shape —
    still a clear error instead of the downstream broadcast failure."""
    if not path.endswith(".npz"):
        path = path + ".npz"
    z = np.load(path)
    fingerprint = None
    if "fp_mode" in z.files:
        # tolerate partial fingerprints: a checkpoint written before
        # FINGERPRINT_FIELDS grew lacks the newer fp_* entries —
        # validate_fingerprint skips absent fields
        fingerprint = {k: str(z[f"fp_{k}"]) for k in FINGERPRINT_FIELDS
                       if f"fp_{k}" in z.files}
    if expect_fingerprint is not None:
        found = fingerprint
        if found is None:
            # legacy file: the flat weight vector length is still a
            # decisive compatibility signal
            found = {"grad_size": str(int(z["ps_weights"].shape[0]))}
        validate_fingerprint(found, expect_fingerprint, path)
    server = ServerState(
        ps_weights=jnp.asarray(z["ps_weights"]),
        Vvelocity=jnp.asarray(z["Vvelocity"]),
        Verror=jnp.asarray(z["Verror"]),
        round_idx=jnp.asarray(z["round_idx"]),
    )
    clients = None
    client_rows = None
    if "crows_ids" in z.files:
        client_rows = {k[len("crows_"):]: z[k] for k in z.files
                       if k.startswith("crows_")}
    elif "client_errors" in z:
        clients = ClientState(
            errors=jnp.asarray(z["client_errors"]),
            velocities=jnp.asarray(z["client_velocities"]),
            weights=jnp.asarray(z["client_weights"]),
        )
    acct = {k[len("acct_"):]: z[k] for k in z.files
            if k.startswith("acct_") and k != "acct_prev_change_words"}
    prev = (z["acct_prev_change_words"]
            if "acct_prev_change_words" in z.files else None)
    thr = {k[len("thr_"):]: z[k] for k in z.files
           if k.startswith("thr_")}
    sched = {k[len("sched_"):]: z[k] for k in z.files
             if k.startswith("sched_")}
    smp = {k[len("smp_"):]: z[k] for k in z.files
           if k.startswith("smp_")}
    asyb = {k[len("asyb_"):]: z[k] for k in z.files
            if k.startswith("asyb_")}
    return Checkpoint(server, clients, int(z["scheduler_step"]),
                      acct or None, prev, fingerprint, thr or None,
                      sched or None, smp or None, client_rows,
                      asyb or None)


# ---------------- keep-last-k rotation + latest manifest -----------------

def _manifest_path(prefix: str) -> str:
    return prefix + ".latest"


def _round_stamp(basename: str) -> int:
    """Round index from a `<name>-r<round:08d>.npz` basename, or -1
    for anything that doesn't match the stamp pattern."""
    try:
        return int(basename.rsplit("-r", 1)[1].split(".", 1)[0])
    except (IndexError, ValueError):
        return -1


def _atomic_write_text(path: str, text: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# ---------------- checkpoint integrity (ISSUE 12 satellite) --------------

class CorruptCheckpointError(ValueError):
    """A checkpoint file failed its integrity check: unreadable npz
    (truncated/torn bytes) or a per-array checksum mismatch against
    the manifest recorded at save time. The resilient loader
    (load_resilient) treats this as 'fall back to the previous
    rotation', not a crash."""


# the errors np.load raises on a truncated/corrupted .npz — the shapes
# a torn write, a partial copy, or bit rot actually produce
_NPZ_READ_ERRORS = (OSError, ValueError, KeyError, EOFError,
                    zipfile.BadZipFile)


def file_integrity(path: str) -> Tuple[Dict[str, int], bool]:
    """ONE read pass over a checkpoint .npz: per-array CRC32s plus a
    finite verdict (every float-dtype array is all-finite). The bytes
    come from DISK (save_rotating re-reads the file it just wrote, so
    the manifest vouches for the written artifact, not the in-memory
    arrays it came from); the finite bit rides the same pass because
    a second full read at every rotation would double checkpoint IO.
    The verdict feeds the manifest's `finite` map (ISSUE 16): the
    rollback loader skips checkpoints recorded non-finite instead of
    resuming into the same poisoned state it just tripped on."""
    out: Dict[str, int] = {}
    finite = True
    with np.load(path) as z:
        for name in z.files:
            a = np.ascontiguousarray(z[name])
            out[name] = zlib.crc32(a.tobytes()) & 0xFFFFFFFF
            if finite and np.issubdtype(a.dtype, np.floating):
                finite = bool(np.isfinite(a).all())
    return out, finite


def file_checksums(path: str) -> Dict[str, int]:
    """Per-array CRC32s of a checkpoint .npz (see file_integrity)."""
    return file_integrity(path)[0]


def verify_checkpoint_file(path: str,
                           checksums: Optional[Dict[str, int]]
                           ) -> None:
    """Integrity-check one checkpoint file: it must be a readable npz
    and, when the manifest recorded `checksums` for it, every array's
    CRC32 must match (missing/extra arrays are mismatches too).
    Raises CorruptCheckpointError; `checksums=None` (a legacy manifest
    or the glob/fixed-name fallback) checks readability only."""
    try:
        found = file_checksums(path)
    except _NPZ_READ_ERRORS as e:
        raise CorruptCheckpointError(
            f"checkpoint {path!r} is unreadable "
            f"({type(e).__name__}: {e}) — truncated or torn write?"
        ) from e
    if not checksums:
        return
    expect = {k: int(v) for k, v in checksums.items()}
    if found != expect:
        bad = sorted(set(expect) ^ set(found)
                     | {k for k in set(expect) & set(found)
                        if expect[k] != found[k]})
        raise CorruptCheckpointError(
            f"checkpoint {path!r} failed its integrity check: "
            f"array(s) {bad[:5]} disagree with the manifest checksums "
            "recorded at save time — corrupted on disk?")


def load_resilient(prefix: str,
                   expect_fingerprint: Optional[dict] = None,
                   on_fallback: Optional[Callable[[str, str], None]]
                   = None,
                   require_finite: bool = False
                   ) -> Optional[Tuple[str, Checkpoint]]:
    """Corruption-tolerant auto-resume (ISSUE 12 satellite): walk the
    rotation newest-first — manifest history, then stamped files the
    manifest lost, then the legacy fixed name — integrity-checking
    each candidate (verify_checkpoint_file, with the manifest's
    per-array checksums when recorded) and loading the FIRST good one.
    A corrupt/truncated newest checkpoint therefore falls back to the
    previous keep-last-k rotation instead of crashing mid-resume;
    every skipped candidate fires `on_fallback(path, reason)` (the
    drivers journal a loud `checkpoint_fallback` event) and prints.

    A CheckpointMismatchError (config fingerprint disagreement) is NOT
    corruption and re-raises immediately: silently falling back past a
    wrong-config checkpoint would resume from an ancestor of a
    different run. Returns (path, Checkpoint) or None when nothing
    loadable exists.

    `require_finite=True` (ISSUE 16 numeric rollback): ALSO skip any
    candidate whose manifest `finite` entry records False — a save
    that captured non-finite state, exactly what the rollback must
    walk past. A MISSING finite entry (pre-16 manifest, or the
    glob/fixed-name fallback with no manifest at all) means
    unknown-but-loadable, so old rotations stay resumable; the
    loaded arrays are the authority then."""
    ckpt_dir = os.path.dirname(prefix) or "."
    candidates: List[str] = []
    checksums: Dict[str, Dict[str, int]] = {}
    finite_map: Dict[str, bool] = {}
    try:
        with open(_manifest_path(prefix)) as f:
            manifest = json.load(f)
        for base in manifest.get("history", []):
            candidates.append(os.path.join(ckpt_dir, base))
        checksums = manifest.get("checksums", {}) or {}
        finite_map = manifest.get("finite", {}) or {}
    except (OSError, ValueError):
        pass
    # stamped files the manifest lost track of, newest first; then the
    # legacy fixed name — the latest_checkpoint_path fallback order
    seen = set(candidates)
    for p in sorted(_glob.glob(prefix + "-r*.npz"), reverse=True):
        if p not in seen:
            candidates.append(p)
    fixed = prefix if prefix.endswith(".npz") else prefix + ".npz"
    if fixed not in seen and os.path.exists(fixed):
        candidates.append(fixed)
    for path in candidates:
        if not os.path.exists(path):
            continue
        if require_finite and \
                finite_map.get(os.path.basename(path)) is False:
            reason = ("manifest records non-finite state at save "
                      "time (numeric rollback skips it)")
            print(f"checkpoint fallback: skipping non-finite "
                  f"{path!r}; trying the previous rotation")
            if on_fallback is not None:
                on_fallback(path, reason)
            continue
        try:
            verify_checkpoint_file(
                path, checksums.get(os.path.basename(path)))
            return path, load_checkpoint(
                path, expect_fingerprint=expect_fingerprint)
        except CheckpointMismatchError:
            raise
        except (CorruptCheckpointError, *_NPZ_READ_ERRORS) as e:
            reason = f"{type(e).__name__}: {e}"
            print(f"checkpoint fallback: skipping corrupt {path!r} "
                  f"({reason}); trying the previous rotation")
            if on_fallback is not None:
                on_fallback(path, reason)
    return None


def save_rotating(prefix: str, server: ServerState,
                  clients: Optional[ClientState] = None,
                  keep_last: int = 3, max_age_hours: float = 0.0,
                  writer: Optional[AsyncCheckpointWriter] = None,
                  **kw) -> str:
    """Atomic round-stamped save + `<prefix>.latest` manifest update +
    keep-last-k pruning. Returns the written path.

    Files are `<prefix>-r<round:08d>.npz`; the manifest is JSON
    {"latest": basename, "history": [basenames newest-first],
    "checksums": {...}, "finite": {basename: bool}} written
    atomically AFTER the checkpoint itself, so a preemption between
    the two leaves the manifest pointing at the previous (intact)
    file. Pruning removes only files the rotation itself wrote (they
    must match the stamp pattern), never a legacy fixed-name
    checkpoint. Collective in multi-controller runs (save_checkpoint
    gathers); only the coordinator touches the filesystem.

    max_age_hours > 0 ALSO prunes kept entries older than that
    wall-clock age (file mtime) — keep-last-k bounds disk by count,
    age pruning bounds it by time for long slow-rotating pod runs.
    The just-written `latest` entry is exempt (its mtime is fresh
    anyway), so the manifest can never dangle: every basename it
    lists — `latest` included — names a file that survived pruning."""
    round_idx = int(np.asarray(mh.gather_host(server.round_idx)))
    path = f"{prefix}-r{round_idx:08d}.npz"
    save_checkpoint(path, server, clients, writer=writer, **kw)

    def _manifest_and_prune():
        base = os.path.basename(path)
        mpath = _manifest_path(prefix)
        history = []
        old_sums: dict = {}
        old_fin: dict = {}
        try:
            with open(mpath) as f:
                m = json.load(f)
            history = list(m.get("history", []))
            old_sums = dict(m.get("checksums", {}) or {})
            old_fin = dict(m.get("finite", {}) or {})
        except (OSError, ValueError):
            pass
        # entries stamped AFTER this round belong to an abandoned
        # timeline (a dir reused without --resume, or a resume from an
        # older checkpoint): drop them from the history so the prune
        # below removes their files — otherwise a lost manifest would
        # let the glob fallback resume the abandoned run
        history = [h for h in history if _round_stamp(h) <= round_idx]
        history = [base] + [h for h in history if h != base]
        keep = history[:max(keep_last, 1)]
        if max_age_hours > 0:
            # age filter BEFORE the manifest write: the history must
            # only ever list files the prune below leaves on disk.
            # keep[0] is the file written moments ago — never pruned,
            # so `latest` always resolves.
            import time
            cutoff_ts = time.time() - max_age_hours * 3600.0
            ckpt_dir = os.path.dirname(prefix) or "."

            def fresh(basename: str) -> bool:
                try:
                    return (os.path.getmtime(
                        os.path.join(ckpt_dir, basename)) >= cutoff_ts)
                except OSError:
                    return False
            keep = [keep[0]] + [h for h in keep[1:] if fresh(h)]
        # per-array checksums (ISSUE 12 satellite) + finite bit
        # (ISSUE 16): computed in ONE pass by RE-READING the
        # just-written file, so the manifest vouches for the bytes on
        # disk — load_resilient verifies checksums at resume and
        # falls back on mismatch, and the numeric-rollback loader
        # (require_finite) walks past entries recording finite=False.
        # Prior entries carry forward; both dicts are trimmed to the
        # kept history so they cannot grow without bound.
        try:
            old_sums[base], old_fin[base] = file_integrity(path)
        except _NPZ_READ_ERRORS as e:
            # a checkpoint that cannot be re-read right after its
            # atomic replace is ALREADY corrupt — keep the manifest
            # entry checksum-less (readability is still checked at
            # load) but say so loudly
            print(f"checkpoint warning: cannot checksum just-written "
                  f"{path!r} ({e})")
        sums = {b: old_sums[b] for b in keep if b in old_sums}
        fins = {b: old_fin[b] for b in keep if b in old_fin}
        _atomic_write_text(mpath, json.dumps(
            {"latest": base, "history": keep, "checksums": sums,
             "finite": fins}, indent=2))
        # prune every stamped file NOT in the kept history (not just
        # the manifest's own tail): a lost/corrupt manifest must not
        # orphan earlier stamped files forever, and stale
        # higher-round files from a pre-resume timeline must not
        # shadow the live one in the glob fallback
        keep_set = set(keep)
        for old in _glob.glob(prefix + "-r*.npz"):
            if os.path.basename(old) not in keep_set:
                try:
                    os.remove(old)
                except OSError:
                    pass

    if mh.is_coordinator():
        if writer is None:
            _manifest_and_prune()
        else:
            # FIFO on the single writer thread: the stamped .npz write
            # submitted by save_checkpoint above lands before this
            # manifest update, preserving the "manifest never points
            # at a missing file" invariant
            writer.submit(_manifest_and_prune)
    mh.sync_processes("checkpoint-rotated")
    return path


def save_final(prefix: str, server: ServerState,
               clients: Optional[ClientState] = None,
               keep_last: int = 3, max_age_hours: float = 0.0,
               **kw) -> str:
    """End-of-run save: ONE collective gather, two artifacts — the
    rotated stamped checkpoint (+ manifest, so a later --resume sees
    this final state) and the legacy fixed `<prefix>.npz` the
    finetune/HF tooling loads. The fixed name is a coordinator-side
    atomic copy of the stamped bytes, not a second gather+serialize
    (which would double a multi-GB device->host transfer at
    shutdown). Returns the fixed-name path."""
    writer = kw.pop("writer", None)
    stamped = save_rotating(prefix, server, clients,
                            keep_last=keep_last,
                            max_age_hours=max_age_hours,
                            writer=writer, **kw)
    if writer is not None:
        # the fixed-name copy below reads the stamped bytes — the
        # queued write must be durable first
        writer.drain()
    fixed = prefix if prefix.endswith(".npz") else prefix + ".npz"
    if mh.is_coordinator():
        tmp = fixed + ".tmp"
        shutil.copyfile(stamped, tmp)
        os.replace(tmp, fixed)
    mh.sync_processes("checkpoint-final")
    return fixed


def latest_checkpoint_path(prefix: str) -> Optional[str]:
    """Resolve the newest checkpoint for `prefix`: the manifest's
    `latest` entry if it names an existing file, else the
    highest-round `<prefix>-r*.npz` on disk (manifest lost), else the
    legacy fixed `<prefix>.npz`, else None."""
    ckpt_dir = os.path.dirname(prefix) or "."
    try:
        with open(_manifest_path(prefix)) as f:
            base = json.load(f).get("latest")
        if base:
            cand = os.path.join(ckpt_dir, base)
            if os.path.exists(cand):
                return cand
    except (OSError, ValueError):
        pass
    stamped = sorted(_glob.glob(prefix + "-r*.npz"))
    if stamped:
        return stamped[-1]
    if os.path.exists(prefix + ".npz"):
        return prefix + ".npz"
    return None


def load_latest(prefix: str,
                expect_fingerprint: Optional[dict] = None
                ) -> Optional[Checkpoint]:
    """Auto-resume entry point: load the newest checkpoint for
    `prefix` (see latest_checkpoint_path), or None when there is
    nothing to resume from. Fingerprint-validated like
    load_checkpoint."""
    path = latest_checkpoint_path(prefix)
    if path is None:
        return None
    return load_checkpoint(path, expect_fingerprint=expect_fingerprint)


def transfer_for_finetune(old_params, new_template):
    """Head-swap transfer (reference resnet9.py:105-130 + finetune load
    at cv_train.py:377-384): copy every leaf whose path+shape matches
    the new model; leaves that differ (e.g. the classifier head for a
    different class count) keep the new model's fresh initialization.
    Returns (params, frozen_mask_pytree) where frozen_mask marks the
    transferred (frozen in the reference) leaves with 1.0."""
    old_flat = dict(jax.tree_util.tree_flatten_with_path(old_params)[0])
    new_flat, treedef = jax.tree_util.tree_flatten_with_path(new_template)

    out, frozen = [], []
    for path, leaf in new_flat:
        prev = old_flat.get(path)
        if prev is not None and prev.shape == leaf.shape:
            out.append(jnp.asarray(prev))
            frozen.append(jnp.ones((), jnp.float32))
        else:
            out.append(leaf)
            frozen.append(jnp.zeros((), jnp.float32))
    params = jax.tree_util.tree_unflatten(treedef, out)
    mask = jax.tree_util.tree_unflatten(treedef, frozen)
    return params, mask

"""Writer-thread watchdog (ISSUE 12 satellite).

The three bounded-queue persistence writers — the telemetry journal
(telemetry/journal.RunJournal), the checkpoint serializer
(utils/checkpoint.AsyncCheckpointWriter), and the tiered-state spill
queue (federated/statestore, which reuses AsyncCheckpointWriter) —
all drain with `queue.Queue.join()`, which waits FOREVER. A hung
fsync (dead NFS mount, a wedged FUSE filesystem) therefore turns the
crash-time drain — the one code path that runs exactly when the
operator most needs the process to finish dying — into a silent hang.

`drain_queue` is join-with-deadline: identical semantics to
`Queue.join()` when every queued write completes, a `TimeoutError`
NAMING the stuck writer when the deadline passes. The writers take
the timeout from `Config.writer_drain_timeout_s`
(`--writer_drain_timeout_s`; 0 keeps the wait-forever default, so
existing behavior is unchanged unless the knob is set).
"""
from __future__ import annotations

import queue
import time


def drain_queue(q: "queue.Queue", timeout: float, name: str) -> None:
    """`q.join()` bounded by `timeout` seconds.

    timeout <= 0 waits forever (plain join). On expiry raises
    TimeoutError naming `name` and the number of writes still queued —
    actionable ("the checkpoint writer is stuck — hung fsync?") where
    a bare hang is not. Uses the Queue's own all_tasks_done condition
    (the mechanism join() itself waits on), so completion wake-ups are
    immediate, not polled."""
    if timeout is None or timeout <= 0:
        q.join()
        return
    deadline = time.monotonic() + float(timeout)
    with q.all_tasks_done:
        while q.unfinished_tasks:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"{name} writer failed to drain within "
                    f"{float(timeout):.1f}s — {q.unfinished_tasks} "
                    "queued write(s) still pending (hung fsync / dead "
                    "filesystem?). The queue is NOT drained; raise "
                    "--writer_drain_timeout_s or fix the backing "
                    "store.")
            q.all_tasks_done.wait(remaining)

from commefficient_tpu.utils.schedules import PiecewiseLinear, Exp, LambdaLR  # noqa: F401
from commefficient_tpu.utils.logging import (  # noqa: F401
    Logger, TableLogger, TSVLogger, Timer, make_logdir,
)

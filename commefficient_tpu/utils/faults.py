"""Deterministic fault injection for federated rounds.

The round engine models two failure classes that real federated
deployments (FetchSGD's target setting) and preemptible TPU pods
exhibit and the reference never does:

  * client dropout — a sampled client fails to complete a round: its
    upload is excluded from aggregation, its persistent state rows are
    bit-untouched, and accounting charges it nothing;
  * run preemption — the whole training process dies between rounds
    and must resume from the newest checkpoint bit-exactly.

Both are driven from this module so tests can script failures
deterministically: `FaultSchedule` says exactly which clients drop in
which round and after which round the run "crashes" (a raised
`InjectedFault`), and `bernoulli_survivors` is the production-path
random dropout draw (`Config.client_dropout`), a pure function of
(seed, round) so a resumed run replays the identical survivor
sequence.

The schedule is consumed host-side by `FedModel` (federated/api.py):
the survivor mask it produces is passed into the jitted round as data
(`round.RoundBatch.survivors`), which keeps the mask visible to the
host accounting without any device sync, and keeps the jitted program
itself schedule-agnostic.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence

import numpy as np


class InjectedFault(RuntimeError):
    """Raised by FedModel when a FaultSchedule says the run crashes
    after a given round. The round index that completed last rides
    along so tests can checkpoint/resume at exactly that boundary."""

    def __init__(self, round_idx: int):
        super().__init__(
            f"injected fault: crash after round {round_idx}")
        self.round_idx = int(round_idx)


def bernoulli_survivors(seed: int, round_idx: int, num_workers: int,
                        dropout: float) -> np.ndarray:
    """The production dropout draw: [num_workers] f32 {0,1} survivor
    mask, Bernoulli(1 - dropout) per participant slot.

    Pure function of (seed, round_idx): resuming from a checkpoint at
    round k replays rounds k+1.. with the identical masks an
    uninterrupted run would have drawn — required for the crash->resume
    bit-equivalence contract. Drawn host-side with a counter-based
    numpy generator (no global RNG state)."""
    if dropout <= 0.0:
        return np.ones(num_workers, np.float32)
    rng = np.random.default_rng(
        np.random.SeedSequence([int(seed), 0x0D120, int(round_idx)]))
    return (rng.random(num_workers) >= dropout).astype(np.float32)


@dataclass(frozen=True)
class FaultSchedule:
    """A deterministic script of failures for one training run.

    drop:        {round_idx: client ids that drop that round}. Ids are
                 GLOBAL client ids; a listed id only matters if that
                 client was sampled into the round.
    drop_slots:  {round_idx: participant SLOT indices that drop} — for
                 tests that care about position in the round rather
                 than identity (e.g. "slot 0 of round 2").
    drop_all:    rounds where every sampled client drops (the
                 zero-survivor no-op case).
    crash_after: raise InjectedFault once the given round has fully
                 completed (state updated, accounting recorded) — the
                 preemption point a checkpoint/resume test recovers
                 from. None = never crash.
    """
    drop: Mapping[int, Sequence[int]] = field(default_factory=dict)
    drop_slots: Mapping[int, Sequence[int]] = field(default_factory=dict)
    drop_all: Sequence[int] = ()
    crash_after: Optional[int] = None

    def survival_mask(self, round_idx: int,
                      client_ids: np.ndarray) -> Optional[np.ndarray]:
        """[W] f32 survivor mask for this round, or None when the
        schedule says nothing about it (round runs untouched)."""
        round_idx = int(round_idx)
        client_ids = np.asarray(client_ids)
        if round_idx in set(int(r) for r in self.drop_all):
            return np.zeros(client_ids.shape[0], np.float32)
        mask = None
        dropped = self.drop.get(round_idx)
        if dropped is not None:
            mask = (~np.isin(client_ids,
                             np.asarray(dropped))).astype(np.float32)
        slots = self.drop_slots.get(round_idx)
        if slots is not None:
            if mask is None:
                mask = np.ones(client_ids.shape[0], np.float32)
            mask[np.asarray(slots, np.int64)] = 0.0
        return mask

    def should_crash(self, round_idx: int) -> bool:
        return (self.crash_after is not None
                and int(round_idx) == int(self.crash_after))

"""Deterministic fault injection for federated rounds.

The round engine models the failure classes that real federated
deployments (FetchSGD's target setting) and preemptible TPU pods
exhibit and the reference never does:

  * client dropout — a sampled client fails to complete a round: its
    upload is excluded from aggregation, its persistent state rows are
    bit-untouched, and accounting charges it nothing;
  * stragglers — a sampled client is SLOW rather than dead: it
    finishes only a fraction of its local work before the round
    deadline. The work fraction becomes a per-client completed-
    examples (single-step modes) / completed-steps (fedavg) budget
    inside the jitted round, aggregation weights by examples actually
    processed (FedNova-style), and a fraction below
    `Config.straggler_cutoff` degrades to the dropout path;
  * run preemption — the whole training process dies between rounds
    (`crash_after`) or while a scanned span is still in flight
    (`crash_in_span`, losing every round since the last span
    boundary) and must resume from the newest checkpoint bit-exactly.

All are driven from this module so tests can script failures
deterministically: `FaultSchedule` says exactly which clients drop or
slow down in which round and where the run "crashes" (a raised
`InjectedFault`), while `bernoulli_survivors` and
`straggler_work_fractions` are the production-path random draws
(`Config.client_dropout` / `Config.straggler_*`), pure functions of
(seed, round) so a resumed run replays the identical fault sequence.

The schedule is consumed host-side by `FedModel` (federated/api.py):
the survivor mask it produces is passed into the jitted round as data
(`round.RoundBatch.survivors`), which keeps the mask visible to the
host accounting without any device sync, and keeps the jitted program
itself schedule-agnostic.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence

import numpy as np

from commefficient_tpu.analysis.domains import DOMAINS


class InjectedFault(RuntimeError):
    """Raised by FedModel when a FaultSchedule says the run crashes
    after a given round. The round index that completed last rides
    along so tests can checkpoint/resume at exactly that boundary."""

    def __init__(self, round_idx: int):
        super().__init__(
            f"injected fault: crash after round {round_idx}")
        self.round_idx = int(round_idx)


def bernoulli_survivors(seed: int, round_idx: int, num_workers: int,
                        dropout: float) -> np.ndarray:
    """The production dropout draw: [num_workers] f32 {0,1} survivor
    mask, Bernoulli(1 - dropout) per participant slot.

    Pure function of (seed, round_idx): resuming from a checkpoint at
    round k replays rounds k+1.. with the identical masks an
    uninterrupted run would have drawn — required for the crash->resume
    bit-equivalence contract. Drawn host-side with a counter-based
    numpy generator (no global RNG state)."""
    if dropout <= 0.0:
        return np.ones(num_workers, np.float32)
    rng = np.random.default_rng(
        np.random.SeedSequence([int(seed), DOMAINS["dropout"],
                                int(round_idx)]))
    return (rng.random(num_workers) >= dropout).astype(np.float32)


def straggler_work_fractions(seed: int, round_idx: int, num_workers: int,
                             rate: float,
                             min_work: float = 0.1) -> np.ndarray:
    """The production straggler draw: [num_workers] f32 work fractions
    in (0, 1]. Each participant slot is a straggler with probability
    `rate`; a straggler's fraction is uniform in [min_work, 1),
    everyone else works at 1.0 (full round).

    Same replay contract as `bernoulli_survivors`: a pure function of
    (seed, round_idx) with its own counter-based generator (a distinct
    domain tag, so the straggler stream never aliases the dropout
    stream), required for crash->resume bit-equivalence."""
    if rate <= 0.0:
        return np.ones(num_workers, np.float32)
    rng = np.random.default_rng(
        np.random.SeedSequence([int(seed), DOMAINS["straggler"],
                                int(round_idx)]))
    is_straggler = rng.random(num_workers) < rate
    frac = min_work + (1.0 - min_work) * rng.random(num_workers)
    return np.where(is_straggler, frac, 1.0).astype(np.float32)


def poison_mask(seed: int, round_idx: int, num_workers: int,
                rate: float) -> np.ndarray:
    """The production value-fault draw (ISSUE 16, Config.poison_rate):
    [num_workers] f32 {0,1} mask, 1 marking a participant slot whose
    transmitted update is CORRUPTED this round (Config.poison_kind
    picks how — the jitted round applies it device-side, so the
    injection exercises the same screened program a real bad update
    would hit).

    Same replay contract as `bernoulli_survivors`: a pure function of
    (seed, round_idx) on its own counter-based generator and PRNG
    domain, so the poison stream never aliases the dropout/straggler
    streams and a rolled-back run re-poisons exactly the rounds the
    original did (which is what makes the forced-screen resume
    deterministic)."""
    if rate <= 0.0:
        return np.zeros(num_workers, np.float32)
    rng = np.random.default_rng(
        np.random.SeedSequence([int(seed), DOMAINS["poison"],
                                int(round_idx)]))
    return (rng.random(num_workers) < rate).astype(np.float32)


def byzantine_mask(seed: int, round_idx: int, num_workers: int,
                   rate: float) -> np.ndarray:
    """The production adversary draw (ISSUE 17,
    Config.byzantine_rate): [num_workers] f32 {0,1} mask, 1 marking a
    participant slot controlled by the scripted adversary this round
    (Config.attack picks the crafted update — the jitted round builds
    it device-side, so colluding attackers can read the honest
    cohort's statistics exactly as the threat model allows).

    Same replay contract as `poison_mask`: a pure function of
    (seed, round_idx) on its own counter-based generator and PRNG
    domain ("byzantine"), so the adversary stream never aliases the
    dropout/straggler/poison streams and a resumed run faces the
    identical attack sequence."""
    if rate <= 0.0:
        return np.zeros(num_workers, np.float32)
    rng = np.random.default_rng(
        np.random.SeedSequence([int(seed), DOMAINS["byzantine"],
                                int(round_idx)]))
    return (rng.random(num_workers) < rate).astype(np.float32)


@dataclass(frozen=True)
class FaultSchedule:
    """A deterministic script of failures for one training run.

    drop:        {round_idx: client ids that drop that round}. Ids are
                 GLOBAL client ids; a listed id only matters if that
                 client was sampled into the round.
    drop_slots:  {round_idx: participant SLOT indices that drop} — for
                 tests that care about position in the round rather
                 than identity (e.g. "slot 0 of round 2").
    drop_all:    rounds where every sampled client drops (the
                 zero-survivor no-op case).
    slow:        {round_idx: {participant SLOT: work fraction}} —
                 scripted stragglers. A listed slot completes only
                 that fraction of its local work (examples for
                 single-step modes, local SGD steps for fedavg);
                 unlisted slots work at 1.0. Composes with the random
                 Config.straggler_rate draw by elementwise minimum.
    poison:      {round_idx: participant SLOT indices whose update is
                 CORRUPTED that round} — scripted value faults
                 (ISSUE 16). The listed slots' transmitted updates
                 are corrupted device-side per Config.poison_kind;
                 composes with the random Config.poison_rate draw by
                 elementwise maximum. Unlike drop/slow, a poisoned
                 client still runs its round at full work — whether
                 its corruption reaches the server state is exactly
                 what Config.update_screen decides.
    crash_after: raise InjectedFault once the given round has fully
                 completed (state updated, accounting recorded) — the
                 preemption point a checkpoint/resume test recovers
                 from. None = never crash.
    crash_in_span: raise InjectedFault while the span CONTAINING this
                 round is still in flight — before any round of that
                 span commits state or accounting. Models a preemption
                 that kills the host mid-device-program: everything
                 since the last span boundary is lost, and resume must
                 land bit-exactly on the last flushed span. On the
                 per-round path each round is its own span of one.
                 UNLIKE crash_after (which fires only after its round
                 has committed, so a resumed run starting past it
                 never re-triggers), crash_in_span fires BEFORE its
                 round commits — a resumed run that re-installs the
                 same schedule restarts at that round and crashes at
                 the identical point again, forever. That models
                 repeated preemption of the same span; a chaos drill
                 that should make progress after resume must clear or
                 advance the schedule (set_fault_schedule(None)) once
                 the crash has been exercised, the way the tests
                 resume with a fresh, schedule-free model.

    Control-plane faults (ISSUE 12, parallel/plantransport.py —
    consumed by the plan TRANSPORT, not by FedModel):

    coordinator_crash_at: the COORDINATOR dies while broadcasting this
                 round's RoundPlan — before the plan ever reaches the
                 other controllers or the device (the plan may already
                 be write-ahead-journaled; the deterministic-restart
                 path recomputes it and cross-checks the journaled
                 digest). Raises InjectedFault(round - 1): the last
                 round that fully completed. Like crash_in_span, it
                 RE-FIRES if the schedule is still installed on the
                 resumed transport.
    broadcast_drop: rounds whose FIRST broadcast send is lost in
                 flight (TimeoutError; the utils/retry wrapper around
                 the send recovers on the next attempt).
    broadcast_dup: rounds delivered TWICE — receivers must install
                 idempotently (keyed by round index).
    broadcast_slow: {round_idx: n} — the first n receive attempts for
                 that round time out before the payload lands (models
                 a slow coordinator; the receiver's retry loop rides
                 it out).
    """
    drop: Mapping[int, Sequence[int]] = field(default_factory=dict)
    drop_slots: Mapping[int, Sequence[int]] = field(default_factory=dict)
    drop_all: Sequence[int] = ()
    slow: Mapping[int, Mapping[int, float]] = field(default_factory=dict)
    poison: Mapping[int, Sequence[int]] = field(default_factory=dict)
    # {round_idx: participant SLOT indices controlled by the
    # adversary that round} — the scripted drill harness (ISSUE 17).
    # Slot-indexed like poison; composes with the random
    # Config.byzantine_rate draw by elementwise maximum. An attacker
    # runs its round at full work and submits the crafted update
    # Config.attack selects; whether it reaches the server state is
    # what the robust aggregator (and screening) decide.
    byzantine: Mapping[int, Sequence[int]] = field(default_factory=dict)
    crash_after: Optional[int] = None
    crash_in_span: Optional[int] = None
    coordinator_crash_at: Optional[int] = None
    broadcast_drop: Sequence[int] = ()
    broadcast_dup: Sequence[int] = ()
    broadcast_slow: Mapping[int, int] = field(default_factory=dict)

    def survival_mask(self, round_idx: int,
                      client_ids: np.ndarray) -> Optional[np.ndarray]:
        """[W] f32 survivor mask for this round, or None when the
        schedule says nothing about it (round runs untouched)."""
        round_idx = int(round_idx)
        client_ids = np.asarray(client_ids)
        if round_idx in set(int(r) for r in self.drop_all):
            return np.zeros(client_ids.shape[0], np.float32)
        mask = None
        dropped = self.drop.get(round_idx)
        if dropped is not None:
            mask = (~np.isin(client_ids,
                             np.asarray(dropped))).astype(np.float32)
        slots = self.drop_slots.get(round_idx)
        if slots is not None:
            if mask is None:
                mask = np.ones(client_ids.shape[0], np.float32)
            mask[np.asarray(slots, np.int64)] = 0.0
        return mask

    def work_fractions(self, round_idx: int,
                       num_slots: int) -> Optional[np.ndarray]:
        """[W] f32 scripted work fractions for this round, or None
        when the schedule lists no straggler for it (round runs at
        full work). Fractions must lie in (0, 1] — the work-fraction
        domain the round engine is built for: zero work is NOT a
        straggler (ceil(0 * valid) would process nothing yet still
        scatter fresh error-feedback rows back), it is a dropped
        client — script it with drop/drop_slots, or give it a small
        fraction under Config.straggler_cutoff to take the degradation
        path."""
        spec = self.slow.get(int(round_idx))
        if spec is None:
            return None
        out = np.ones(num_slots, np.float32)
        for slot, frac in spec.items():
            frac = float(frac)
            if not 0.0 < frac <= 1.0:
                raise ValueError(
                    f"FaultSchedule.slow[{round_idx}][{slot}] = {frac} "
                    "is outside the (0, 1] work-fraction domain; for "
                    "zero work use drop/drop_slots (dropout), or a "
                    "small fraction below Config.straggler_cutoff")
            out[int(slot)] = frac
        return out

    def poison_mask_for(self, round_idx: int,
                        num_slots: int) -> Optional[np.ndarray]:
        """[W] f32 {0,1} scripted poison mask for this round, or None
        when the schedule poisons nobody in it. Slot-indexed like
        drop_slots (tests care about position, not identity — the
        drill scripts 'slot k of round r emits garbage')."""
        slots = self.poison.get(int(round_idx))
        if slots is None:
            return None
        out = np.zeros(num_slots, np.float32)
        out[np.asarray(slots, np.int64)] = 1.0
        return out

    def byzantine_mask_for(self, round_idx: int,
                           num_slots: int) -> Optional[np.ndarray]:
        """[W] f32 {0,1} scripted adversary mask for this round, or
        None when the schedule scripts no attacker in it. Slot-indexed
        like poison_mask_for (the drill scripts 'slot k of round r is
        the adversary')."""
        slots = self.byzantine.get(int(round_idx))
        if slots is None:
            return None
        out = np.zeros(num_slots, np.float32)
        out[np.asarray(slots, np.int64)] = 1.0
        return out

    def should_crash(self, round_idx: int) -> bool:
        return (self.crash_after is not None
                and int(round_idx) == int(self.crash_after))

    def should_crash_in_span(self, first_round: int,
                             n_rounds: int) -> bool:
        """True when crash_in_span lands inside [first_round,
        first_round + n_rounds): the span must die before any of its
        rounds commit (FedModel raises InjectedFault(first_round - 1),
        the last round that actually completed)."""
        return (self.crash_in_span is not None
                and int(first_round) <= int(self.crash_in_span)
                < int(first_round) + int(n_rounds))

    # ---------------- control-plane fault queries (ISSUE 12) -------------
    def should_crash_coordinator(self, round_idx: int) -> bool:
        """True when the coordinator dies broadcasting this round's
        plan (the transport raises InjectedFault(round_idx - 1))."""
        return (self.coordinator_crash_at is not None
                and int(round_idx) == int(self.coordinator_crash_at))

    def broadcast_dropped(self, round_idx: int, attempt: int) -> bool:
        """True when this round's broadcast SEND attempt is lost (only
        the first attempt drops; the retry goes through)."""
        return (attempt == 0 and int(round_idx)
                in set(int(r) for r in self.broadcast_drop))

    def broadcast_duplicated(self, round_idx: int) -> bool:
        return int(round_idx) in set(int(r) for r in self.broadcast_dup)

    def broadcast_slow_attempts(self, round_idx: int) -> int:
        """How many receive attempts for this round time out before
        the payload is visible (0 = delivered immediately)."""
        return int(self.broadcast_slow.get(int(round_idx), 0))

"""Run logging: stdout table, TSV, run dirs, wall-clock timing
(reference: CommEfficient/utils.py:14-99 Logger/TableLogger/TSVLogger/
Timer, make_logdir at :51-64; TensorBoard hookup is optional at the
driver level, cv_train.py:150-158)."""
from __future__ import annotations

import os
import time
from datetime import datetime

import numpy as np


class Logger:
    def _p(self, msg, args=None):
        print(msg.format(args) if args is not None else msg)
    debug = info = warn = error = critical = _p


class TableLogger:
    """Fixed-width column table on stdout; header from the first row.

    Schema-drift tolerant: rows may GAIN keys mid-run (new columns are
    appended and the header reprints once) or LOSE keys (the missing
    cell renders as '-') — a driver that adds a metric after round 1,
    or an epoch row that skips an optional field, no longer dies with
    a KeyError halfway through a multi-hour run."""

    _MISSING = object()

    def append(self, output: dict):
        fresh = [k for k in output if k not in getattr(self, "keys", ())]
        if not hasattr(self, "keys"):
            self.keys = list(fresh)
            print(*(f"{k:>12s}" for k in self.keys))
        elif fresh:
            self.keys.extend(fresh)
            print(*(f"{k:>12s}" for k in self.keys))
        row = []
        for k in self.keys:
            v = output.get(k, self._MISSING)
            if v is self._MISSING:
                row.append(f"{'-':>12}")
            elif isinstance(v, (float, np.floating)):
                row.append(f"{v:12.4f}")
            else:
                row.append(f"{v!s:>12}")
        print(*row)


class NullLogger:
    """Swallows rows — non-coordinator processes of a multi-controller
    run log nothing (the reference's workers likewise leave stdout to
    the rank-0 PS)."""

    def append(self, output: dict):
        pass


class TSVColumn:
    """One TSV column: header name, the row key it reads, a format
    spec, and a multiplicative scale applied before formatting."""

    def __init__(self, header: str, key: str, fmt: str = "{}",
                 scale: float = 1.0):
        self.header, self.key, self.fmt, self.scale = header, key, fmt, scale

    def render(self, row: dict) -> str:
        if self.key not in row:
            return ""  # schema-tolerant: a missing source key is blank
        v = row[self.key]
        if self.scale != 1.0 and isinstance(
                v, (int, float, np.integer, np.floating)):
            v = v * self.scale
        return self.fmt.format(v)


# the reference's hard-coded epoch/hours/top1Accuracy schema
# (CommEfficient/utils.py TSVLogger), now just the default column spec
LEGACY_TSV_COLUMNS = (
    TSVColumn("epoch", "epoch"),
    TSVColumn("hours", "total_time", "{:.8f}", 1.0 / 3600),
    TSVColumn("top1Accuracy", "test_acc", "{:.2f}", 100.0),
)


class TSVLogger:
    """Schema-driven TSV accumulator. The column spec is data (a
    sequence of TSVColumn), not code: pass your own columns to record
    any row schema; the default reproduces the reference's
    epoch,hours,top1Accuracy format byte for byte. Rows missing a
    column's source key render that cell blank instead of raising."""

    def __init__(self, columns=LEGACY_TSV_COLUMNS):
        self.columns = tuple(columns)
        self.log = [",".join(c.header for c in self.columns)]

    def append(self, output: dict):
        self.log.append(",".join(c.render(output) for c in self.columns))

    def __str__(self):
        return "\n".join(self.log)


class Timer:
    """Interval timer on the MONOTONIC clock: every read subtracts two
    stamps to form a duration, and a wall-clock (time.time) delta is
    not a duration — an NTP step mid-run would report negative or
    inflated epoch times (graftlint GL011)."""

    def __init__(self):
        self.times = [time.monotonic()]
        self.total_time = 0.0

    def __call__(self, include_in_total=True):
        self.times.append(time.monotonic())
        dt = self.times[-1] - self.times[-2]
        if include_in_total:
            self.total_time += dt
        return dt


def make_logdir(cfg) -> str:
    mode = cfg.mode
    sketch_str = (f"{mode}: {cfg.num_rows} x {cfg.num_cols}"
                  if mode == "sketch" else f"{mode}")
    k_str = (f"k: {cfg.k}"
             if mode in ("sketch", "true_topk", "local_topk") else "")
    clients_str = f"{cfg.num_workers}/{cfg.num_clients}"
    now = datetime.now().strftime("%b%d_%H-%M-%S")
    return os.path.join(
        "runs", f"{now}_{clients_str}_{sketch_str}_{k_str}")

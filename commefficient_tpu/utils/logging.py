"""Run logging: stdout table, TSV, run dirs, wall-clock timing
(reference: CommEfficient/utils.py:14-99 Logger/TableLogger/TSVLogger/
Timer, make_logdir at :51-64; TensorBoard hookup is optional at the
driver level, cv_train.py:150-158)."""
from __future__ import annotations

import os
import time
from datetime import datetime

import numpy as np


class Logger:
    def _p(self, msg, args=None):
        print(msg.format(args) if args is not None else msg)
    debug = info = warn = error = critical = _p


class TableLogger:
    """Fixed-width column table on stdout; header from the first row."""

    def append(self, output: dict):
        if not hasattr(self, "keys"):
            self.keys = list(output.keys())
            print(*(f"{k:>12s}" for k in self.keys))
        row = []
        for k in self.keys:
            v = output[k]
            if isinstance(v, (float, np.floating)):
                row.append(f"{v:12.4f}")
            else:
                row.append(f"{v!s:>12}")
        print(*row)


class NullLogger:
    """Swallows rows — non-coordinator processes of a multi-controller
    run log nothing (the reference's workers likewise leave stdout to
    the rank-0 PS)."""

    def append(self, output: dict):
        pass


class TSVLogger:
    def __init__(self):
        self.log = ["epoch,hours,top1Accuracy"]

    def append(self, output: dict):
        self.log.append("{},{:.8f},{:.2f}".format(
            output["epoch"], output["total_time"] / 3600,
            output["test_acc"] * 100))

    def __str__(self):
        return "\n".join(self.log)


class Timer:
    def __init__(self):
        self.times = [time.time()]
        self.total_time = 0.0

    def __call__(self, include_in_total=True):
        self.times.append(time.time())
        dt = self.times[-1] - self.times[-2]
        if include_in_total:
            self.total_time += dt
        return dt


def make_logdir(cfg) -> str:
    mode = cfg.mode
    sketch_str = (f"{mode}: {cfg.num_rows} x {cfg.num_cols}"
                  if mode == "sketch" else f"{mode}")
    k_str = (f"k: {cfg.k}"
             if mode in ("sketch", "true_topk", "local_topk") else "")
    clients_str = f"{cfg.num_workers}/{cfg.num_clients}"
    now = datetime.now().strftime("%b%d_%H-%M-%S")
    return os.path.join(
        "runs", f"{now}_{clients_str}_{sketch_str}_{k_str}")

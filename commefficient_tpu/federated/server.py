"""Server-side aggregation: the five update/decompression algorithms.

Pure-functional re-design of the reference's `get_server_update` +
`_server_helper_*` family (reference: CommEfficient/fed_aggregator.py:
469-613). The reference's helper signature
`(gradient, Vvelocity, Verror, args, lr) -> (update, Vvelocity, Verror)`
was already functional; we keep it, add an explicit PRNG key (server-side
DP noise), and return an explicit `velocity_mask` so that momentum
factor masking of *client* velocities (true_topk) is data flow instead
of a global-variable side channel. (The reference's version of that is
broken: `g_participating_clients` is assigned as a local and never set
globally — SURVEY.md §7.4 D6 — so we fix rather than replicate.)

All helpers run under jit; branch-free masking replaces the reference's
`tensor[nz] = 0` in-place scatter idiom.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from commefficient_tpu.config import Config
from commefficient_tpu.ops.flat import dp_noise, masked_topk
from commefficient_tpu.ops.sketch import CSVec


class ServerUpdate(NamedTuple):
    """Result of one server aggregation step.

    update:        dense [D] weight update; PS applies w -= update.
    Vvelocity:     new server (virtual) momentum state.
    Verror:        new server (virtual) error-feedback state.
    velocity_mask: [D] multiplicative mask (0 at freshly-transmitted
                   coordinates) to apply to participating clients'
                   local velocities — momentum factor masking for
                   true_topk (reference intent at
                   fed_aggregator.py:525-533). None when inapplicable.
    """
    update: jax.Array
    Vvelocity: jax.Array
    Verror: jax.Array
    velocity_mask: Optional[jax.Array]


def args2sketch(cfg: Config) -> CSVec:
    """Sketch geometry from config (reference fed_aggregator.py:464-467).
    Carries Config.kernel_backend so the sketch's dense hot-path ops
    (encode / estimate / threshold decode) run on the fused Pallas
    kernels when selected — the hash tables themselves are identical
    either way."""
    return CSVec(d=cfg.grad_size, c=cfg.num_cols, r=cfg.num_rows,
                 num_blocks=cfg.num_blocks, seed=42,
                 backend=cfg.kernel_backend)


def get_server_update(gradient: jax.Array, Vvelocity: jax.Array,
                      Verror: jax.Array, cfg: Config, lr,
                      key: Optional[jax.Array] = None,
                      alive: Optional[jax.Array] = None) -> ServerUpdate:
    """Dispatch on cfg.mode (reference fed_aggregator.py:469-481).
    `lr` may be a scalar or a per-parameter [D] vector (param-group
    LRs for Fixup nets, reference fed_aggregator.py:411-427).

    `alive`: optional traced boolean — False means NO client survived
    the round (client dropout, round.RoundBatch.survivors). The
    helper still runs (jit has no cheap dynamic skip), but its result
    is gated to a no-op: zero weight update and Vvelocity/Verror
    passed through bit-exactly. Without the gate a zero gradient
    would still decay momentum (rho * V) and fold V into the error
    accumulator — state drift from a round in which no information
    arrived."""
    # dispatch through the mode's Compressor plugin (ISSUE 19); the
    # five classic plugins delegate straight back to the helpers below
    from commefficient_tpu import compress
    upd = compress.get_compressor(cfg.mode).decode(
        cfg, gradient, Vvelocity, Verror, lr, key)
    if alive is None:
        return upd
    return ServerUpdate(
        update=jnp.where(alive, upd.update, jnp.zeros_like(upd.update)),
        Vvelocity=jnp.where(alive, upd.Vvelocity, Vvelocity),
        Verror=jnp.where(alive, upd.Verror, Verror),
        # a dead round transmits nothing, so no client velocity
        # coordinate may be factor-masked either
        velocity_mask=(None if upd.velocity_mask is None
                       else jnp.where(alive, upd.velocity_mask,
                                      jnp.ones_like(upd.velocity_mask))),
    )


def _fedavg(avg_update, Vvelocity, Verror, cfg: Config, lr, key) -> ServerUpdate:
    # (reference fed_aggregator.py:483-495) — lr is forced to 1 by the
    # optimizer for fedavg; clients already applied the real LR locally.
    rho = cfg.virtual_momentum
    Vvelocity = avg_update + rho * Vvelocity
    return ServerUpdate(Vvelocity, Vvelocity, Verror, None)


def _uncompressed(gradient, Vvelocity, Verror, cfg: Config, lr, key) -> ServerUpdate:
    # (reference fed_aggregator.py:497-509)
    rho = cfg.virtual_momentum
    Vvelocity = gradient + rho * Vvelocity
    grad = Vvelocity
    if cfg.do_dp and cfg.dp_mode == "server":
        grad = grad + dp_noise(key, grad.shape, cfg.noise_multiplier)
    return ServerUpdate(grad * lr, Vvelocity, Verror, None)


def _true_topk(gradient, Vvelocity, Verror, cfg: Config, lr, key) -> ServerUpdate:
    # (reference fed_aggregator.py:511-542)
    rho = cfg.virtual_momentum
    Vvelocity = gradient + rho * Vvelocity
    Verror = Verror + Vvelocity

    update = masked_topk(Verror, k=cfg.k)
    not_sent = (update == 0).astype(Verror.dtype)

    # error feedback + momentum factor masking at transmitted coords
    Verror = Verror * not_sent
    Vvelocity = Vvelocity * not_sent

    # clients' local velocities are masked at the same coords; the
    # round engine applies this to participating rows only.
    vel_mask = not_sent if cfg.local_momentum > 0 else None
    return ServerUpdate(update * lr, Vvelocity, Verror, vel_mask)


def _local_topk(local_topk_grad, Vvelocity, Verror, cfg: Config, lr, key) -> ServerUpdate:
    # (reference fed_aggregator.py:544-566): virtual momentum over the
    # *already sparsified* summed gradient; no virtual error possible.
    rho = cfg.virtual_momentum
    Vvelocity = local_topk_grad + rho * Vvelocity
    return ServerUpdate(Vvelocity * lr, Vvelocity, Verror, None)


def _sketched(sketched_grad, Vvelocity, Verror, cfg: Config, lr, key) -> ServerUpdate:
    # (reference fed_aggregator.py:568-613). State lives in sketch
    # space: Vvelocity/Verror are [r, c] tables; linearity makes
    # momentum/error accumulation in table space exact.
    rho = cfg.virtual_momentum
    sketch = args2sketch(cfg)

    # error_type is "virtual" or "none" here: Config.validate()
    # rejects sketch+local outright, as the reference's own workers do
    # (fed_worker.py:221-222 asserts it away — the server-side alias at
    # fed_aggregator.py:579-580 is unreachable there too, so there is
    # no local-error branch to carry).
    Vvelocity = sketched_grad + rho * Vvelocity
    if cfg.error_type == "virtual":
        Verror = Verror + Vvelocity
        decode_table = Verror
    else:  # "none": decode straight from the momentum table.
        # (the reference would unsketch an all-zero Verror here and
        # silently produce a zero update — drift note D-class, not
        # replicated)
        decode_table = Vvelocity

    if sketch._threshold_decode:
        # large-d route: sampled-threshold heavy-hitter recovery (one
        # mask, no big sort/gather/scatter — ops/sketch.py docs) and a
        # contiguous dense re-encode
        update = sketch.decode_topk_dense(decode_table, k=cfg.k)
        sketched_update = sketch.encode(update)
    else:
        idx, vals = sketch.decode_topk_sparse(decode_table, k=cfg.k)
        update = jnp.zeros(cfg.grad_size, jnp.float32).at[idx].set(
            vals, mode="drop")
        # encode_k_sparse picks the faster of the scatter-add /
        # dense-rotation routes per geometry and backend (CSVec owns
        # that heuristic)
        sketched_update = sketch.encode_k_sparse(idx, vals, dense=update)

    # virtual error feedback: re-sketch the k-sparse update and zero
    # the error/momentum tables wherever the re-sketch landed
    # (reference fed_aggregator.py:593-611; note the reference
    # deliberately zeroes rather than subtracts — subtracting diverges
    # per its own comment at :596-599).
    not_sent = (sketched_update == 0).astype(Vvelocity.dtype)
    if cfg.error_type == "virtual":
        Verror = Verror * not_sent
    Vvelocity = Vvelocity * not_sent

    return ServerUpdate(update * lr, Vvelocity, Verror, None)

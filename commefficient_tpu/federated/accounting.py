"""Per-client communication accounting — the headline observability
feature of the system (SURVEY.md §5; reference:
CommEfficient/fed_aggregator.py:170-299).

Semantics parity:
  * upload bytes per participating client per round: 4 bytes x
    mode-dependent float count (reference :291-299) — grad_size for
    uncompressed/true_topk/fedavg, k for local_topk, r*c for sketch.
    One deliberate divergence (ISSUE 6): a sketch table quantized for
    the wire (--sketch_table_dtype bf16/int8) is billed at the WIRE
    element size (Config.upload_bytes), not at f32 — the reference
    has no quantized transport to bill.
    The local_topk count stays the ANALYTIC k, exactly like the
    reference's; above ops/flat.py's TOPK_THRESHOLD_MIN_D the actual
    transmitted support is k within ~1% sampling noise — PLUS any
    threshold-tie widening (sampled_threshold_mask keeps every
    coordinate tied at the threshold, so a tie-heavy vector can
    transmit far more than k). The analytic number remains the billed
    one, but CommAccountant records the REALIZED nonzero count of each
    round's aggregate update next to it (realized_nonzeros /
    max_realized_nonzeros) so a blowout is visible rather than
    silently under-billed (download bytes are unaffected — they count
    actual changed weights via the bitset).
  * download bytes per participating client: 4 bytes x number of
    weights that changed since that client last participated
    (reference :239-289), with the same cheap path (single
    updated-since-init boolean when num_epochs <= 1 and whole-dataset
    batches, :171-177) and bounded-staleness clamp (deque maxlen =
    10/participation, :179-194 — under-counts clients stale for longer,
    with probability < e^-10 as the reference's comment derives).

TPU-first re-design of the expensive path: the reference keeps a deque
of FULL weight vectors (maxlen x D floats — 28 MB x maxlen for
ResNet9) and diffs against each participant's snapshot every round,
O(maxlen x D) host work. The information actually needed is only
*which coordinates changed each round*, and for the compressed modes
that set is k-sparse. So the device packs the round's change mask into
a D/32-word bitset (one small transfer), and the host keeps a deque of
bitsets (875 KB each for 7M params): a client stale for s rounds costs
one OR-reduction over s bitsets + popcount — exactly the
"disagrees with the client's snapshot" count, modulo coordinates that
changed and changed back to the identical float (which the reference
counts as unchanged; measure-zero in practice).
"""
from __future__ import annotations

from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import Config

DEQUE_MAXLEN_MULT = 10  # (reference fed_aggregator.py:21)

_POPCOUNT_TABLE = np.array([bin(i).count("1") for i in range(256)],
                           dtype=np.uint32)

try:  # fused C kernels (commefficient_tpu/native/accounting.c)
    from commefficient_tpu.native import native_accounting as _native
except ImportError:
    _native = None


def pack_change_bits(update: jax.Array) -> jax.Array:
    """Device-side: pack (update != 0) into uint32 words. Runs under
    jit; the host transfer is D/32 words instead of D floats.

    The packing arithmetic is f32: a dot of 16 {0,1} bits with
    [1, 2, ..., 2^15] is exact in f32 (sum < 2^16 < 2^24), and TPU
    multiplies/reduces floats natively while 32-bit integer
    multiply-accumulate is emulated scalar code (measured ~75 ms/round
    at D=6.6M for the all-uint32 formulation — it dominated the whole
    federated round; see PERF.md). One emulated shift+or per WORD
    (D/32 elements) remains."""
    d = update.shape[0]
    n_words = -(-d // 32)
    bits = jnp.not_equal(update, 0.0)
    bits = jnp.pad(bits, (0, n_words * 32 - d))
    halves = bits.reshape(n_words, 2, 16).astype(jnp.float32)
    w16 = jnp.asarray(2.0, jnp.float32) ** jnp.arange(16)
    packed = halves @ w16                                 # [n_words, 2]
    lo = packed[:, 0].astype(jnp.uint32)
    hi = packed[:, 1].astype(jnp.uint32)
    return lo | (hi << jnp.uint32(16))


def _popcount(words: np.ndarray) -> int:
    if _native is not None:
        return int(_native.popcount_words(
            np.ascontiguousarray(words).data))
    return int(_POPCOUNT_TABLE[words.view(np.uint8)].sum())


def _prefix_or_popcounts(changes, depths, n_words: int) -> dict:
    """{s: popcount(OR of the last s change bitsets)} for each needed
    staleness s in `depths`. The OR prefix must walk every depth up to
    max(depths) either way; the C fast path fuses OR+popcount in one
    64-bit pass per depth, while the numpy fallback popcounts ONLY at
    the requested depths (each popcount materializes a byte-table
    temporary, so popcounting every depth would dominate)."""
    depths = sorted(set(int(d) for d in depths))
    if not depths:
        return {}
    max_depth = depths[-1]
    if _native is not None and max_depth > 0:
        # zero-copy: each deque entry's buffer is consumed directly
        rows = [np.ascontiguousarray(np.asarray(c), np.uint32).data
                for c in changes]
        counts = _native.prefix_or_popcounts(rows, n_words, max_depth)
        return {d: counts[d] for d in depths}
    out = {}
    if depths[0] == 0:
        out[0] = 0
    acc = np.zeros(n_words, np.uint32)
    need = set(depths)
    for d in range(1, max_depth + 1):
        acc |= changes[-d]
        if d in need:
            out[d] = int(_POPCOUNT_TABLE[acc.view(np.uint8)].sum())
    return out


class CommAccountant:
    def __init__(self, cfg: Config, num_clients: int,
                 frozen_count: int = 0):
        self.cfg = cfg
        self.num_clients = num_clients
        self.n_words = -(-cfg.grad_size // 32)
        # finetune-frozen coordinates transmit nothing in the dense-
        # upload modes (the reference's requires_grad=False params are
        # not in the flat vector at all); sketch tables and the top-k
        # budget keep their full size regardless
        self.upload_floats = cfg.upload_floats
        if frozen_count and cfg.mode in ("uncompressed", "true_topk",
                                         "fedavg"):
            self.upload_floats = cfg.grad_size - frozen_count
        # billed upload BYTES at the wire dtype (ISSUE 6 accounting
        # fix): a bf16/int8 sketch table must not be charged at f32
        # element size. Config.upload_bytes is the mode's Compressor
        # plugin answering at its realized wire dtype (ISSUE 19);
        # the frozen-count adjustment above overrides it for the
        # dense modes whose payload genuinely shrinks (those all
        # transmit f32, so bytes stay 4 x floats exactly as before).
        # These are the `up_bytes` the journal records (api.py ->
        # telemetry).
        self.upload_bytes = (4.0 * self.upload_floats
                             if frozen_count
                             and cfg.mode in ("uncompressed",
                                              "true_topk", "fedavg")
                             else float(cfg.upload_bytes))
        # local_topk blowout observability (module docstring: the
        # upload charge stays the ANALYTIC k): ops/flat.py's
        # sampled_threshold_mask can select MORE than k on threshold
        # ties, and above TOPK_THRESHOLD_MIN_D the count also carries
        # ~1% sampling noise. record_round therefore keeps the
        # REALIZED nonzero count of the round's aggregate update
        # (popcount of its change bitset, one lag behind like the
        # download math) next to the analytic per-client k, so a tie
        # blowout is visible — compare realized_nonzeros against
        # (surviving uploaders x k): the union of W k-sparse uploads
        # is at most W*k except when ties widen a client's support.
        self.realized_nonzeros: Optional[int] = None
        self.max_realized_nonzeros = 0
        # cheap path applies when every client re-downloads everything
        # changed since init (reference fed_aggregator.py:171-177)
        self.cheap = (cfg.num_epochs <= 1 and cfg.local_batch_size == -1)
        if self.cheap:
            self.updated_since_init = np.zeros(self.n_words, np.uint32)
        else:
            # expected gap between a client's COMPLETED rounds is
            # 1 / (sampling rate * survival rate): client dropout
            # lengthens absences, and an overflowed window would make
            # the stale clip below silently undercharge the
            # accumulated download a returning client owes
            participation = (cfg.num_workers / num_clients
                             * (1.0 - cfg.client_dropout))
            maxlen = int(DEQUE_MAXLEN_MULT / participation)
            self.changes: deque = deque([], maxlen=maxlen)
            # SPARSE staleness (ISSUE 9): a dense [num_clients] int64
            # vector made accountant state O(population). Staleness of
            # client c is `rounds_seen - last reset`, where the reset
            # round is stored only for clients that have ever
            # participated (never-seen clients default to reset 0 =
            # stale since the beginning, exactly the dense vector's
            # semantics) — O(clients-ever-seen) state and checkpoint.
            self.rounds_seen = 0
            self._last_reset: dict = {}

    def _check_ids(self, participating: np.ndarray) -> None:
        """The dense stale vector this storage replaced bounds-checked
        ids implicitly via fancy indexing; the sparse map must do it
        explicitly or a caller bug books phantom clients that ride
        into every checkpoint (same guard as the tracker's
        _rows_for)."""
        if participating.size and (
                int(participating.min()) < 0
                or int(participating.max()) >= self.num_clients):
            raise ValueError(
                f"client id out of range for a {self.num_clients}-"
                f"client population: {participating}")

    def staleness(self, client_ids) -> np.ndarray:
        """Rounds since each client's last COMPLETED round (unclipped;
        the download math clips to the change-window length). Exposed
        because the dense `stale` vector is gone — staleness is now
        derived from the sparse reset map."""
        ids = np.asarray(client_ids, np.int64).reshape(-1)
        return np.array([self.rounds_seen - self._last_reset.get(int(c), 0)
                         for c in ids], np.int64)

    def record_round(self, participating: np.ndarray,
                     prev_changed_words: Optional[np.ndarray],
                     survivors: Optional[np.ndarray] = None):
        """Account one round. `prev_changed_words` is the packed change
        bitset of the PREVIOUS round's weight update (None on the first
        round — weights haven't changed since clients were initialized,
        so round 1 downloads are free, matching reference :258-261).

        `survivors`: optional [W] {0,1} mask aligned with
        `participating` (client dropout). A dropped client completed
        neither its download nor its upload, so it is charged NOTHING
        and its staleness keeps growing — it will pay the accumulated
        download the next round it actually finishes.

        ISSUE 17 narrows what the caller passes here: under value
        screening the mask is the ADMITTED set (screened == dropped ==
        not billed), and under a robust aggregator it is the
        CONTRIBUTOR set — a client every one of whose cells was
        trimmed out of the order statistics contributed nothing to the
        aggregate and is not billed upload bytes either. The mask
        producer changed; this method's contract did not.

        Returns (download_bytes, upload_bytes), each [W] COHORT-indexed
        — aligned slot-for-slot with `participating`, dropped slots
        charged 0.0. (Before ISSUE 9 these were [num_clients] vectors:
        two population-length allocations per round, the exact
        O(population) host cost the refactor removes. Every consumer
        only ever indexed participants or summed.)
        """
        participating = np.asarray(participating).reshape(-1)
        self._check_ids(participating)
        W = participating.shape[0]
        alive = (np.ones(W, bool) if survivors is None
                 else np.asarray(survivors).reshape(-1) > 0)
        completed = participating[alive]
        download = np.zeros(W)

        if self.cheap:
            if prev_changed_words is not None:
                self.updated_since_init |= np.asarray(prev_changed_words)
            download[alive] = 4.0 * _popcount(self.updated_since_init)
        else:
            if prev_changed_words is not None:
                self.changes.append(np.asarray(prev_changed_words))
            if len(self.changes) and len(completed):
                stale = np.clip(self.staleness(completed), 0,
                                len(self.changes))
                # staleness values share one OR-reduction prefix walk
                counts = _prefix_or_popcounts(
                    self.changes, np.unique(stale), self.n_words)
                download[alive] = [4.0 * counts[int(s)] for s in stale]
            for c in completed:
                self._last_reset[int(c)] = self.rounds_seen
            self.rounds_seen += 1

        upload = np.zeros(W)
        upload[alive] = self.upload_bytes

        if self.cfg.mode == "local_topk" and prev_changed_words is not None:
            # realized support of the previous round's aggregate
            # update, recorded next to the analytic k (__init__ note)
            self.realized_nonzeros = _popcount(
                np.asarray(prev_changed_words))
            self.max_realized_nonzeros = max(self.max_realized_nonzeros,
                                             self.realized_nonzeros)
        return download, upload

    def advance_round(self, participating: np.ndarray,
                      prev_changed_words: Optional[np.ndarray],
                      survivors: Optional[np.ndarray] = None) -> None:
        """Advance the accountant's state for a round whose byte totals
        the caller doesn't want (FedModel.run_rounds(account=False)):
        the change deque and staleness bookkeeping move exactly as in
        record_round (dropped clients' staleness included), only the
        popcount work is skipped. Without this, the first accounted
        round after an unaccounted span would misattribute download
        bytes."""
        participating = np.asarray(participating).reshape(-1)
        self._check_ids(participating)
        if survivors is not None:
            participating = participating[
                np.asarray(survivors).reshape(-1) > 0]
        if self.cheap:
            if prev_changed_words is not None:
                self.updated_since_init |= np.asarray(prev_changed_words)
        else:
            if prev_changed_words is not None:
                self.changes.append(np.asarray(prev_changed_words))
            for c in participating:
                self._last_reset[int(c)] = self.rounds_seen
            self.rounds_seen += 1

    # -- checkpoint round-trip (utils.checkpoint serializes this so
    #    resumed runs keep cumulative comm totals correct) -------------
    def state_dict(self) -> dict:
        state = {}
        if self.cheap:
            state["updated_since_init"] = self.updated_since_init.copy()
        else:
            # sparse staleness (ISSUE 9): O(clients-ever-seen) arrays,
            # not the dense [num_clients] vector — checkpoints stay
            # O(cohort) at million-client populations
            ids = np.array(sorted(self._last_reset), np.int64)
            state["stale_rounds"] = np.int64(self.rounds_seen)
            state["stale_ids"] = ids
            state["stale_at"] = np.array(
                [self._last_reset[int(c)] for c in ids], np.int64)
            state["changes"] = (np.stack(list(self.changes))
                                if len(self.changes)
                                else np.zeros((0, self.n_words), np.uint32))
        return state

    def load_state_dict(self, state: dict) -> None:
        if self.cheap:
            self.updated_since_init = np.asarray(
                state["updated_since_init"], np.uint32)
        else:
            if "stale_ids" in state:
                self.rounds_seen = int(np.asarray(state["stale_rounds"]))
                ids = np.asarray(state["stale_ids"], np.int64)
                at = np.asarray(state["stale_at"], np.int64)
                self._last_reset = {int(c): int(a)
                                    for c, a in zip(ids, at)}
            else:
                # legacy dense vector: recover an equivalent sparse
                # map. Absolute round counts beyond the change-window
                # clip never matter, so anchoring rounds_seen at the
                # vector's max staleness preserves every observable
                # charge (never-seen clients sat AT the max).
                stale = np.asarray(state["stale"], np.int64)
                self.rounds_seen = int(stale.max()) if stale.size else 0
                self._last_reset = {
                    int(c): int(self.rounds_seen - s)
                    for c, s in enumerate(stale)
                    if int(s) != self.rounds_seen}
            rows = np.asarray(state["changes"], np.uint32)
            if self.changes.maxlen is not None and \
                    len(rows) > self.changes.maxlen:
                # the checkpoint was written under a config with a
                # wider window (e.g. a higher client_dropout, which
                # isn't — deliberately — in the fingerprint): grow to
                # fit rather than silently dropping the oldest rows,
                # which would undercharge returning clients' downloads
                self.changes = deque([], maxlen=len(rows))
            self.changes.clear()
            for row in rows:
                self.changes.append(row)

"""Buffered async aggregation: staleness-weighted late admission of
straggler contributions (ISSUE 10).

The synchronous straggler contract (PR 2) truncates a slow client at
the round deadline: a work fraction f means only its first ceil(f *
valid) examples ever count, and the rest of its local work is simply
lost. FetchSGD's error-feedback formulation makes a better option
safe — the sketch/update merge is LINEAR, so a late contribution is
just another summand of a later round's aggregate ("Federated
Learning: Strategies for Improving Communication Efficiency" frames
the staleness/throughput trade-off; PAPERS.md).

`AsyncAdmitBuffer` mechanizes that entirely on the host side of the
dispatch, as a merge on the cohort operands the three traced round
programs already take — ZERO new device programs:

  * DEFER: a sampled slot with work fraction < 1 (random straggler
    draw, FaultSchedule.slow, or a deadline truncation — anything
    that survived the straggler cutoff) is pulled out of its round:
    its survivor bit is zeroed (bit-exactly the dropped-client path —
    no upload, state rows written back untouched, accounting charges
    nothing) and its (client id, batch rows, work fraction) is
    buffered with due round t + k;
  * ADMIT: at round t+k the buffered entry is merged back into the
    cohort operands — preferring its original slot if that slot is
    idle, else any idle slot, else displacing the highest-index fresh
    slot — with work fraction f * decay**rounds_late. The FedNova-
    style processed-example reweighting the work operand already
    implements (round.py shard_train) turns the discounted fraction
    into a staleness-discounted aggregation weight: the late client's
    transmit is scaled by (and the divide-by-total counts) the
    discounted example budget.

At k=0 the defer and the admit land in the SAME compose() call and
the entry returns to its own slot with weight f * decay**0 == f in
f32 — the composed operands are bit-identical to the synchronous
straggler path (tests/test_pipeline.py asserts this), which is the
identity anchoring the whole mechanism.

Crash safety: pending entries ride in checkpoints (`asyb_*` keys,
utils/checkpoint) so a resumed run admits exactly what the
uninterrupted run would have; the buffer is bounded by construction
(at most num_workers entries per in-flight round, k rounds deep).
Single-controller only (Config.validate): entries hold batch rows,
which are process-local under multihost feeding.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import numpy as np


class _PendingEntry(NamedTuple):
    client_id: int
    origin: int                 # round the work was drawn for
    due: int                    # origin + delay
    frac: np.float32            # work completed by its own deadline
    slot: int                   # cohort slot it occupied at origin
    data: Tuple[np.ndarray, ...]  # that slot's batch rows
    mask: np.ndarray


class AsyncAdmitBuffer:
    """Host-side defer/admit buffer for one run. `compose` is called
    once per round (both dispatch paths) AFTER the fault/cutoff pass
    (api._faults_for_round) and BEFORE operand placement, in round
    order — the same discipline the fault draws follow, so the
    composed stream is a pure function of (stream, faults, buffer
    state) and crash->resume replays it bit-exactly once the buffer
    state is restored."""

    def __init__(self, delay: int, decay: float = 0.5):
        if delay < 0:
            raise ValueError(f"delay={delay} must be >= 0")
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay={decay} must be in (0, 1]")
        self.delay = int(delay)
        self.decay = float(decay)
        self._pending: List[_PendingEntry] = []
        # the admit merges applied by the LAST compose() call, as
        # (slot, client_id, effective_work_fraction, origin_round)
        # tuples — the plan-carried form of the admission stream
        # (ISSUE 12): FedModel folds these into the round's install
        # digest so every controller proves it merged the identical
        # late contributions, and a deterministic restart can verify
        # its replayed admissions against the write-ahead journal.
        self.last_admits: List[Tuple[int, int, float, int]] = []

    # ---------------- the math -------------------------------------------
    def staleness_weight(self, rounds_late: int) -> np.float32:
        """decay**rounds_late as f32 — the discount multiplied into an
        admitted entry's work fraction. rounds_late == 0 yields exactly
        f32(1.0), so same-round admission is weight-exact."""
        if rounds_late < 0:
            raise ValueError(f"rounds_late={rounds_late} must be >= 0")
        return np.float32(self.decay ** int(rounds_late))

    @property
    def pending_count(self) -> int:
        return len(self._pending)

    # ---------------- per-round composition ------------------------------
    def compose(self, round_idx: int, client_ids, data, mask,
                survivors: Optional[np.ndarray],
                work: Optional[np.ndarray]):
        """Defer this round's stragglers, admit entries due this
        round. Returns (client_ids, data, mask, survivors, work) with
        the same None-collapse conventions api._faults_for_round
        established (all-ones work -> None; work present forces a
        survivor vector). Inputs are returned UNTOUCHED — same array
        objects — when the buffer neither defers nor admits, so the
        async-off-equivalent rounds stay on the exact operands (and
        therefore programs) a buffer-free build dispatches."""
        round_idx = int(round_idx)
        self.last_admits = []
        due = [e for e in self._pending if e.due <= round_idx]
        if work is None and not due:
            return client_ids, data, mask, survivors, work

        ids = np.asarray(client_ids)
        W = ids.shape[0]
        surv_arr = (np.ones(W, np.float32) if survivors is None
                    else np.asarray(survivors, np.float32).copy())
        work_arr = (np.ones(W, np.float32) if work is None
                    else np.asarray(work, np.float32).copy())
        changed = False

        # 1) defer: every live straggling slot leaves this round on
        # the dropped-client path and enters the buffer
        if work is not None:
            for i in np.flatnonzero((work_arr < 1.0)
                                    & (surv_arr > 0.0)):
                i = int(i)
                self._pending.append(_PendingEntry(
                    client_id=int(ids[i]), origin=round_idx,
                    due=round_idx + self.delay,
                    frac=np.float32(work_arr[i]), slot=i,
                    data=tuple(np.asarray(d)[i].copy() for d in data),
                    mask=np.asarray(mask)[i].copy()))
                surv_arr[i] = 0.0
                work_arr[i] = 1.0
                changed = True
            # the just-deferred entries are due NOW at delay == 0
            due = [e for e in self._pending if e.due <= round_idx]

        # 2) admit: merge due entries back into the cohort operands
        if due:
            self._pending = [e for e in self._pending
                             if e.due > round_idx]
            ids = np.array(ids, copy=True)
            data = tuple(np.array(d, copy=True) for d in data)
            mask = np.array(mask, copy=True)
            taken: set = set()
            for e in due:  # FIFO — deterministic slot assignment
                slot = self._pick_slot(e.slot, surv_arr, taken)
                taken.add(slot)
                ids[slot] = e.client_id
                for d, row in zip(data, e.data):
                    d[slot] = row
                mask[slot] = e.mask
                surv_arr[slot] = 1.0
                work_arr[slot] = e.frac * self.staleness_weight(
                    round_idx - e.origin)
                self.last_admits.append(
                    (slot, int(e.client_id), float(work_arr[slot]),
                     int(e.origin)))
            changed = True

        if not changed:
            return client_ids, data, mask, survivors, work
        # None-collapse, mirroring api._faults_for_round: an all-ones
        # work vector traces the work-free program, and a surviving
        # work vector always rides with a survivor operand
        out_work: Optional[np.ndarray] = work_arr
        if np.all(work_arr >= 1.0):
            out_work = None
        out_surv: Optional[np.ndarray] = surv_arr
        if (out_work is None and survivors is None
                and np.all(surv_arr >= 1.0)):
            out_surv = None
        return ids, data, mask, out_surv, out_work

    @staticmethod
    def _pick_slot(preferred: int, surv_arr: np.ndarray,
                   taken: set) -> int:
        """Deterministic admission slot: the entry's own origin slot
        when idle (the k=0 bit-identity path), else the lowest-index
        idle slot, else — a fully live round — displace from the END
        (the highest-index fresh slot not already claimed this round;
        the displaced draw simply doesn't run, like an over-
        provisioned surplus slot)."""
        if surv_arr[preferred] <= 0.0 and preferred not in taken:
            return preferred
        for i in range(len(surv_arr)):
            if surv_arr[i] <= 0.0 and i not in taken:
                return i
        for i in range(len(surv_arr) - 1, -1, -1):
            if i not in taken:
                return i
        raise ValueError("more admissions than cohort slots")

    # ---------------- checkpoint round-trip ------------------------------
    def state_dict(self) -> dict:
        """Pending entries as plain arrays (`asyb_*` checkpoint keys).
        Batch rows stack homogeneously — every entry came off the same
        loader geometry — so the payload is a handful of [m, ...]
        arrays, empty when nothing is pending."""
        m = len(self._pending)
        out = {
            "ids": np.array([e.client_id for e in self._pending],
                            np.int64),
            "origin": np.array([e.origin for e in self._pending],
                               np.int64),
            "due": np.array([e.due for e in self._pending], np.int64),
            "frac": np.array([e.frac for e in self._pending],
                             np.float32),
            "slot": np.array([e.slot for e in self._pending],
                             np.int64),
            "n_data": np.int64(len(self._pending[0].data) if m else 0),
        }
        if m:
            out["mask"] = np.stack([e.mask for e in self._pending])
            for j in range(int(out["n_data"])):
                out[f"data{j}"] = np.stack(
                    [e.data[j] for e in self._pending])
        return out

    def load_state_dict(self, state: dict) -> None:
        ids = np.asarray(state["ids"], np.int64)
        self._pending = []
        n_data = int(np.asarray(state.get("n_data", 0)))
        for i in range(ids.shape[0]):
            self._pending.append(_PendingEntry(
                client_id=int(ids[i]),
                origin=int(np.asarray(state["origin"])[i]),
                due=int(np.asarray(state["due"])[i]),
                frac=np.float32(np.asarray(state["frac"])[i]),
                slot=int(np.asarray(state["slot"])[i]),
                data=tuple(np.asarray(state[f"data{j}"])[i]
                           for j in range(n_data)),
                mask=np.asarray(state["mask"])[i]))

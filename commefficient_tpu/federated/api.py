"""Reference-shaped high-level API: FedModel + FedOptimizer.

Drop-in call-pattern parity with the reference driver loop (reference:
CommEfficient/cv_train.py:389-404, 193-229):

    model = FedModel(module, compute_loss_train, cfg, compute_loss_val)
    opt = FedOptimizer(model, cfg)
    scheduler = LambdaLR(opt, lr_lambda=...)
    ...
    scheduler.step()
    loss, acc, download, upload = model(batch)   # one federated round
    opt.step()
    ...
    model.finalize()

Under the hood there are no processes, queues, or shared memory
(reference FedModel.__init__ spawns workers and a NCCL group,
fed_aggregator.py:137-164): the entire round — client compute, psum,
server decompression, weight update, client-state scatter — is ONE
jitted program built by `federated.round.make_round_fns`, executed when
`model(batch)` is called. The learning rate the scheduler set *before*
the call is the one the fused round applies, which matches the
reference's ordering (lr_scheduler.step() precedes model(batch),
cv_train.py:198-229); `opt.step()` therefore only performs host-side
bookkeeping and exists for call-pattern parity.

The loss callback contract is preserved from the reference
(SURVEY.md §3.5) modulo functional style: the reference takes
compute_loss(model, batch, args) -> (loss, *metrics); here it is
loss_fn(params_pytree, batch_tuple, mask) -> (loss, (metrics...)) —
the mask is the price of static shapes.
"""
from __future__ import annotations

import time
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import Config
from commefficient_tpu.federated import round as fround
from commefficient_tpu.federated.accounting import (
    CommAccountant, pack_change_bits,
)
from commefficient_tpu.federated.async_agg import AsyncAdmitBuffer
from commefficient_tpu.ops.flat import flatten_params
from commefficient_tpu.parallel import multihost as mh
from commefficient_tpu.parallel.mesh import make_multihost_client_mesh
from commefficient_tpu.parallel.plantransport import (
    PlanDigestError, install_digest,
)
from commefficient_tpu.telemetry.clients import ClientThroughputTracker
from commefficient_tpu.telemetry.metrics import METRIC_INDEX
from commefficient_tpu.telemetry.trace import TRACE
from commefficient_tpu.utils.faults import (
    FaultSchedule, InjectedFault, bernoulli_survivors, byzantine_mask,
    poison_mask, straggler_work_fractions,
)
from commefficient_tpu.utils.retry import is_transient_error, with_retries


class _StagedRound(NamedTuple):
    """One round's host-prepared dispatch operands (FedModel.
    stage_round): the batch leaves already explicitly placed on the
    mesh, plus the host-side copies commit_staged's accounting and
    telemetry consume. Staging may run one round AHEAD of the commit
    (the pipelined prefetch) because nothing in it reads round
    state — fault draws are pure functions of (seed, round index)."""
    round_idx: int
    batch: "fround.RoundBatch"        # operands placed on the mesh
    lr: jax.Array
    client_ids: np.ndarray            # host copy, post-admission
    survivors: Optional[np.ndarray]   # host copy (accounting)
    # tiered client state (ISSUE 11, Config.state_tier=host): the
    # round's LRU slot assignment + spill/restore motion, decided at
    # stage time (pure host bookkeeping, deterministic in the cohort
    # stream) and executed against the device block at commit time.
    # None under the default device tier. When set, `batch.client_ids`
    # carries device SLOTS, not global ids — the gather/scatter
    # programs index the bounded working-set block.
    tier_plan: Optional[object] = None


class _SpanHandle(NamedTuple):
    """One dispatched-but-uncollected scanned span (FedModel.
    dispatch_rounds -> collect_rounds). `metrics`/`bits` are the span
    program's output futures; the host copies carry what the deferred
    accounting/telemetry commit needs. Collect in dispatch order."""
    first: int
    ids_host: np.ndarray              # [N, W], post-admission
    surv_all: Optional[np.ndarray]
    work_all: Optional[np.ndarray]
    crash_at: Optional[int]
    account: bool
    metrics: object                   # round.RoundMetrics (futures)
    bits: jax.Array                   # [N, D/32] change bitsets
    t_dispatch0: float
    t_dispatched: float
    # graftscope correlation (ISSUE 13): the scanned-span index at
    # dispatch — the same counter --profile_spans selects on, so the
    # device_execute trace span recorded at collect correlates with a
    # jax.profiler capture of the same span. -1 = unknown (callers
    # outside the scanloop).
    span_idx: int = -1


class FedModel:
    def __init__(self, module, loss_train, cfg: Config,
                 loss_val=None, params=None, mesh=None,
                 init_batch=None, num_clients: Optional[int] = None,
                 lr_scale_vec: Optional[np.ndarray] = None):
        """module: a Flax module (init/apply) OR None if `params` and
        loss callbacks close over the model themselves.
        loss_*: loss_fn(params, batch_tuple, mask) -> (loss, metrics).
        init_batch: example batch tuple for module.init.
        """
        self.module = module
        self.training = True
        if params is None:
            if module is None or init_batch is None:
                raise ValueError("need either params or module+init_batch")
            params = module.init(jax.random.PRNGKey(cfg.seed), *init_batch)
        self.params_template = params
        vec, self.unravel = flatten_params(params)
        cfg = cfg.replace(grad_size=int(vec.shape[0])).validate()
        self.cfg = cfg

        if mesh is None:
            # widest clients axis that divides num_workers (round_step
            # shards the participating clients evenly across the mesh),
            # after reserving the model_parallel factor: with mp > 1
            # the mesh carries a model axis (the engine replicates over
            # it unless the loss is tp-wrapped, parallel/tp.py — see
            # gpt2_train's TP branch for the wrapped path)
            mp = max(cfg.model_parallel, 1)
            if len(jax.devices()) < mp:
                raise ValueError(
                    f"model_parallel={mp} needs at least {mp} devices, "
                    f"have {len(jax.devices())}")
            n = min(len(jax.devices()) // mp, max(cfg.num_workers, 1))
            while cfg.num_workers % n:
                n -= 1
            # slice-major DCN layout: real multi-slice topology is
            # auto-detected; --num_slices > 1 emulates the grouping on
            # single-slice/CPU devices (and on real multi-slice
            # hardware must match the physical count); the flat
            # single-slice mesh is the default case of the same call.
            # The device subset is chosen slice-balanced: a flat
            # prefix of jax.devices() can land unevenly across slices
            # (4+2 of 2x4) and the hybrid mesh build would fail; when
            # no balanced pick exists, fall back to a flat mesh over
            # the prefix.
            from commefficient_tpu.parallel.mesh import (
                make_client_mesh, make_client_model_mesh,
                slice_balanced_prefix,
            )
            picked = slice_balanced_prefix(jax.devices(), n * mp)
            if picked is not None:
                mesh = make_multihost_client_mesh(
                    model_parallel=mp, devices=picked,
                    num_slices=cfg.num_slices if cfg.num_slices > 1
                    else None)
            elif mp == 1:
                mesh = make_client_mesh(n)
            else:
                mesh = make_client_model_mesh(
                    n, mp, devices=jax.devices()[:n * mp])
        self.mesh = mesh
        self.num_clients = cfg.resolved_num_clients(num_clients)

        self._loss_train = loss_train
        self._loss_val = loss_val if loss_val is not None else loss_train

        # frozen-coordinate gradient mask: exactly-zero lr scales mark
        # finetune-frozen leaves; zero their gradients at the source so
        # they consume no compression budget (reference freezing is
        # requires_grad=False, which removes them entirely)
        grad_mask = None
        if lr_scale_vec is not None and np.any(np.asarray(lr_scale_vec) == 0):
            grad_mask = (np.asarray(lr_scale_vec) != 0).astype(np.float32)

        self._train_round = fround.make_train_fn(
            self._loss_train, self.unravel, cfg, self.mesh,
            grad_mask=grad_mask)
        self._eval_batch = fround.make_eval_fn(
            self._loss_val, self.unravel, cfg, self.mesh)

        self.server = fround.init_server_state(cfg, vec, mesh=self.mesh)
        # tiered cold client state (ISSUE 11): under state_tier=host
        # the ClientState blocks hold only the LRU working set —
        # client_state_rows picks the allocation — and the store below
        # conducts slot assignment, host spill, and restore through
        # the SAME gather/scatter state-motion programs.
        self.clients = fround.init_client_state(
            cfg, fround.client_state_rows(cfg, self.num_clients), vec,
            mesh=self.mesh)
        self.state_store = None
        if cfg.state_tier != "device":
            from commefficient_tpu.federated.statestore import (
                TieredStateStore, tracked_fields,
            )
            if any(tracked_fields(cfg).values()):
                self.state_store = TieredStateStore(
                    cfg, self.mesh, self._train_round, vec,
                    self.num_clients)
        # O(cohort) checkpointing (ISSUE 9): client-state rows are zero
        # (or the init-weights tile, topk_down) until a client first
        # participates, so checkpoints persist only the rows of
        # clients-ever-sampled — this host-side id set tracks them.
        # The init-weights host copy reconstructs untouched topk_down
        # rows at resume. A resume from a LEGACY dense checkpoint loses
        # the touched set, so saves fall back to dense from there on
        # (_sparse_rows_ok).
        self._touched: set = set()
        self._sparse_rows_ok = True
        self._init_weights_host = (np.asarray(vec, np.float32)
                                   if cfg.do_topk_down else None)

        self.accountant = CommAccountant(
            cfg, self.num_clients,
            frozen_count=(0 if grad_mask is None
                          else int((grad_mask == 0).sum())))
        # differential privacy (ISSUE 19): the RDP accountant is
        # stateless — cumulative epsilon is a pure function of the
        # committed-round count, so a crash->resume re-derives the
        # identical curve from the restored round counter (no extra
        # checkpoint state, no drift).
        self.privacy = None
        if cfg.mode == "dp_sketch" and cfg.dp_noise_mult > 0:
            from commefficient_tpu.compress import RdpAccountant
            self.privacy = RdpAccountant(cfg.dp_noise_mult,
                                         cfg.dp_delta)
        self._prev_change_words: Optional[np.ndarray] = None
        self._pack_bits = jax.jit(pack_change_bits)
        from jax.sharding import PartitionSpec as P
        self._P = P
        # the PRNG key (like every jit operand) must be a GLOBAL array
        # in a multi-controller run; globalize is a plain device_put in
        # single-process ones
        self._key = mh.globalize(self.mesh, P(),
                                 jax.random.PRNGKey(cfg.seed))
        self._optimizer: Optional["FedOptimizer"] = None
        # per-parameter lr scale vector (Fixup param groups,
        # reference fed_aggregator.py:411-427); None -> scalar lr.
        # Held host-side: the product with the scheduler's lr is formed
        # on host and globalized per call.
        self.lr_scale_vec = (None if lr_scale_vec is None
                             else np.asarray(lr_scale_vec, np.float32))
        # global-feeding fallback for meshes where a process's devices
        # are NOT a contiguous block of the clients axis (multihost.
        # local_row_slice raises there): every process materializes the
        # identical GLOBAL batch and it is placed per-shard via
        # globalize's callback — correct for any device->process
        # layout, at the cost of host-materializing the full batch.
        self.feed_global = False
        # fault tolerance: host-side mirror of server.round_idx (kept
        # in lockstep so survivor draws and crash points never sync on
        # the device counter), plus an optional injected fault script
        # (utils/faults.FaultSchedule; set_fault_schedule)
        self._rounds_done = 0
        self.fault_schedule: Optional[FaultSchedule] = None
        # finite-frontier rollback (ISSUE 16): rounds below this index
        # dispatch with the admission screen FORCE-enabled — set by
        # force_screen_rounds after a numeric-trip rollback so the
        # replayed window screens the corruption out. 0 = no window.
        self._screen_force_until = 0
        # adaptive screening (ISSUE 17): one controller per run tunes
        # the norm-screen multiplier toward --target_screened_rate;
        # attach_scheduler shares it with the RoundScheduler so the
        # live value rides every sealed plan. _plan_screen_mult stashes
        # a consumed plan's stamped multiplier per round — a replayed
        # or broadcast plan's value WINS over the local controller's.
        self.screen_ctl = None
        if cfg.adaptive_screen:
            from commefficient_tpu.scheduler import (
                AdaptiveScreenController,
            )
            self.screen_ctl = AdaptiveScreenController(cfg)
        self._plan_screen_mult = {}
        # plan-riding controller bank (ISSUE 20, control/): None by
        # default — make_bank constructs one exactly when a bank
        # controller flag is set. attach_scheduler shares it so the
        # fresh coordinator path stamps every sealed plan through it;
        # _plan_controls stashes each consumed plan's `controls` dict
        # per round — the plan-carried values WIN over local state
        # (install) and the stashed staleness_decay is applied to the
        # async admission buffer at compose time, so the discount a
        # round executes with is exactly the digest-covered journaled
        # one.
        from commefficient_tpu.control import make_bank
        self.control_bank = make_bank(cfg)
        self._plan_controls = {}
        # observability (telemetry/): the throughput tracker always
        # exists (cheap arrays; its state rides in every checkpoint so
        # resume restores it even for runs that never journal), while
        # the session — journal + profiler + the host-side metric
        # conductor — is attached by the driver when cfg.telemetry is on
        self.throughput = ClientThroughputTracker(self.num_clients)
        self.telemetry = None
        # round scheduling (commefficient_tpu/scheduler): the drivers
        # attach a RoundScheduler whose selection-time plans this
        # model consumes at dispatch (attach_scheduler); None — or a
        # default uniform/no-deadline scheduler, which plans nothing —
        # leaves every code path bit-identical to a scheduler-free
        # build
        self.scheduler = None
        # the run's FedSampler (data/sampler.py), attached by
        # scheduler.attach_round_scheduler so its stream state rides
        # in checkpoints (smp_* keys) — the exact-data-stream resume
        # contract under non-uniform sampling
        self.data_sampler = None
        # per-round scheduled-slot masks (RoundPlan.active), stashed
        # at plan consumption and handed to the telemetry feeding so
        # idle over-provisioned pads are EXCLUDED from the throughput
        # tracker (they were never asked to work — counting them as
        # participations would depress the completion ratio the
        # scheduler's survival estimate reads)
        self._plan_active = {}
        # coordinator-broadcast control plane (ISSUE 12,
        # parallel/plantransport.py). plan_transport: the attached
        # PlanTransport (None = transport-free, every path
        # bit-identical to the pre-feature build). _plan_journal:
        # consumed plans' journal fields, stashed by _faults_for_round
        # and sealed WRITE-AHEAD by _seal_plan — the `schedule` event
        # (with its install digest) is journaled and flushed durable
        # BEFORE the round's dispatch, so a plan is never executed
        # before it is durable. _replay_digests: the write-ahead plan
        # stream of a pre-crash journal (load_plan_stream) — a
        # deterministic restart cross-checks every replayed round's
        # recomputed digest against it and fails loud on divergence.
        self.plan_transport = None
        self._plan_journal = {}
        self._replay_digests = {}
        self._wa_dirty = False
        # pipelined round engine (ISSUE 10): stage-side round counter
        # (runs ahead of _rounds_done when a prefetched round/span has
        # been staged but not yet committed; equal otherwise), the
        # buffered async-admission state (--async_admit_rounds), and
        # the off-critical-path checkpoint writer (--pipeline). All
        # three are None/identity in the default config, so the
        # default dispatch path is bit-identical to the pre-feature
        # synchronous loop.
        self._rounds_staged = 0
        self.async_admit = (
            AsyncAdmitBuffer(cfg.async_admit_rounds,
                             cfg.async_staleness_decay)
            if cfg.async_admit_rounds > 0 else None)
        if cfg.pipeline:
            # deferred import: utils.checkpoint imports federated.round
            # for its (Server|Client)State types, so a module-level
            # import here would be circular
            from commefficient_tpu.utils.checkpoint import (
                AsyncCheckpointWriter,
            )
            self.ckpt_writer = AsyncCheckpointWriter(
                drain_timeout=cfg.writer_drain_timeout_s)
        else:
            self.ckpt_writer = None

    def attach_telemetry(self, session) -> None:
        """Install a telemetry.TelemetrySession (or None to detach).
        The model feeds it per-round device metric vectors on the
        unscanned path (one-round lag — no added syncs) and whole
        host-materialized spans from run_rounds; a session without its
        own tracker is pointed at this model's `throughput`."""
        self.telemetry = session
        if session is not None and session.tracker is None:
            session.tracker = self.throughput

    def attach_scheduler(self, scheduler) -> None:
        """Install a scheduler.RoundScheduler (or None to detach). Its
        per-round plans — idle over-provisioned slots and deadline
        work fractions — compose into the fault operands in
        _faults_for_round; scheduler state rides in checkpoints under
        `sched_*` keys and load_state restores it."""
        self.scheduler = scheduler
        if scheduler is not None:
            # working-set-aware prefetch (ISSUE 11): the scheduler's
            # commit_round warms the HOST side of an upcoming plan's
            # cohort restores — LRU-neutral, so prefetch timing can
            # never perturb the eviction stream
            scheduler.state_prefetch = (
                self.state_store.prefetch_host_rows
                if self.state_store is not None else None)
            # adaptive screening (ISSUE 17): the scheduler stamps the
            # controller's live multiplier into every sealed plan (and
            # its is_default goes False, so plans exist to carry it)
            if self.screen_ctl is not None:
                scheduler.screen_ctl = self.screen_ctl
            # controller bank (ISSUE 20): same sharing contract — the
            # scheduler stamps fresh plans through the bank and its
            # is_default goes False so plans exist to carry the values
            if self.control_bank is not None:
                scheduler.control_bank = self.control_bank

    def scheduler_state(self) -> Optional[dict]:
        """The `sched_*` checkpoint payload: the attached scheduler's
        counter state_dict, or None without one — every checkpoint
        call site passes this, next to throughput.state_dict()."""
        return (self.scheduler.state_dict()
                if self.scheduler is not None else None)

    def attach_data_sampler(self, sampler) -> None:
        """Install the run's FedSampler (or None to detach). Its
        stream state — rng, mid-epoch cursor and permutations — rides
        in checkpoints under `smp_*` and load_state restores it, so a
        resumed run CONTINUES the exact data stream rather than
        replaying the epoch head (which, under non-uniform sampling,
        would re-draw against the checkpoint-time tracker and feed
        different data than the uninterrupted run)."""
        self.data_sampler = sampler

    def sampler_state(self) -> Optional[dict]:
        """The `smp_*` checkpoint payload: the attached FedSampler's
        stream state_dict, or None without one."""
        return (self.data_sampler.state_dict()
                if self.data_sampler is not None else None)

    def async_admit_state(self) -> Optional[dict]:
        """The `asyb_*` checkpoint payload: pending async-admission
        entries (federated/async_agg), or None when buffered async
        aggregation is off — every checkpoint call site passes this
        next to sampler_state()."""
        return (self.async_admit.state_dict()
                if self.async_admit is not None else None)

    def attach_transport(self, transport) -> None:
        """Install a parallel/plantransport.PlanTransport (or None to
        detach). With one attached, every round's control decision —
        the post-composition cohort, survivor/work operands, and async
        admit merges — is digested, write-ahead journaled (`schedule`
        events gain a `digest` field, flushed durable before
        dispatch), and cross-checked against the other controllers
        (transport.verify); a diverged process raises PlanDigestError
        instead of silently dispatching a different round."""
        self.plan_transport = transport

    def load_plan_stream(self, journal_path: str) -> None:
        """Deterministic-restart hook: load the write-ahead plan
        stream of the pre-crash run. Two halves:

          * the journaled PLANS install into the scheduler
            (load_replay_plans) — replayed rounds re-execute the
            exact decisions the crashed run durably committed (the
            journal is the AUTHORITATIVE decision log; recomputing a
            throughput selection against the restored tracker would
            diverge wherever wall-clock EMA feeds landed between the
            checkpoint boundary and the crash);
          * the journaled DIGESTS cross-check every replayed round's
            recomputed install digest — a replay that still diverges
            (differing seed/config, a non-deterministic merge) fails
            loud (PlanDigestError) instead of silently rewriting
            history."""
        from commefficient_tpu.parallel.plantransport import (
            journaled_plan_stream,
        )
        self._replay_digests, plans = journaled_plan_stream(
            journal_path)
        if plans and self.scheduler is not None and hasattr(
                self.scheduler, "load_replay_plans"):
            self.scheduler.load_replay_plans(plans)

    def _seal_plan(self, round_idx: int, client_ids,
                   survivors, work, admits=(), pois=None,
                   screen=None) -> None:
        """Write-ahead seal of one round's control decision (ISSUE
        12): journal the `schedule` event (with the install digest
        when a transport or a replay stream is live), cross-check the
        digest against the replayed journal and the other
        controllers. Transport-free default runs with a default
        scheduler stash no fields and compute no digest — this is a
        no-op there, bit-identically to the pre-feature build.

        pois/screen (ISSUE 16): a screened-family round's poison mask
        and screen-enable flag are part of the control decision — they
        ride the digest and the journaled record, so multi-controller
        screened runs verify them like any other operand and a replay
        with a diverged rollback window fails loud."""
        fields = self._plan_journal.pop(int(round_idx), None)
        digest = None
        if self.plan_transport is not None or self._replay_digests:
            digest = install_digest(round_idx, client_ids, survivors,
                                    work, admits, poison=pois,
                                    screen_on=screen)
        if pois is not None and fields is not None:
            fields["screen_on"] = float(screen) if screen is not None \
                else None
            fields["n_poisoned"] = int((np.asarray(pois) > 0).sum())
        if self._replay_digests:
            expect = self._replay_digests.pop(int(round_idx), None)
            if expect is not None and expect != digest:
                raise PlanDigestError(
                    f"round {round_idx}: deterministic-restart replay "
                    f"computed install digest {digest[:12]}… but the "
                    f"write-ahead journal recorded {expect[:12]}… — "
                    "the resumed control stream diverged from what "
                    "the crashed run durably committed (differing "
                    "config/seed, or a non-deterministic decision "
                    "leaked into the plan)")
        if self.plan_transport is not None and fields is None:
            # a transport run journals the write-ahead stream for
            # EVERY round (a default scheduler plans nothing, but the
            # admit merges and fault operands are still the control
            # decision a takeover must be able to verify)
            ids = np.asarray(client_ids).reshape(-1)
            fields = {"round": int(round_idx),
                      "sampler": self.cfg.sampler,
                      "n_sampled": int(len(ids) if survivors is None
                                       else (np.asarray(survivors)
                                             > 0).sum())}
        if fields is not None and self.telemetry is not None:
            if digest is not None:
                fields["digest"] = digest
            self.telemetry.journal_event("schedule", **fields)
            if self.plan_transport is not None:
                self._wa_dirty = True
        if self.plan_transport is not None and digest is not None:
            self.plan_transport.verify(round_idx, digest,
                                       scope="install")

    def _flush_write_ahead(self) -> None:
        """Barrier the journal's writer queue so every sealed plan is
        DURABLE before the dispatch that executes it (the write-ahead
        contract; a no-op for the default synchronous journal, whose
        events are durable as soon as they return, and for
        transport-free runs)."""
        if self._wa_dirty:
            self._wa_dirty = False
            if self.telemetry is not None:
                self.telemetry.journal_flush()

    def drain_persistence(self) -> None:
        """Block until every queued off-critical-path checkpoint write
        (--pipeline's AsyncCheckpointWriter) is durable; a no-op
        otherwise. Drivers call this before any SYNCHRONOUS save (the
        manifest must rotate in order) and in their finally blocks, so
        an InjectedFault drill flushes exactly like a clean
        shutdown. Also drains the tiered state store's spill queue
        (state_tier=host) so every evicted row is durable in the host
        tail."""
        if self.ckpt_writer is not None:
            self.ckpt_writer.drain()
        if self.state_store is not None:
            self.state_store.flush()

    def close_persistence(self) -> None:
        """drain_persistence + stop the writer threads (driver
        shutdown). Idempotent."""
        if self.ckpt_writer is not None:
            self.ckpt_writer.close()
        if self.state_store is not None:
            self.state_store.close()

    def _scheduler_active(self) -> bool:
        """True when an attached scheduler can actually produce plans
        (non-default policy) — the scanned path must then run the
        fault-composition pass even with dropout/stragglers off."""
        return self.scheduler is not None and not self.scheduler.is_default

    def _journal_fault(self, kind: str, round_idx: int) -> None:
        """Record an InjectedFault about to raise (utils/faults) in the
        run journal — the crash boundary is then visible in the run's
        own record, not just the process exit status."""
        if self.telemetry is not None:
            self.telemetry.journal_event("injected_fault", fault=kind,
                                         round=int(round_idx))
            self.telemetry.flush()

    def set_fault_schedule(self,
                           schedule: Optional[FaultSchedule]) -> None:
        """Install (or clear, with None) a deterministic fault script:
        scripted client drops override/augment the random
        client_dropout draw, scripted slow fractions compose (min)
        with the random straggler draw, crash_after raises
        InjectedFault once that round has fully completed, and
        crash_in_span kills the span CONTAINING that round before any
        of it commits — the two preemption points a checkpoint/resume
        test (or chaos drill) recovers from. Note crash_in_span
        RE-FIRES if the schedule is still installed after resume
        (resume restarts the uncommitted round — see FaultSchedule);
        clear it with set_fault_schedule(None) for a drill that should
        progress past the crash."""
        self.fault_schedule = schedule

    def trace_round_programs(self, batch,
                             include_span: bool = False,
                             span_len: int = 2) -> dict:
        """{variant: ClosedJaxpr} of the three single-round programs
        THIS model dispatches — the graftaudit (analysis/audit) hook
        for auditing a real workload rather than the CLI's synthetic
        one. `batch` is a (client_ids, data, mask) triple exactly as
        `model(batch)` takes it; only its shapes/dtypes matter (the
        trace is abstract — nothing executes, no state moves). The
        traced body is `round.make_train_fn`'s COHORT round_step — the
        program the per-round jit compiles, operating on the gathered
        [num_workers, D] CohortState rows (jax.eval_shape over the
        gather body supplies their avals; nothing executes) — so what
        the auditor walks is what `model(batch)` dispatches, and a
        population-shaped operand showing up in it is exactly the
        AU004 regression the audit hard-errors on.

        include_span=True adds a "span" entry: the scanned
        `train_rounds` program over `span_len` stacked copies of the
        batch (round.stack_batch_for_span) — what the mesh tier
        (graftmesh) prices per-link, here traceable over the real
        workload/mesh too."""
        from commefficient_tpu.federated.round import (
            audit_batch_variants, stack_batch_for_span,
        )
        client_ids, data, mask = batch
        rb = fround.RoundBatch(
            jnp.asarray(np.asarray(client_ids, np.int32)),
            tuple(jnp.asarray(d) for d in data),
            jnp.asarray(np.asarray(mask, np.float32)))
        # the lr operand must have the DISPATCHED aval: with a
        # per-parameter scale vector _lr() ships a [D] f32 array, and
        # auditing a scalar-lr program instead would walk a program
        # this model never runs
        lr = (jnp.asarray(0.1 * self.lr_scale_vec)
              if self.lr_scale_vec is not None else jnp.float32(0.1))
        cohort = jax.eval_shape(self._train_round.gather_fn,
                                self.clients, rb.client_ids)
        out = {}
        for variant, vb in audit_batch_variants(rb, self.cfg).items():
            out[variant] = jax.make_jaxpr(self._train_round.round_step)(
                self.server, cohort, vb, lr, self._key)
        if include_span:
            span = stack_batch_for_span(rb, span_len)
            # stacking handles both lr avals: [span_len] for the
            # scalar, [span_len, D] for a per-parameter scale vector
            lrs = jnp.stack([lr] * span_len)
            out["span"] = jax.make_jaxpr(
                self._train_round.train_rounds)(
                self.server, self.clients, span, lrs, self._key)
        return out

    def client_rows_payload(self, clients=None,
                            tier: Optional[dict] = None
                            ) -> Optional[dict]:
        """The O(cohort) client-state checkpoint payload
        (utils/checkpoint `crows_*` keys): the touched-row id set, the
        gathered rows of every tracked state block for exactly those
        ids, and (topk_down) the init-weights base vector untouched
        rows are reconstructed from. None when this model cannot
        guarantee row sparseness — stateless configs (nothing to
        save), or a resume from a legacy dense checkpoint (unknown
        touched set) — in which case callers fall back to the dense
        `clients` save path.

        The device gather pads the id list to a 256 multiple so its
        program recompiles O(log) times over a run, not per save; the
        host transfer is explicit (mh.gather_host), so span-boundary
        saves stay transfer-guard-clean.

        `clients`: optional ClientState override — the pipelined span
        checkpoint (training/scanloop snapshot) persists span t's
        state while self.clients already points at span t+1's
        in-flight result. `tier`: the matching snapshot_tier() dict
        under state_tier=host (the LRU/touched bookkeeping at that
        same boundary).

        Under the tiered store (state_tier=host) the payload comes
        from the store instead: resident rows via an O(working set)
        padded-256 SLOT gather, evicted rows straight from the host
        tail with no device work at all (the satellite fix — a cold
        million-client tail costs the save zero gather bytes), plus
        the LRU order/slot map so resume replays the exact eviction
        stream."""
        if clients is None:
            clients = self.clients
        if self.state_store is not None:
            return self.state_store.checkpoint_rows(clients, tier=tier)
        tracked = [l.ndim == 2 for l in clients]
        if not any(tracked):
            return None
        if not self._sparse_rows_ok:
            return None
        ids = (np.sort(np.fromiter(self._touched, np.int64))
               if self._touched else np.zeros((0,), np.int64))
        payload = {"ids": ids}
        if self._init_weights_host is not None:
            payload["base_weights"] = self._init_weights_host
        empty = np.zeros((0,), np.float32)
        if len(ids) == 0:
            for name in ("errors", "velocities", "weights"):
                payload[name] = empty
            return payload
        padded = np.pad(ids, (0, (-len(ids)) % 256), mode="edge")
        gidx = mh.globalize(self.mesh, self._P(),
                            padded.astype(np.int32))
        for name, used in zip(("errors", "velocities", "weights"),
                              tracked):
            if not used:
                payload[name] = empty
                continue
            field = getattr(clients, name)
            payload[name] = np.asarray(
                mh.gather_host(field[gidx]))[:len(ids)]
        return payload

    @property
    def checkpoint_fingerprint(self) -> dict:
        """The config-compatibility fingerprint checkpoints written by
        this model embed, and resumes into it must match."""
        from commefficient_tpu.utils.checkpoint import config_fingerprint
        return config_fingerprint(self.cfg, self.num_clients)

    def _survivors_for_round(self, round_idx: int, client_ids
                             ) -> Optional[np.ndarray]:
        """[W] f32 survivor mask for one round, or None when nothing
        drops clients (the mask-free fast path — None keeps the jitted
        round on the exact program a dropout-free build traces).
        Deterministic in (cfg.seed, round_idx), so crash->resume
        replays the identical masks. Host-side by design: the mask
        enters the jitted round as data AND drives byte accounting
        without any device sync."""
        ids = np.asarray(client_ids)
        mask = None
        if self.cfg.client_dropout > 0:
            mask = bernoulli_survivors(self.cfg.seed, round_idx,
                                       ids.shape[0],
                                       self.cfg.client_dropout)
        if self.fault_schedule is not None:
            scripted = self.fault_schedule.survival_mask(round_idx, ids)
            if scripted is not None:
                mask = scripted if mask is None else mask * scripted
        return mask

    def _work_for_round(self, round_idx: int, client_ids
                        ) -> Optional[np.ndarray]:
        """[W] f32 work fractions for one round, or None when nothing
        slows clients down. Deterministic in (cfg.seed, round_idx),
        like the survivor draw; scripted FaultSchedule.slow fractions
        compose with the random draw by elementwise minimum (the
        slower cause wins)."""
        W = np.asarray(client_ids).shape[0]
        work = None
        if self.cfg.straggler_rate > 0:
            work = straggler_work_fractions(
                self.cfg.seed, round_idx, W, self.cfg.straggler_rate,
                self.cfg.straggler_min_work)
        if self.fault_schedule is not None:
            scripted = self.fault_schedule.work_fractions(round_idx, W)
            if scripted is not None:
                work = (scripted if work is None
                        else np.minimum(work, scripted))
        return work

    def _faults_for_round(self, round_idx: int, client_ids
                          ) -> Tuple[Optional[np.ndarray],
                                     Optional[np.ndarray]]:
        """(survivors, work) for one round, with the straggler cutoff
        applied: a work fraction below Config.straggler_cutoff
        DEGRADES to the dropout path — its survivor bit is zeroed (no
        upload, state rows bit-untouched, accounting charges nothing)
        and its work entry is reset to the inert 1.0. A work vector
        that ends up all-ones collapses back to None, so such a round
        runs the EXACT dropout program an explicitly-dropped client
        traces — the bit-identity the cutoff contract promises. When
        work survives, a missing survivor mask is filled with ones:
        the work program always carries both operands (round.py traces
        exactly three programs).

        A scheduler RoundPlan composes through the SAME operands
        before the cutoff pass: idle over-provisioned slots zero the
        survivor mask (bit-exactly the dropped-client path) and
        deadline fractions min-compose with the straggler draw — the
        slower cause wins, and a deadline fraction below the straggler
        cutoff degrades to dropout like any other. The consumed plan
        is journaled as a `schedule` event, so scheduling decisions
        are in the run's own record."""
        surv = self._survivors_for_round(round_idx, client_ids)
        work = self._work_for_round(round_idx, client_ids)
        plan = (self.scheduler.take_plan(round_idx)
                if self.scheduler is not None else None)
        if plan is not None:
            if plan.active is not None:
                surv = (plan.active if surv is None
                        else surv * plan.active)
                self._plan_active[int(round_idx)] = plan.active
            if plan.work is not None:
                w = np.asarray(plan.work, np.float32)
                work = w if work is None else np.minimum(work, w)
            if plan.screen_mult is not None:
                # adaptive screening (ISSUE 17): a replayed/broadcast
                # plan's stamped multiplier wins over the local
                # controller's value (_screen_flag pops this)
                self._plan_screen_mult[int(round_idx)] = float(
                    plan.screen_mult)
            if plan.controls:
                # controller bank (ISSUE 20): the plan-carried values
                # are the authoritative trajectory — stash them for
                # compose-time application (staleness decay) and
                # install them as the bank's live state, so followers,
                # replayed rounds, and takeover coordinators all run
                # the journaled decision instead of recomputing one
                self._plan_controls[int(round_idx)] = dict(
                    plan.controls)
                if self.control_bank is not None:
                    self.control_bank.install(plan.controls)
            # journaling is deferred to _seal_plan (ISSUE 12): the
            # `schedule` event must carry the digest of the FULLY
            # composed decision (async admits land after this pass)
            # and be durable before dispatch — write-ahead
            fields = plan.journal_fields()
            if self.plan_transport is not None:
                # transport runs journal the FULL serialized plan: the
                # journal is then the authoritative decision log a
                # deterministic restart REPLAYS (scheduler.
                # load_replay_plans installs these bytes for replayed
                # rounds instead of recomputing decisions against a
                # wall-clock-fed tracker the replay cannot reproduce)
                from commefficient_tpu.parallel.plantransport import (
                    serialize_plan,
                )
                fields["plan"] = serialize_plan(plan).decode()
            self._plan_journal[int(round_idx)] = fields
        if work is not None:
            work = np.asarray(work, np.float32)
            cutoff = self.cfg.straggler_cutoff
            if cutoff > 0:
                below = work < cutoff
                if below.any():
                    s = (np.ones(work.shape[0], np.float32)
                         if surv is None else surv.copy())
                    s[below] = 0.0
                    surv = s
                    work = np.where(below, np.float32(1.0), work)
            if np.all(work >= 1.0):
                work = None
        if work is not None and surv is None:
            surv = np.ones(work.shape[0], np.float32)
        return surv, work

    # -- value-fault screening (ISSUE 16) --------------------------------
    def _screened_dispatch(self, round_idx: int) -> bool:
        """Whether dispatches at `round_idx` take the SCREENED program
        family (round.SCREENED_PROGRAM_VARIANTS): screening or poison
        configured statically, a scripted poison schedule installed,
        or the round inside a post-rollback forced-screen window. A
        default config outside any window builds the poison-free
        treedef, so its three programs stay byte-identical."""
        return (fround.screened_family(self.cfg)
                or round_idx < self._screen_force_until
                or (self.fault_schedule is not None
                    and bool(self.fault_schedule.poison
                             or self.fault_schedule.byzantine)))

    def _poison_values(self, round_idx: int,
                       num_slots: int) -> np.ndarray:
        """[W] f32 {0,1} composed poison mask for one round: the
        random Config.poison_rate draw (utils/faults.poison_mask, its
        own PRNG domain — deterministic in (seed, round), so a resumed
        run replays the identical faults) max-composed with any
        scripted FaultSchedule.poison slots. All-zeros when nothing
        poisons — the inert operand a screening-only round ships.

        Byzantine adversaries (ISSUE 17) ride the SAME operand: under
        Config.byzantine_rate > 0 (validate() makes the two rates
        mutually exclusive, and the attack transform keys statically
        off the rate) the flags mark adversary-controlled slots
        instead — drawn on the "byzantine" PRNG domain, max-composed
        with scripted FaultSchedule.byzantine slots."""
        if self.cfg.byzantine_rate > 0:
            mask = byzantine_mask(self.cfg.seed, round_idx, num_slots,
                                  self.cfg.byzantine_rate)
            if self.fault_schedule is not None:
                scripted = self.fault_schedule.byzantine_mask_for(
                    round_idx, num_slots)
                if scripted is not None:
                    mask = np.maximum(mask, scripted)
            return mask
        mask = poison_mask(self.cfg.seed, round_idx, num_slots,
                           self.cfg.poison_rate)
        if self.fault_schedule is not None:
            scripted = self.fault_schedule.poison_mask_for(round_idx,
                                                           num_slots)
            if scripted is not None:
                mask = np.maximum(mask, scripted)
        return mask

    def _screen_flag(self, round_idx: int) -> np.float32:
        """The traced screen-enable scalar for one round: nonzero when
        the admission screen applies (configured on, or the round is
        in a forced post-rollback window), else 0.0 — poison then
        flows through to the server state (the trip path).

        Adaptive screening (ISSUE 17): under Config.adaptive_screen
        the scalar's VALUE is the live norm multiplier — the traced
        program never changes, the threshold is data. screen_mult_min
        > 1 keeps every on-value disjoint from the off sentinel 0. A
        consumed plan's stamped multiplier (broadcast or journal
        replay — _faults_for_round stashed it) wins over the local
        controller's, so takeover and restart REPLAY the trajectory
        instead of recomputing it."""
        on = (self.cfg.update_screen != "off"
              or round_idx < self._screen_force_until)
        if not on:
            self._plan_screen_mult.pop(int(round_idx), None)
            return np.float32(0.0)
        if self.cfg.adaptive_screen:
            mult = self._plan_screen_mult.pop(int(round_idx), None)
            if mult is None and self.screen_ctl is not None:
                mult = self.screen_ctl.plan_mult()
            if mult is not None:
                return np.float32(mult)
        return np.float32(1.0)

    def force_screen_rounds(self, n: int) -> None:
        """Force the in-round admission screen ON for the next `n`
        dispatched rounds — the finite-frontier rollback's quarantine
        window (Config.rollback_screen_rounds): after walking back to
        a finite checkpoint, the replayed rounds re-draw the identical
        poison (pure in (seed, round)) but the forced screen admits it
        out, so the run crosses the trip boundary finitely."""
        self._screen_force_until = max(
            self._screen_force_until, self._rounds_done + int(n))

    # -- robust aggregation + adaptive screening (ISSUE 17) ---------------
    def _journal_aggregator(self, round_idx: int,
                            stats: np.ndarray) -> None:
        """Journal one round's `aggregator` event from the device
        agg_stats vector (round.RoundMetrics.agg_stats): mean clients
        trimmed per cell, clients norm-clipped, the l2 residual
        between the robust aggregate and the admitted mean, and the
        contributing-client count. A non-finite residual (an entirely
        corrupt cohort) journals as -1.0 — the journal is strict
        JSON."""
        resid = float(stats[2])
        self.telemetry.journal_event(
            "aggregator", round=int(round_idx),
            aggregator=self.cfg.aggregator,
            n_trimmed=round(float(stats[0]), 6),
            n_clipped=int(stats[1]),
            residual_l2=(round(resid, 6) if np.isfinite(resid)
                         else -1.0),
            n_contrib=int(stats[3]))

    # -- compressor plugins + differential privacy (ISSUE 19) -------------
    def _journal_compressor(self, round_idx: int,
                            up_bytes: float) -> None:
        """Journal one committed round's `compressor` event: the
        mode's static per-client wire geometry plus the round's
        accounted upload total — summarize() accumulates these into
        the per-mode bytes-on-wire table."""
        self.telemetry.journal_event(
            "compressor", round=int(round_idx), mode=self.cfg.mode,
            wire_bytes=float(self.cfg.upload_bytes),
            up_bytes=round(float(up_bytes), 3))

    def _journal_privacy(self, round_idx: int) -> None:
        """Journal one committed round's `privacy` event (cumulative
        epsilon over the rounds committed so far) and fail LOUDLY
        once the budget is exhausted. The exhausted round is
        journaled BEFORE the raise, so the journal records the
        crossing a post-mortem needs."""
        eps = float(self.privacy.epsilon(round_idx + 1))
        if self.telemetry is not None:
            self.telemetry.journal_event(
                "privacy", round=int(round_idx),
                epsilon=round(eps, 6),
                sigma=float(self.cfg.dp_noise_mult),
                clip=float(self.cfg.dp_clip),
                delta=float(self.cfg.dp_delta))
        target = float(self.cfg.dp_target_epsilon)
        if target > 0 and eps > target:
            raise RuntimeError(
                f"privacy budget exhausted at round {round_idx}: "
                f"cumulative epsilon {eps:.4f} exceeds "
                f"--dp_target_epsilon {target:g} at delta "
                f"{self.cfg.dp_delta:g}. Raise --dp_noise_mult, "
                f"raise --dp_target_epsilon, or train fewer rounds.")

    def _observe_screening(self, round_idx: int, n_screened: int,
                           survivors) -> None:
        """Feed the adaptive-screen controller one committed round's
        observed screened count — EVERY round, zero included, so the
        trajectory is a pure function of the observation stream — and
        journal a `screen_adapt` event when the threshold moved."""
        n_cohort = (int((np.asarray(survivors) > 0).sum())
                    if survivors is not None else 0)
        changed = self.screen_ctl.observe(round_idx, n_screened,
                                          n_cohort)
        if changed is not None and self.telemetry is not None:
            old, new, rate = changed
            self.telemetry.journal_event(
                "screen_adapt", round=int(round_idx),
                old_mult=round(old, 6), new_mult=round(new, 6),
                rate=round(rate, 6),
                target=float(self.cfg.target_screened_rate))

    # -- plan-riding controller bank (ISSUE 20) --------------------------
    @staticmethod
    def _control_signals(row) -> dict:
        """Commit-time signal dict for ControllerBank.observe_commit
        from one materialized [NUM_METRICS] telemetry row (or {} when
        metrics are off — controllers then skip the observation)."""
        if row is None or getattr(row, "size", 0) == 0:
            return {}
        row = np.asarray(row, np.float32)
        return {"estimate_residual": float(
            row[METRIC_INDEX["estimate_residual"]])}

    def _journal_control_events(self) -> None:
        """Drain the bank's queued adjustments into `control` journal
        events — the single journaling seam for draw-time (stamp),
        commit-time (observe_commit), and span (feed_span)
        adjustments alike."""
        if self.control_bank is None:
            return
        events = self.control_bank.take_events()
        if self.telemetry is None:
            return
        for adj in events:
            self.telemetry.journal_event(
                "control", round=int(adj.round_idx),
                controller=str(adj.controller),
                signal=round(float(adj.signal), 6),
                old=round(float(adj.old), 6),
                new=round(float(adj.new), 6),
                clamped=bool(adj.clamped))

    def _apply_plan_controls(self, round_idx: int) -> None:
        """Apply one consumed plan's stashed controller values to the
        operands the round is about to compose with — currently the
        async admission buffer's staleness decay. Runs BEFORE
        async_admit.compose so the defer/admit weights this round
        journals and digests use exactly the plan-carried discount."""
        controls = self._plan_controls.pop(int(round_idx), None)
        if (controls and self.async_admit is not None
                and "staleness_decay" in controls):
            self.async_admit.decay = float(
                np.float32(controls["staleness_decay"]))

    # -- reference API surface -------------------------------------------
    def train(self, training: bool):
        self.training = training

    def __call__(self, batch):
        if self.training:
            return self._call_train(batch)
        return self._call_val(batch)

    def finalize(self):
        """No worker processes to tear down (reference needed this at
        fed_aggregator.py:196-203); kept for API parity."""

    @property
    def ps_weights(self) -> jax.Array:
        return self.server.ps_weights

    def state_dict(self):
        """Current PS weights as the model's parameter pytree
        (reference materializes this through a __getattr__ hack,
        fed_aggregator.py:372-376)."""
        return self.unravel(self.server.ps_weights)

    def load_state(self, ckpt) -> int:
        """Install a loaded `utils.checkpoint.Checkpoint` into this
        model, globalizing every field onto this model's mesh — the
        multi-controller-safe resume path (every process loads the same
        file from shared storage, the reference's rank-0 rendezvous
        inverted). Returns the checkpoint's scheduler step.

        Validates the checkpoint's config fingerprint (when present)
        against this model — a mismatched resume raises
        CheckpointMismatchError here even if the caller skipped
        validation at load_checkpoint time."""
        if ckpt.fingerprint is not None:
            from commefficient_tpu.utils.checkpoint import (
                validate_fingerprint,
            )
            validate_fingerprint(ckpt.fingerprint,
                                 self.checkpoint_fingerprint,
                                 "<loaded checkpoint>")
        P = self._P
        s = ckpt.server
        # globalize_owned, not globalize: the scanned span DONATES the
        # server state, so the resumed buffers must be XLA-owned — a
        # zero-copied checkpoint numpy array in the donation chain is
        # the heap-corruption class multihost.zeros documents
        self.server = fround.ServerState(
            mh.globalize_owned(self.mesh, P(), s.ps_weights),
            mh.globalize_owned(self.mesh, P(), s.Vvelocity),
            mh.globalize_owned(self.mesh, P(), s.Verror),
            mh.globalize_owned(self.mesh, P(), s.round_idx))
        if ckpt.client_rows is not None:
            # O(cohort) checkpoint (crows_* keys): rebuild the sharded
            # population blocks from init — zeros, or the saved
            # init-weights tile for topk_down — then scatter the saved
            # touched rows in. Bit-exact: untouched rows never left
            # their init values (dropped clients' rows are written
            # back bit-untouched), so init + touched rows IS the full
            # state.
            rows = ckpt.client_rows
            if rows.get("base_weights") is not None:
                self._init_weights_host = np.asarray(
                    rows["base_weights"], np.float32)
            base = (self._init_weights_host
                    if self._init_weights_host is not None
                    else np.asarray(ckpt.server.ps_weights, np.float32))
            if self.state_store is not None:
                # tiered store (ISSUE 11): fresh working-set block at
                # init values, then the store rebuilds the tiers —
                # rows recorded resident (crows_lru_*) scatter back
                # into their slots so the eviction stream replays;
                # everything else (incl. a payload written by a
                # state_tier=device run, which has no lru keys) lands
                # in the host tail. Bit-exact either way: residency
                # never changes row values.
                self.state_store.set_init_weights(
                    self._init_weights_host)
                self.clients = fround.init_client_state(
                    self.cfg,
                    fround.client_state_rows(self.cfg,
                                             self.num_clients),
                    jnp.asarray(base), mesh=self.mesh)
                self.clients = self.state_store.load_rows(
                    self.clients, rows)
                # the store's LRU + tail are the touched set for a
                # tiered model; the host _touched mirror stays unused
                self._sparse_rows_ok = True
                self._finish_load(ckpt)
                return ckpt.scheduler_step
            self.clients = fround.init_client_state(
                self.cfg, self.num_clients, jnp.asarray(base),
                mesh=self.mesh)
            ids = np.asarray(rows["ids"], np.int64)
            self._touched = set(int(i) for i in ids)
            self._sparse_rows_ok = True
            if len(ids):
                gidx = mh.globalize(self.mesh, P(),
                                    ids.astype(np.int32))
                new = self.clients
                for name in ("errors", "velocities", "weights"):
                    data = np.asarray(rows.get(name, ()))
                    field = getattr(new, name)
                    if data.ndim != 2 or field.ndim != 2:
                        continue
                    placed = mh.globalize(self.mesh, P(),
                                          data.astype(np.float32))
                    new = new._replace(
                        **{name: field.at[gidx].set(placed)})
                self.clients = new
        elif ckpt.clients is not None:
            if self.state_store is not None:
                # legacy dense blocks into the tiered store: the
                # vectorized diff against init recovers the touched
                # set the dense format never recorded; touched rows
                # land in the host tail, the working set starts cold,
                # and this model's own saves stay sparse
                dense = {name: np.asarray(getattr(ckpt.clients, name))
                         for name in self.state_store.fields}
                self.state_store.import_dense(dense)
                self._sparse_rows_ok = True
            else:
                # legacy dense client blocks: place them whole. The
                # touched-row set is unrecoverable from a dense save,
                # so this model's own checkpoints fall back to the
                # dense format from here on (client_rows_payload ->
                # None) rather than silently dropping pre-resume rows
                # from sparse saves.
                specs = fround.client_state_specs(ckpt.clients)
                # globalize_owned: these blocks enter the scatter/span
                # donation chain (see the server fields above)
                self.clients = fround.ClientState(*[
                    mh.globalize_owned(self.mesh, spec,
                                       np.asarray(field))
                    for field, spec in zip(ckpt.clients, specs)])
                if any(np.asarray(f).ndim == 2 for f in ckpt.clients):
                    self._sparse_rows_ok = False
        self._finish_load(ckpt)
        return ckpt.scheduler_step

    def _finish_load(self, ckpt) -> None:
        """The state-block-independent half of load_state: accounting,
        throughput, scheduler, sampler, async-admission, and the host
        round mirrors — shared by the device-tier and tiered-store
        resume paths."""
        if ckpt.accountant_state:
            self.accountant.load_state_dict(ckpt.accountant_state)
        if ckpt.throughput:
            # per-client throughput EMA / participation — bit-exact
            # resume (telemetry/clients.py; test_telemetry proves it)
            self.throughput.load_state_dict(ckpt.throughput)
        if ckpt.scheduler and self.scheduler is not None:
            # scheduler counters (sched_* keys) — attach the run's
            # RoundScheduler BEFORE load_state so this lands
            self.scheduler.load_state_dict(ckpt.scheduler)
        if ckpt.sampler and self.data_sampler is not None:
            # FedSampler stream state (smp_* keys) — attach the run's
            # sampler (attach_round_scheduler) BEFORE load_state; the
            # drivers then consume the restored mid-epoch stream via
            # sampler.resolve_resume instead of the head-replay
            # fast-forward
            self.data_sampler.load_state_dict(ckpt.sampler)
        if ckpt.async_admit and self.async_admit is not None:
            # pending async admissions (asyb_* keys): the resumed run
            # admits exactly what the uninterrupted one would have
            self.async_admit.load_state_dict(ckpt.async_admit)
        if ckpt.prev_change_words is not None:
            self._prev_change_words = ckpt.prev_change_words
        # resync the host round mirror so dropout draws / crash points
        # continue exactly where the checkpointed run left off (the
        # stage counter too: a resumed run has no in-flight prefetch —
        # a lost one replays from the restored sampler cursor)
        self._rounds_done = int(np.asarray(ckpt.server.round_idx))
        self._rounds_staged = self._rounds_done

    # -- internals --------------------------------------------------------
    def _feed(self, rows, leading_axes: int = 0):
        """Place one round-batch leaf on the mesh: per-process local
        rows via shard_rows (the default), or — under the feed_global
        fallback — the full global value via globalize with the same
        clients-sharded spec."""
        if self.feed_global:
            P = self._P
            spec = P(*([None] * leading_axes), "clients",
                     *([None] * (np.ndim(rows) - leading_axes - 1)))
            return mh.globalize(self.mesh, spec, rows)
        return mh.shard_rows(self.mesh, rows, leading_axes=leading_axes)

    def _lr(self):
        if self._optimizer is None:
            raise RuntimeError("attach a FedOptimizer before training")
        lr = self._optimizer.param_groups[0]["lr"]
        # per-parameter LR scaling (finetune freezing / Fixup param
        # groups) applies in EVERY mode: for fedavg the [D] vector
        # reaches the client's local SGD steps (fedavg_step broadcasts
        # it elementwise), while the server update stays at lr=1.
        if self.lr_scale_vec is not None:
            return lr * self.lr_scale_vec
        return lr

    def stage_round(self, batch) -> _StagedRound:
        """The HOST half of one round dispatch (ISSUE 10 split):
        crash-in-flight check, fault/schedule composition, async
        admission, and explicit operand placement — everything
        `model(batch)` does before the device sees the round. Pure
        host work keyed by the staged round index (deterministic fault
        draws), so the pipelined driver may stage round t+1 while
        round t executes on device; rounds must be staged and
        committed in the same order. `_call_train` composes
        stage+commit back-to-back, which IS the pre-split synchronous
        path operation for operation."""
        client_ids, data, mask = batch
        this_round = self._rounds_staged
        # mid-span preemption, per-round path: each round is its own
        # span of one — the kill lands while this round's program is
        # in flight, so NOTHING commits (state, accounting, counter)
        if (self.fault_schedule is not None
                and self.fault_schedule.should_crash_in_span(
                    this_round, 1)):
            self._journal_fault("crash_in_span", this_round - 1)
            raise InjectedFault(this_round - 1)
        # graftscope (ISSUE 13): the `plan` stage — fault/schedule
        # composition, async admission, and the write-ahead seal; the
        # scheduler's broadcast/install work nests inside as
        # `plan_install` spans
        with TRACE.span("plan", round=this_round):
            survivors, work = self._faults_for_round(this_round,
                                                     client_ids)
            self._apply_plan_controls(this_round)
            admits = ()
            if self.async_admit is not None:
                # buffered async aggregation (federated/async_agg):
                # defer this round's stragglers onto the
                # dropped-client path and merge admissions due this
                # round into the cohort operands
                (client_ids, data, mask, survivors,
                 work) = self.async_admit.compose(
                    this_round, client_ids, data, mask, survivors,
                    work)
                admits = self.async_admit.last_admits
            # value-fault screening (ISSUE 16): a screened-family
            # round always ships the full operand trio — ones-filled
            # survivors, the composed poison mask, the traced screen
            # flag — so exactly two screened programs exist and the
            # per-round screen decision never retraces
            pois = screen = None
            if self._screened_dispatch(this_round):
                W = np.asarray(client_ids).shape[0]
                pois = self._poison_values(this_round, W)
                screen = self._screen_flag(this_round)
                if survivors is None:
                    survivors = np.ones(W, np.float32)
            # write-ahead plan seal (ISSUE 12): digest + journal the
            # composed control decision, flush it durable before this
            # round's dispatch, and cross-check against the other
            # controllers / the replayed journal. No-op without a
            # transport or replay stream (beyond the journaling the
            # scheduler always got).
            self._seal_plan(this_round, client_ids, survivors, work,
                            admits, pois=pois, screen=screen)
            self._flush_write_ahead()

        # tiered client state (ISSUE 11): assign device slots AFTER
        # admission composition (an admitted client needs a slot too).
        # Pure host bookkeeping — the spill/restore device ops run at
        # commit time against the then-current block, so staging may
        # still run one round ahead under Config.pipeline.
        tier_plan = None
        ids_for_device = np.asarray(client_ids, np.int32)
        if self.state_store is not None:
            tier_plan = self.state_store.plan_round(client_ids)
            ids_for_device = tier_plan.slots

        with TRACE.span("stage", round=this_round):
            P = self._P
            lr = self._lr()
            # explicit placement for BOTH lr shapes: a raw python
            # float operand is an IMPLICIT host->device transfer at
            # every dispatch — the first thing --debug_transfer_guard
            # caught. np.float32(lr) is the identical f32 value the
            # weak-typed scalar would have become, so results are
            # bit-unchanged.
            lr = mh.globalize(self.mesh, P(),
                              lr if isinstance(lr, np.ndarray)
                              else np.float32(lr))
            placed = fround.RoundBatch(
                mh.globalize(self.mesh, P(), ids_for_device),
                tuple(self._feed(d) for d in data),
                self._feed(mask),
                None if survivors is None
                else mh.globalize(self.mesh, P(), survivors),
                None if work is None
                else mh.globalize(self.mesh, P(), work),
                None if pois is None
                else mh.globalize(self.mesh, P(), pois),
                None if pois is None
                else mh.globalize(self.mesh, P(), screen))
        self._rounds_staged = this_round + 1
        return _StagedRound(this_round, placed, lr,
                            np.asarray(client_ids), survivors,
                            tier_plan)

    def commit_staged(self, staged: _StagedRound):
        """The DISPATCH half: the gather->round->scatter bracket plus
        the lagged accounting/telemetry bookkeeping. Donation contract
        (Config.donate_round_state): the round jit donates the
        gathered CohortState and the scatter-back jit donates the full
        ClientState — self.clients is reassigned from the result below
        and never read in between. ServerState is deliberately NOT
        donated on this path: the prev_weights reference captured here
        is read AFTER dispatch for the one-round-lagged accounting
        bitset, and a donated ps_weights would be a deleted buffer by
        then (round.ROUND_DEAD_ARGNUMS / SCATTER_DEAD_ARGNUMS are the
        authoritative declarations)."""
        prev_weights = self.server.ps_weights
        this_round = staged.round_idx
        if staged.tier_plan is not None:
            # tier motion first (ISSUE 11): spill-gather the plan's
            # eviction victims from the CURRENT block (their values
            # include every earlier round's scatter-back), then
            # restore-scatter the misses' host rows into their slots —
            # both through the round handle's existing state-motion
            # programs, so the gather below reads a fully-resident
            # working set. The graftscope bracket carries the round
            # tag the nested tier_spill/tier_restore spans inherit.
            with TRACE.span("tier_motion", round=this_round):
                self.clients = self.state_store.execute(
                    self.clients, staged.tier_plan)
        with TRACE.span("dispatch", round=this_round):
            self.server, self.clients, metrics = self._train_round(
                self.server, self.clients, staged.batch, staged.lr,
                self._key)
        self._rounds_done = this_round + 1
        # O(cohort) checkpoint support: these rows may now differ from
        # their init values (dropped clients' rows were written back
        # bit-untouched, but over-including them only costs a few
        # zero rows in the sparse save). The tiered store tracks its
        # own touched set (LRU + tail) — this host mirror would be
        # write-only dead weight there.
        if self.state_store is None:
            self._touched.update(
                int(i) for i in staged.client_ids.reshape(-1))

        # Communication accounting with ONE round of lag: this round's
        # change bitset is dispatched and its device->host copy started
        # asynchronously; the popcount consumes the PREVIOUS round's
        # bits, which are already on the host. Materializing the fresh
        # bits here instead would block on the round that was just
        # dispatched — a full round-trip of sync per round on the
        # tunnel (PERF.md measurement rules).
        with TRACE.span("collect", round=this_round):
            bits = self._pack_bits(self.server.ps_weights
                                   - prev_weights)
            bits.copy_to_host_async()
            # screened family (ISSUE 16): accounting charges the
            # EFFECTIVE mask — host survivors x device admission — so
            # a screened client is billed exactly like a dropped one.
            # The device_get is a sync, but only screened configs ever
            # take it; the default path reads the host copy as before.
            # Robust aggregation (ISSUE 17) narrows the billed mask
            # once more: a client the order statistics kept NO cell of
            # (metrics.contributors) shipped an update the aggregate
            # provably contains nothing of, so it is billed like a
            # screened one.
            surv_acc = staged.survivors
            if metrics.admitted is not None:
                surv_acc = np.asarray(jax.device_get(metrics.admitted),
                                      np.float32)
            surv_bill = surv_acc
            if metrics.contributors is not None:
                surv_bill = np.asarray(
                    jax.device_get(metrics.contributors), np.float32)
            download, upload = self.accountant.record_round(
                staged.client_ids,
                None if self._prev_change_words is None
                else np.asarray(self._prev_change_words),
                survivors=surv_bill)
        self._prev_change_words = bits
        n_screened = None
        if metrics.admitted is not None and staged.survivors is not None:
            n_screened = int((staged.survivors > 0).sum()
                             - (surv_acc > 0).sum())
            if n_screened > 0 and self.telemetry is not None:
                self.telemetry.journal_event(
                    "screened", round=this_round,
                    n_screened=n_screened,
                    kind=(self.cfg.update_screen
                          if self.cfg.update_screen != "off"
                          else "finite"))
        if metrics.agg_stats is not None and self.telemetry is not None:
            self._journal_aggregator(
                this_round, np.asarray(
                    jax.device_get(metrics.agg_stats), np.float64))
        if self.screen_ctl is not None and n_screened is not None:
            self._observe_screening(this_round, n_screened,
                                    staged.survivors)
        # controller bank (ISSUE 20): commit-time observation on the
        # round's device-deterministic metric row (a replayed round
        # re-observes identically), then drain every queued
        # adjustment — draw-time stamps included — into `control`
        # journal events. The device_get is a sync, but only
        # bank-enabled configs ever take it.
        if self.control_bank is not None:
            self.control_bank.observe_commit(
                this_round, self._control_signals(
                    jax.device_get(metrics.telemetry)
                    if self.cfg.telemetry else None))
            self._journal_control_events()
        # compressor + privacy journaling (ISSUE 19): per committed
        # round, after accounting so up_bytes is this round's billed
        # total. _journal_privacy raises once the epsilon budget is
        # exhausted — the round above fully committed, so the abort
        # lands at the same clean boundary an injected crash does.
        if self.telemetry is not None:
            self._journal_compressor(this_round, upload.sum())
        if self.privacy is not None:
            self._journal_privacy(this_round)

        # telemetry, one-round lag (same discipline as the metric
        # return below): hand the session this round's DEVICE metric
        # vector + example counts; it materializes the previous round's
        # (already complete — free) and journals it
        sched_mask = self._plan_active.pop(this_round, None)
        if self.telemetry is not None:
            self.telemetry.on_round(
                this_round, staged.client_ids,
                metrics.telemetry if self.cfg.telemetry else None,
                metrics.num_examples,
                comm=(float(download.sum()), float(upload.sum())),
                scheduled=sched_mask)
            if self.state_store is not None:
                # tier residency telemetry (ISSUE 11): working-set
                # hit/miss and spill/restore deltas for this round —
                # journal-schema-checked by validate_journal, hit rate
                # surfaced by summarize()
                self.telemetry.journal_event(
                    "state_tier", round=this_round,
                    **self.state_store.take_journal_fields())
                # checksummed tiers (ISSUE 16): any tail rows that
                # failed verification since the last drain journal
                # one loud `state_quarantine` event each
                for q in self.state_store.take_quarantine_events():
                    self.telemetry.journal_event(
                        "state_quarantine", round=this_round, **q)

        # injected preemption: the round above fully completed (state,
        # accounting, round counter) — crash at the exact boundary a
        # real preemption would leave behind
        if (self.fault_schedule is not None
                and self.fault_schedule.should_crash(this_round)):
            self._journal_fault("crash_after", this_round)
            raise InjectedFault(this_round)

        # metrics stay device arrays: callers that float() them decide
        # when to pay the sync (drivers materialize with a 1-round lag)
        return [metrics.losses, *metrics.metrics, download, upload]

    def _call_train(self, batch):
        """batch = (client_ids, data, mask). `client_ids` is always the
        GLOBAL [W] participant list (cheap; the sampler runs identically
        on every process). In a multi-controller run, `data`/`mask`
        carry ONLY this process's rows (FedLoader feed_slice →
        multihost.local_row_slice): per-process batch feeding — no host
        materializes the global batch."""
        return self.commit_staged(self.stage_round(batch))

    def run_rounds(self, client_ids, data, mask, lrs, account: bool = True):
        """Run N federated rounds as ONE device program (scanned; see
        round.train_rounds). client_ids: [N, W]; data: pytree of
        [N, W, B, ...]; mask: [N, W, B]; lrs: [N].

        Composed from `dispatch_rounds` (host staging + the async
        device dispatch) and `collect_rounds` (blocking on the span's
        results, then accounting/telemetry/crash bookkeeping) — the
        ISSUE 10 split the pipelined staging loop uses to overlap span
        t+1's dispatch with span t's collection. Called through here
        the two halves run back-to-back: the pre-split synchronous
        behavior, operation for operation.

        Returns (losses [N, W], metrics [N, W]..., download, upload)
        with download/upload the span's total BYTES (scalars — the
        accountant's per-round rows are cohort-indexed since ISSUE 9,
        so there is no population-length vector to hand back, and
        every caller only ever consumed the totals). account=False
        returns zeros and skips the per-round popcount work, but the
        [N, D/32] bitset transfer and staleness bookkeeping still
        happen so later accounted rounds stay correct.

        Fault tolerance: per-round survivor masks (client_dropout /
        FaultSchedule drops) and work fractions (straggler_rate /
        FaultSchedule slow) ride into the scanned program as [N, W]
        operands; a FaultSchedule crash_after that lands INSIDE the
        span truncates it — only the rounds up to and including the
        crash round run (and are accounted), then InjectedFault is
        raised at the identical boundary the unscanned path crashes
        at, so scanned and per-round runs checkpoint/resume
        bit-identically. A crash_in_span landing anywhere in the span
        instead kills it BEFORE any round commits (the host died while
        the span's device program was in flight) — resume must come
        from the last span boundary's checkpoint."""
        return self.collect_rounds(
            self.dispatch_rounds(client_ids, data, mask, lrs,
                                 account=account))

    def dispatch_rounds(self, client_ids, data, mask, lrs,
                        account: bool = True) -> "_SpanHandle":
        """Stage and DISPATCH one scanned span without blocking on its
        results: fault/schedule composition and async admission per
        round, explicit operand placement, the retry-guarded span
        dispatch, and the state reassignment (the returned arrays are
        futures — dispatch is asynchronous). Returns the handle
        `collect_rounds` consumes; handles must be collected in
        dispatch order. The pipelined staging loop dispatches span t+1
        before collecting span t, so the device never idles on host
        staging or persistence."""
        lrs = np.asarray(lrs, np.float32)
        ids_host = np.asarray(client_ids)
        n_rounds = ids_host.shape[0]
        first = self._rounds_done

        # mid-span preemption: the whole span is lost — no state, no
        # accounting, no counter movement; InjectedFault carries the
        # last round that actually completed (the last span boundary)
        if (self.fault_schedule is not None
                and self.fault_schedule.should_crash_in_span(
                    first, n_rounds)):
            self._journal_fault("crash_in_span", first - 1)
            raise InjectedFault(first - 1)

        # span truncation at an injected crash boundary
        crash_at = None
        if (self.fault_schedule is not None
                and self.fault_schedule.crash_after is not None
                and first <= self.fault_schedule.crash_after
                < first + n_rounds):
            crash_at = int(self.fault_schedule.crash_after)
            n_rounds = crash_at - first + 1
            ids_host = ids_host[:n_rounds]
            lrs = lrs[:n_rounds]
            data = tuple(np.asarray(d)[:n_rounds] for d in data)
            mask = np.asarray(mask)[:n_rounds]

        # per-round survivor masks + work fractions (None when nothing
        # can drop/slow — the operand-free treedefs keep the scanned
        # program a fault-free build traces). Any round with work
        # forces the full [N, W] pair: one scanned program per span.
        # With async admission on, every round runs the composition
        # pass (pending entries from earlier rounds/spans may admit
        # here) and the composed ids/data/mask rows replace the staged
        # ones — still a pure host-side merge on the cohort operands.
        surv_all = work_all = None
        pois_all = screen_all = None
        screened = self._screened_dispatch(first)
        span_idx = int(getattr(self, "_spans_dispatched", 0))
        if (self.cfg.client_dropout > 0 or self.cfg.straggler_rate > 0
                or self.fault_schedule is not None
                or self._scheduler_active()
                or self.async_admit is not None
                or self.plan_transport is not None
                or self._replay_digests
                or screened):
            # graftscope: the whole span's per-round composition is
            # ONE `plan` stage span (tagged with the first round)
            with TRACE.span("plan", round=first, span=span_idx):
                copied = False
                rows = []
                for n in range(n_rounds):
                    s, w = self._faults_for_round(first + n,
                                                  ids_host[n])
                    self._apply_plan_controls(first + n)
                    admits = ()
                    if self.async_admit is not None:
                        row_ids = ids_host[n]
                        row_data = tuple(np.asarray(d)[n]
                                         for d in data)
                        row_mask = np.asarray(mask)[n]
                        ids_n, data_n, mask_n, s, w = \
                            self.async_admit.compose(
                                first + n, row_ids, row_data,
                                row_mask, s, w)
                        admits = self.async_admit.last_admits
                        if ids_n is not row_ids:
                            # an admission rewrote this round's cohort
                            # rows — copy the span containers LAZILY
                            # (the caller's staged arrays stay
                            # untouched; the common nothing-due case
                            # pays no memcpy)
                            if not copied:
                                ids_host = np.array(ids_host,
                                                    copy=True)
                                data = tuple(
                                    np.array(np.asarray(d), copy=True)
                                    for d in data)
                                mask = np.array(np.asarray(mask),
                                                copy=True)
                                copied = True
                            ids_host[n] = ids_n
                            for d, d_n in zip(data, data_n):
                                d[n] = d_n
                            mask[n] = mask_n
                    # screened family (ISSUE 16): per-round poison
                    # mask + screen flag ride the scanned program as
                    # [N, W]/[N] operands; a forced-screen window
                    # ending mid-span just flips the DATA flag — one
                    # scanned program either way
                    pois_n = screen_n = None
                    if screened:
                        W_n = np.asarray(ids_host[n]).shape[0]
                        pois_n = self._poison_values(first + n, W_n)
                        screen_n = self._screen_flag(first + n)
                        if s is None:
                            s = np.ones(W_n, np.float32)
                    # write-ahead seal per round (ISSUE 12): the whole
                    # span's sealed records flush as one barrier
                    # below, still BEFORE the span's dispatch
                    self._seal_plan(first + n, ids_host[n], s, w,
                                    admits, pois=pois_n,
                                    screen=screen_n)
                    rows.append((s, w, pois_n, screen_n))
                ones = np.ones(ids_host.shape[1], np.float32)
                if any(w is not None for _, w, _, _ in rows):
                    work_all = np.stack(
                        [w if w is not None else ones
                         for _, w, _, _ in rows])
                    surv_all = np.stack(
                        [s if s is not None else ones
                         for s, _, _, _ in rows])
                elif any(s is not None for s, _, _, _ in rows):
                    surv_all = np.stack(
                        [s if s is not None else ones
                         for s, _, _, _ in rows])
                if screened:
                    pois_all = np.stack([p for _, _, p, _ in rows])
                    screen_all = np.asarray(
                        [f for _, _, _, f in rows], np.float32)
                    if surv_all is None:
                        surv_all = np.stack([ones] * n_rounds)

        # tiered client state (ISSUE 11): the span executes as ONE
        # device program with the working-set block on the scan carry,
        # so every miss is restored (and every victim spilled) up
        # front, each round's plan pinning the span's later cohorts
        # resident (plan_span raises an actionable error when the
        # working set cannot hold a span's distinct clients). Under
        # Config.pipeline this staging overlaps the PREVIOUS span's
        # device execution — the prefetch the tier needs to stay off
        # the critical path. The dispatched id operand becomes the
        # per-round SLOT rows; ids_host keeps the global ids for
        # accounting/telemetry.
        ids_device = ids_host
        if self.state_store is not None:
            with TRACE.span("tier_motion", round=first,
                            span=span_idx):
                plans = self.state_store.plan_span(ids_host)
                for plan in plans:
                    self.clients = self.state_store.execute(
                        self.clients, plan)
                ids_device = np.stack([p.slots for p in plans])

        if self.lr_scale_vec is not None:
            # per-parameter LR scaling — same routing _lr() applies on
            # the single-round path (incl. fedavg: the vector reaches
            # the clients' local steps)
            lrs = lrs[:, None] * self.lr_scale_vec[None, :]
        P = self._P

        # multi-controller feeding contract matches _call_train: ids
        # global, data/mask rows process-local (leading [N] span axis
        # unsharded). Dispatch is retry-guarded (utils/retry): the
        # scanned program is FUNCTIONAL — state is only assigned from
        # its result — so a transient runtime failure (coordinator
        # blip on a preemptible pod) can safely be retried without
        # half-mutated state; fatal errors re-raise immediately.
        # Donation caveat (Config.donate_round_state, default on): the
        # span jit donates BOTH state operands (run_rounds reads
        # nothing after dispatch — even the change bitset comes from
        # the span's result), so once the dispatch has CONSUMED them a
        # replay would re-dispatch deleted buffers. _span_classify
        # below closes the ISSUE 7 caveat: a transient-looking failure
        # is reclassified FATAL the moment any donated state leaf is
        # already deleted — the ORIGINAL error re-raises instead of a
        # retry that would either silently replay consumed state or
        # surface a confusing array-deleted error one attempt later.
        # Failures in the staging/globalize phase (where coordinator
        # blips actually land) leave the operands alive and retry as
        # before; --no_donate_round_state restores full span
        # retryability at the cost of transiently doubled state HBM.
        def dispatch():
            return self._train_round.train_rounds(
                self.server, self.clients,
                fround.RoundBatch(
                    mh.globalize(self.mesh, P(),
                                 np.asarray(ids_device, np.int32)),
                    tuple(self._feed(d, leading_axes=1)
                          for d in data),
                    self._feed(mask, leading_axes=1),
                    None if surv_all is None
                    else mh.globalize(self.mesh, P(), surv_all),
                    None if work_all is None
                    else mh.globalize(self.mesh, P(), work_all),
                    None if pois_all is None
                    else mh.globalize(self.mesh, P(), pois_all),
                    None if screen_all is None
                    else mh.globalize(self.mesh, P(), screen_all)),
                mh.globalize(self.mesh, P(), lrs), self._key)

        def _journal_retry(attempt: int, exc: BaseException,
                           delay: float) -> None:
            if self.telemetry is not None:
                self.telemetry.journal_event(
                    "retry", op="scanned round span",
                    attempt=int(attempt), delay_s=round(delay, 3),
                    error=repr(exc)[:200])

        def _span_classify(exc: BaseException) -> bool:
            """Transient AND safe to replay: with donation on, a
            dispatch that already consumed its state operands must not
            be re-dispatched (the ISSUE 7 retry caveat, now closed
            mechanically — tests/test_pipeline.py regression)."""
            if not is_transient_error(exc):
                return False
            if self._train_round.span_donate_argnums:
                for leaf in jax.tree.leaves((self.server, self.clients)):
                    if getattr(leaf, "is_deleted", lambda: False)():
                        return False
            return True

        # write-ahead barrier (ISSUE 12): every sealed plan of this
        # span must be durable before the span executes
        self._flush_write_ahead()
        t_dispatch0 = time.monotonic()
        # graftscope: the `dispatch` span is the HOST cost of staging
        # + dispatching the scanned program (operand placement and
        # the async dispatch call) — the device-side window is the
        # `device_execute` span collect_rounds records at the seam
        with TRACE.span("dispatch", round=first, span=span_idx):
            self.server, self.clients, metrics, bits = with_retries(
                dispatch, describe="scanned round span",
                classify=_span_classify, on_retry=_journal_retry)
        t_dispatched = time.monotonic()
        self._rounds_done = first + n_rounds
        self._rounds_staged = max(self._rounds_staged,
                                  self._rounds_done)
        if self.state_store is None:
            # tiered models track touched ids in the store (see
            # commit_staged)
            self._touched.update(
                int(i) for i in np.asarray(ids_host).reshape(-1))
        return _SpanHandle(first=first, ids_host=ids_host,
                           surv_all=surv_all, work_all=work_all,
                           crash_at=crash_at, account=account,
                           metrics=metrics, bits=bits,
                           t_dispatch0=t_dispatch0,
                           t_dispatched=t_dispatched,
                           span_idx=span_idx)

    def collect_rounds(self, handle: "_SpanHandle"):
        """Block on a dispatched span's results and COMMIT it: the
        accounting bitset device_get, per-round byte accounting, the
        span-boundary telemetry export, the injected crash_after
        boundary, and the metric gathers. Handles must be collected in
        the order their spans were dispatched (accounting and the
        change-bitset lag are sequential)."""
        first = handle.first
        ids_host = handle.ids_host
        surv_all = handle.surv_all
        metrics = handle.metrics
        account = handle.account
        crash_at = handle.crash_at

        # span byte totals (the accountant's per-round rows are
        # COHORT-indexed since ISSUE 9 — a population-length vector
        # per round was exactly the O(num_clients) host cost this
        # refactor removes; callers of this method only ever consumed
        # the totals)
        download = np.float64(0.0)
        upload = np.float64(0.0)
        # explicit device_get (not np.asarray): run_rounds is
        # transfer-guard-clean end to end — tests arm
        # analysis/runtime.forbid_transfers around the whole call
        bits_host = jax.device_get(handle.bits)
        t_blocked = time.monotonic()
        # graftscope: the device-execute window, bracketed at the
        # dispatch/collect seam — dispatch-returned to span-results-
        # forced. Under --pipeline consecutive spans' windows overlap
        # (the double buffer working); the overlap-efficiency metric
        # in summarize() takes the interval UNION. The span tag is
        # the scanned-span index --profile_spans selects on, so a
        # jax.profiler capture correlates with exactly these spans.
        TRACE.record("device_execute", handle.t_dispatched, t_blocked,
                     round=handle.first,
                     span=(handle.span_idx
                           if handle.span_idx >= 0 else None))
        with TRACE.span("collect", round=handle.first,
                        span=(handle.span_idx
                              if handle.span_idx >= 0 else None)):
            if self._prev_change_words is not None:
                # may still be a device array from a preceding
                # single-round call (the lazy-sync path in
                # _call_train)
                self._prev_change_words = jax.device_get(
                    self._prev_change_words)
            # screened family (ISSUE 16): the span's per-round
            # admitted rows replace the host survivor rows for
            # accounting (the bits transfer above already forced the
            # span, so this gather adds no sync) and journal one
            # `screened` event per round that screened anyone
            admitted_rows = None
            if metrics.admitted is not None:
                admitted_rows = np.asarray(
                    mh.gather_host(metrics.admitted), np.float32)
            # robust aggregation (ISSUE 17): per-round contributor
            # masks (billing) and aggregator stats (journal) ride the
            # span results like the admitted rows — the bits transfer
            # already forced the span, these gathers add no sync
            contrib_rows = None
            if metrics.contributors is not None:
                contrib_rows = np.asarray(
                    mh.gather_host(metrics.contributors), np.float32)
            agg_rows = None
            if metrics.agg_stats is not None:
                agg_rows = np.asarray(
                    mh.gather_host(metrics.agg_stats), np.float64)
            comm_rows = []
            for n in range(ids_host.shape[0]):
                surv_n = None if surv_all is None else surv_all[n]
                if admitted_rows is not None:
                    n_scr = None
                    if surv_n is not None:
                        n_scr = int((surv_n > 0).sum()
                                    - (admitted_rows[n] > 0).sum())
                        if n_scr > 0 and self.telemetry is not None:
                            self.telemetry.journal_event(
                                "screened", round=first + n,
                                n_screened=n_scr,
                                kind=(self.cfg.update_screen
                                      if self.cfg.update_screen
                                      != "off" else "finite"))
                    if (agg_rows is not None
                            and self.telemetry is not None):
                        self._journal_aggregator(first + n,
                                                 agg_rows[n])
                    if self.screen_ctl is not None and n_scr is not None:
                        self._observe_screening(first + n, n_scr,
                                                surv_n)
                    surv_n = (contrib_rows[n]
                              if contrib_rows is not None
                              else admitted_rows[n])
                if account:
                    d, u = self.accountant.record_round(
                        ids_host[n], self._prev_change_words,
                        survivors=surv_n)
                    download += d.sum()
                    upload += u.sum()
                    comm_rows.append((float(d.sum()),
                                      float(u.sum())))
                else:
                    # keep the change deque and staleness counters in
                    # sync (skipping only the popcount work) so a
                    # later accounted round doesn't misattribute
                    # downloads across the gap
                    self.accountant.advance_round(
                        ids_host[n], self._prev_change_words,
                        survivors=surv_n)
                    comm_rows.append(None)
                self._prev_change_words = bits_host[n]
                # compressor + privacy journaling (ISSUE 19) — same
                # per-round events as the unscanned commit path; the
                # budget raise lands after this round's accounting
                # lag advanced, the boundary a resume expects
                if (self.telemetry is not None
                        and comm_rows[-1] is not None):
                    self._journal_compressor(first + n,
                                             comm_rows[-1][1])
                if self.privacy is not None:
                    self._journal_privacy(first + n)

        # span-boundary telemetry export: ONE explicit device_get of
        # the [N, M] metric rows + [N, W] example counts, after the
        # bits transfer already forced span completion — telemetry adds
        # no sync points, and the explicit gathers keep the span
        # transfer-guard-clean (test_telemetry proves both). Runs after
        # the accounting loop so each journaled round carries its byte
        # totals (telemetry/journal `down_bytes`/`up_bytes`).
        sched_rows = [self._plan_active.pop(first + n, None)
                      for n in range(ids_host.shape[0])]
        if all(r is None for r in sched_rows):
            sched_rows = None
        tele_rows = None
        if self.telemetry is not None or self.control_bank is not None:
            tele_rows = (mh.gather_host(metrics.telemetry)
                         if self.cfg.telemetry else None)
        if self.telemetry is not None:
            counts_rows = mh.gather_host(metrics.num_examples)
            self.telemetry.on_span(
                first, ids_host, tele_rows, counts_rows,
                dispatch_s=handle.t_dispatched - handle.t_dispatch0,
                block_s=t_blocked - handle.t_dispatched,
                comm_rows=comm_rows, scheduled_rows=sched_rows)
            if self.state_store is not None:
                # per-span tier residency record (ISSUE 11). Under
                # Config.pipeline the deltas attribute the NEXT span's
                # already-staged motion to this span's record — a
                # bounded, documented skew (the journal is validated
                # on schema, not on per-span attribution)
                self.telemetry.journal_event(
                    "state_tier", first_round=first,
                    rounds=int(ids_host.shape[0]),
                    **self.state_store.take_journal_fields())
                for q in self.state_store.take_quarantine_events():
                    self.telemetry.journal_event(
                        "state_quarantine", first_round=first, **q)

        # controller bank (ISSUE 20): per-round commit observation on
        # the span's materialized metric rows (deterministic — a
        # replayed span re-observes identically), then the span-
        # cadence feed with the span's realized wall time (dispatch +
        # device execute; wall-clock, so its adjustments only ever
        # ride FUTURE fresh plans), then one drain of every queued
        # adjustment into `control` journal events — before the
        # injected-crash boundary below, matching the unscanned path
        # where committed rounds journal their adjustments before the
        # crash raises.
        if self.control_bank is not None:
            n_committed = int(ids_host.shape[0])
            for n in range(n_committed):
                self.control_bank.observe_commit(
                    first + n, self._control_signals(
                        None if tele_rows is None else tele_rows[n]))
            self.control_bank.feed_span(
                first + n_committed - 1, n_committed,
                float(t_blocked - handle.t_dispatch0))
            self._journal_control_events()

        if crash_at is not None:
            # every completed round's state/accounting landed above —
            # crash at the same boundary the unscanned path does
            self._journal_fault("crash_after", crash_at)
            raise InjectedFault(crash_at)

        losses = mh.gather_host(metrics.losses)
        mets = [mh.gather_host(m) for m in metrics.metrics]
        return [losses, *mets, download, upload]

    def _call_val(self, batch):
        """Multi-controller contract mirrors _call_train: `data`/`mask`
        are this process's shard rows; results are allgathered so every
        process returns the full per-shard metrics."""
        data, mask = batch
        loss, mets, count = self._eval_batch(
            self.server.ps_weights,
            tuple(self._feed(d) for d in data),
            self._feed(mask))
        return [mh.gather_host(loss), *[mh.gather_host(m) for m in mets],
                mh.gather_host(count)]


class FedOptimizer:
    """Holds param_groups for LR scheduling (reference FedOptimizer,
    fed_aggregator.py:384-458). The actual server update runs fused
    inside FedModel's round program; see module docstring."""

    def __init__(self, model: FedModel, cfg: Optional[Config] = None):
        self.model = model
        self.cfg = cfg or model.cfg
        self.param_groups = [{"lr": 0.0}]
        model._optimizer = self

    def step(self):
        """Host-side no-op kept for reference call-pattern parity; the
        weight update already happened inside model(batch)."""

    def zero_grad(self):
        raise NotImplementedError(
            "gradients are per-round temporaries in the fused design")

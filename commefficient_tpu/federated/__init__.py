from commefficient_tpu.federated.round import (  # noqa: F401
    RoundBatch, ServerState, ClientState, CohortState, RoundMetrics,
    client_state_specs, init_server_state, init_client_state,
    make_round_fns,
)
from commefficient_tpu.federated.server import (  # noqa: F401
    ServerUpdate, get_server_update, args2sketch,
)
from commefficient_tpu.federated.api import FedModel, FedOptimizer  # noqa: F401
from commefficient_tpu.federated.accounting import (  # noqa: F401
    CommAccountant, pack_change_bits,
)

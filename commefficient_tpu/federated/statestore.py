"""Tiered cold client state (ISSUE 11): a bounded device-HBM working
set over a host-spilled long tail.

PR 9 made every per-round *cost* O(active cohort), but the
`[padded_population, D]` client-state blocks still lived sharded in
device HBM — ~78 TB at flagship D for 1e6 local_topk clients, so
"million clients" was real for compute but not for residency. Behind
``Config.state_tier=host`` this module caps the device-resident rows
at an LRU working set of ``Config.state_working_set`` recently-active
clients: the ClientState blocks shrink to ``[working_set, D]``
(federated/round.client_state_rows) and rows are addressed by device
SLOT, while the cold tail lives on the host (optionally disk-backed
sparse memmaps under ``Config.state_spill_dir``).

The PR-9 cohort-gather/scatter-back state-motion pair is the single
choke point extended — and stays the ONLY pair of state-motion
programs per config:

  * a cohort member already resident is a working-set HIT: its slot
    rides straight into the gather's index operand;
  * a MISS is RESTORED before the round through the *same jitted
    scatter program* — its row (host tail, a still-in-flight spill,
    or the init value for a never-seen client) is built host-side,
    explicitly placed with the gather's own cohort shardings, and
    scattered into the assigned slot;
  * the eviction victim's row is SPILLED through the *same jitted
    gather program*: gathered by slot, its device->host copy started
    asynchronously (multihost.async_gather_host), and committed to
    the host tail by a bounded-queue writer thread — the ISSUE-10
    off-critical-path persistence pattern, so a slow host never
    stalls the round loop. Spills are CORRECTNESS (not best-effort
    observability): writer failures re-raise on the caller's thread
    at the next submit/flush.

The three round programs still see only ``[num_workers, D]``
CohortState operands (graftaudit AU004-strict keeps them honest while
the tier moves underneath), and because f32 rows round-trip the host
bit-exactly and the round program is trace-identical between tiers,
the PER-ROUND path is BIT-IDENTICAL to ``state_tier=device``
(tests/test_statetier.py). The scanned span traces a different
program (the block shape rides the carry), so cross-tier agreement
there is the usual cross-program class — exact at test geometries,
float-level where XLA compiles the two spans differently (the PR-9
caveat); each tier's own scanned run is deterministic and resumes
bit-exactly.

Determinism: the LRU advances only in ``plan_round`` — a pure
function of the cohort-id stream — slots are assigned in ascending
order, and the LRU recency order + slot map ride in checkpoints
(``crows_lru_ids`` / ``crows_lru_slots``), so a resumed run replays
the exact eviction stream of the uninterrupted one. A checkpoint
drains the spill queue first, so a crash with spills in flight
resumes bit-exactly from the last saved boundary (the mid-spill
contract).
"""
from __future__ import annotations

import errno
import os
import threading
import zlib
from collections import OrderedDict, deque
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from commefficient_tpu.federated import round as fround
from commefficient_tpu.parallel import multihost as mh
from commefficient_tpu.telemetry.trace import TRACE

# the tracked client-state blocks, in ClientState field order — the
# serialization contract shared with utils/checkpoint's crows_* keys
STATE_FIELDS = ("errors", "velocities", "weights")


def _row_crc(row: np.ndarray) -> int:
    """CRC32 of one tail row's f32 bytes — the per-chunk checksum the
    tiers record at spill time and verify at restore (ISSUE 16). f32
    rows round-trip the host bit-exactly, so any mismatch is real
    corruption (torn memmap page, bit rot, an external write), never
    a representation artifact."""
    return zlib.crc32(np.ascontiguousarray(
        row, dtype=np.float32).tobytes()) & 0xFFFFFFFF


def tracked_fields(cfg) -> Dict[str, bool]:
    """Which ClientState blocks this config actually materializes
    (zero-size placeholders are untracked). Delegates to round.py's
    own predicates — the one source of truth for which blocks the
    gather/scatter programs carry — so a widened tracking rule there
    can never silently diverge from the store's spill format."""
    return {
        "errors": fround._has_errors(cfg),
        "velocities": fround._has_velocities(cfg),
        "weights": cfg.do_topk_down,
    }


class TierPlan(NamedTuple):
    """One round's tier motion, decided at STAGE time (pure host LRU
    bookkeeping — safe to run one round ahead under Config.pipeline)
    and executed at COMMIT time against the then-current device block
    (the victim values captured are post-scatter-back of every
    earlier round)."""
    slots: np.ndarray                 # [W] int32 device slot per cohort member
    restores: Tuple[Tuple[int, int], ...]  # (client_id, slot) misses
    spills: Tuple[Tuple[int, int], ...]    # (victim_id, slot) evictions


def _make_spill_writer(max_pending: int = 4,
                       drain_timeout: float = 0.0):
    """The spill queue IS utils/checkpoint.AsyncCheckpointWriter — the
    ISSUE-10 bounded-queue FIFO thread with deferred re-raise at
    submit()/drain(), exactly the contract a correctness-critical
    spill needs (a failed spill LOSES CLIENT STATE, so it must not be
    best-effort like the journal writer). `drain_timeout` is the
    ISSUE-12 watchdog (Config.writer_drain_timeout_s): a hung spill
    fsync raises TimeoutError naming the state-spill writer instead
    of silently hanging flush()/checkpoint drains. Imported lazily: at
    module scope, importing utils.checkpoint from here would re-enter
    a partially-initialized checkpoint module whenever checkpoint
    itself is the import root (checkpoint -> federated package -> api
    -> this module -> checkpoint); by store-construction time every
    module is fully initialized."""
    from commefficient_tpu.utils.checkpoint import AsyncCheckpointWriter
    return AsyncCheckpointWriter(max_pending=max_pending,
                                 drain_timeout=drain_timeout,
                                 name="state-spill")


class _RamTail:
    """Host-RAM long tail: one growable [cap, D] f32 table per tracked
    block + an id->row map. O(clients-ever-evicted) memory — the
    design point: the tail holds what device HBM no longer does."""

    def __init__(self, fields: List[str], D: int):
        self._fields = list(fields)
        self._D = int(D)
        self._rowmap: Dict[int, int] = {}
        self._tables: Dict[str, np.ndarray] = {
            f: np.zeros((0, self._D), np.float32) for f in fields}

    def _grow(self, need: int) -> None:
        have = next(iter(self._tables.values())).shape[0] \
            if self._tables else 0
        if need <= have:
            return
        cap = max(need, have * 2, 64)
        for f in self._fields:
            t = self._tables[f]
            nt = np.zeros((cap, self._D), np.float32)
            nt[:t.shape[0]] = t
            self._tables[f] = nt

    def put(self, ids, rows: Dict[str, np.ndarray]) -> None:
        for i, cid in enumerate(int(c) for c in ids):
            row = self._rowmap.get(cid)
            if row is None:
                row = len(self._rowmap)
                self._grow(row + 1)
                self._rowmap[cid] = row
            for f in self._fields:
                self._tables[f][row] = rows[f][i]

    def has(self, cid: int) -> bool:
        return int(cid) in self._rowmap

    def get(self, cid: int) -> Dict[str, np.ndarray]:
        row = self._rowmap[int(cid)]
        return {f: self._tables[f][row] for f in self._fields}

    def get_many(self, ids) -> Dict[str, np.ndarray]:
        """Bulk read — one fancy-indexed copy per field instead of a
        per-client Python loop (checkpoint/resume assemble the whole
        touched population through this)."""
        rows = np.fromiter((self._rowmap[int(c)] for c in ids),
                           np.int64, count=len(ids))
        return {f: self._tables[f][rows] for f in self._fields}

    def ids(self) -> List[int]:
        return sorted(self._rowmap)

    def clear(self) -> None:
        self._rowmap.clear()
        for f in self._fields:
            self._tables[f] = np.zeros((0, self._D), np.float32)

    def close(self) -> None:
        pass


class _DiskTail:
    """Disk-backed long tail (Config.state_spill_dir): one sparse
    [num_clients, D] f32 memmap per tracked block, indexed by client
    id — POSIX sparse files make never-spilled rows free on disk.
    Scratch state: created fresh per run and rebuilt from crows_*
    checkpoint rows on resume (the files carry no cross-run
    authority)."""

    def __init__(self, dirpath: str, fields: List[str],
                 num_clients: int, D: int):
        self._dir = str(dirpath)
        self._fields = list(fields)
        self._present: set = set()
        self._maps: Dict[str, np.ndarray] = {}
        try:
            os.makedirs(dirpath, exist_ok=True)
            for f in fields:
                path = os.path.join(dirpath, f"tail_{f}.npy")
                self._maps[f] = np.lib.format.open_memmap(
                    path, mode="w+", dtype=np.float32,
                    shape=(int(num_clients), int(D)))
        except OSError as e:
            raise self._spill_error(e) from e

    def _spill_error(self, e: OSError) -> OSError:
        """Disk-full/IO failure on the spill tail, made actionable
        (ISSUE 12 satellite): spills are CORRECTNESS — a lost spill
        is lost client state — so the error must fail loud and name
        the knob, not surface as a bare errno from inside numpy."""
        why = ("disk full (ENOSPC)" if e.errno == errno.ENOSPC
               else f"{type(e).__name__}: {e}")
        return OSError(
            e.errno or errno.EIO,
            f"state spill write under --state_spill_dir "
            f"{self._dir!r} failed: {why}. Spilled rows are the "
            "authoritative copy of evicted client state — free space "
            "on (or relocate) --state_spill_dir, or drop the flag to "
            "keep the tail in host RAM.")

    def put(self, ids, rows: Dict[str, np.ndarray]) -> None:
        # Residual risk: these are stores into SPARSE memmap pages, so
        # a filesystem that fills up mid-run can deliver the
        # allocation failure as SIGBUS on first-touch (uncatchable)
        # rather than an OSError — the actionable message below covers
        # creation, flush, and whatever the kernel does surface as
        # errno. Preallocating would close that hole but defeats the
        # sparse tail (disk O(touched rows), the point of this class).
        idx = np.asarray(ids, np.int64)
        try:
            for f in self._fields:
                self._maps[f][idx] = rows[f][:len(idx)]
        except OSError as e:
            raise self._spill_error(e) from e
        self._present.update(int(c) for c in idx)

    def has(self, cid: int) -> bool:
        return int(cid) in self._present

    def get(self, cid: int) -> Dict[str, np.ndarray]:
        return {f: np.array(self._maps[f][int(cid)])
                for f in self._fields}

    def get_many(self, ids) -> Dict[str, np.ndarray]:
        """Bulk read — one fancy-indexed memmap gather per field (the
        kernel batches the page reads) instead of per-client random
        reads."""
        idx = np.asarray(ids, np.int64)
        return {f: np.asarray(self._maps[f][idx], np.float32)
                for f in self._fields}

    def ids(self) -> List[int]:
        return sorted(self._present)

    def clear(self) -> None:
        self._present.clear()

    def close(self) -> None:
        try:
            for m in self._maps.values():
                m.flush()
        except OSError as e:
            raise self._spill_error(e) from e


class TieredStateStore:
    """The host-side conductor of ``state_tier=host`` (module
    docstring). Owned by FedModel; every device op routes through the
    round handle's existing gather/scatter jits, so the two
    state-motion programs stay the only programs touching the
    ClientState blocks."""

    def __init__(self, cfg, mesh, handle, init_weights,
                 num_clients: int):
        self.cfg = cfg
        self.mesh = mesh
        self.handle = handle
        self.num_clients = int(num_clients)
        self.tracked = tracked_fields(cfg)
        self.fields = [f for f in STATE_FIELDS if self.tracked[f]]
        self.D = int(cfg.grad_size)
        n = mesh.shape["clients"]
        # init_client_state pads the working set up to the mesh's
        # clients axis; every padded row is a usable slot
        self.slots = -(-int(cfg.state_working_set) // n) * n
        self._lru: "OrderedDict[int, int]" = OrderedDict()
        self._free: deque = deque(range(self.slots))
        self._tail = (_DiskTail(cfg.state_spill_dir, self.fields,
                                self.num_clients, self.D)
                      if cfg.state_spill_dir
                      else _RamTail(self.fields, self.D))
        # spills in flight: id -> (per-field completer dict, row idx);
        # readable synchronously until the writer commits them to the
        # tail (the lock covers tail + pending, both threads touch)
        self._pending: Dict[int, Tuple[dict, int]] = {}
        # per-row CRC32s of what the tail holds (ISSUE 16): cid ->
        # field -> checksum, recorded at every tail write (spill
        # commit on the writer thread, checkpoint/legacy imports on
        # the staging thread) and verified at every tail read. Guarded
        # by _lock like tail/pending (graftsync SHARED_STATE registry,
        # analysis/domains) — both threads write it.
        self._sums: Dict[int, Dict[str, int]] = {}
        # quarantine events awaiting journal drain (take_quarantine_
        # events): rows whose tail bytes failed verification and were
        # re-initialized from the init base. Same _lock guard — the
        # writer thread never appends today, but the list rides the
        # same tail/sums transaction so the registry keeps it honest.
        self._quarantined: List[dict] = []
        self._lock = threading.Lock()
        self._writer = _make_spill_writer(
            drain_timeout=float(getattr(cfg, "writer_drain_timeout_s",
                                        0.0)))
        # scheduler prefetch cache (working-set-aware prefetch of the
        # next plan's cohort): host rows warmed ahead of their restore
        # — LRU-NEUTRAL by construction, so prefetch timing can never
        # perturb the eviction stream
        self._warm: Dict[int, Dict[str, np.ndarray]] = {}
        # clients-ever-resident, maintained incrementally: the tail
        # never drops ids and every client enters the LRU before it
        # can reach pending/tail, so this set always equals
        # lru ∪ pending ∪ tail. snapshot_tier() runs at EVERY span
        # boundary under --pipeline — recomputing the union there
        # would sort the whole touched population per span. The
        # sorted copy is cached and invalidated only when a
        # never-seen client becomes resident. Staging-thread-only.
        self._ever: set = set()
        self._ever_sorted: Optional[np.ndarray] = None
        self._init_weights = (np.asarray(init_weights, np.float32)
                              if cfg.do_topk_down else None)
        # telemetry counters (journal `state_tier` events read the
        # deltas via take_journal_fields)
        self.hits = 0
        self.misses = 0
        self.spills = 0
        self.restores = 0
        self.spill_bytes = 0
        self.restore_bytes = 0
        self.quarantines = 0
        self._emitted = {"hits": 0, "misses": 0, "spills": 0,
                         "restores": 0, "spill_bytes": 0,
                         "restore_bytes": 0, "quarantines": 0}

    # ---------------- planning (stage time, pure host) -------------------
    def plan_round(self, client_ids,
                   pinned: Optional[set] = None) -> TierPlan:
        """Assign a device slot to every cohort member and advance the
        LRU: residents are hits, misses take a free slot or evict the
        least-recently-used non-pinned client. Deterministic in the
        cohort-id stream alone. `pinned` protects ids a surrounding
        span still needs resident (plan_span)."""
        ids = np.asarray(client_ids, np.int64).reshape(-1)
        pin = {int(c) for c in ids}
        if pinned:
            pin |= {int(c) for c in pinned}
        slots = np.empty(len(ids), np.int32)
        restores: List[Tuple[int, int]] = []
        spills: List[Tuple[int, int]] = []
        for i, cid in enumerate(int(c) for c in ids):
            slot = self._lru.get(cid)
            if slot is not None:
                self._lru.move_to_end(cid)
                slots[i] = slot
                self.hits += 1
                continue
            self.misses += 1
            if self._free:
                slot = self._free.popleft()
            else:
                victim = next((c for c in self._lru if c not in pin),
                              None)
                if victim is None:
                    raise ValueError(
                        f"state_working_set={self.cfg.state_working_set} "
                        f"({self.slots} slots) cannot hold the "
                        f"{len(pin)} distinct clients this "
                        "round/span needs resident at once — raise "
                        "--state_working_set or (scanned path) lower "
                        "--scan_span")
                slot = self._lru.pop(victim)
                spills.append((victim, slot))
                self.spills += 1
            if cid not in self._ever:
                self._ever.add(cid)
                self._ever_sorted = None
            self._lru[cid] = slot
            restores.append((cid, slot))
            self.restores += 1
            slots[i] = slot
        return TierPlan(slots, tuple(restores), tuple(spills))

    def plan_span(self, ids_rounds) -> List[TierPlan]:
        """Per-round plans for one scanned span ([N, W] cohort ids).
        The span executes as ONE device program with the block on the
        scan carry and every restore happens BEFORE dispatch, so all
        the span's distinct clients must be simultaneously resident:
        every round's plan pins the whole span's ids — an earlier
        round's slot must not be reassigned by a later round's
        restore (the gather inside the scan would read the wrong
        row), and a too-small working set raises the plan_round error
        above instead of corrupting rows."""
        ids_rounds = np.asarray(ids_rounds)
        span_ids = {int(c) for row in ids_rounds for c in row}
        return [self.plan_round(row, pinned=span_ids)
                for row in ids_rounds]

    # ---------------- execution (commit time, device ops) ----------------
    def execute(self, clients, plan: TierPlan):
        """Run one plan's tier motion against the current device
        block: spill-gathers first (victim values must be captured
        before their slots are overwritten), then restore-scatters.
        Both ride the handle's existing jitted gather/scatter — cache
        hits after the first tiered dispatch. Returns the new block
        (scatter donates the old one under Config.donate_round_state,
        exactly like the post-round scatter-back)."""
        W = int(self.cfg.num_workers)
        # graftscope (ISSUE 13): tier motion as distinct stage spans
        # (one per chunk dispatch) — round/span tags inherit from the
        # caller's tier_motion bracket (federated/api)
        for lo in range(0, len(plan.spills), W):
            with TRACE.span("tier_spill"):
                self._spill_chunk(clients, plan.spills[lo:lo + W], W)
        for lo in range(0, len(plan.restores), W):
            with TRACE.span("tier_restore"):
                clients = self._restore_chunk(
                    clients, plan.restores[lo:lo + W], W)
        return clients

    def _spill_chunk(self, clients, chunk, W: int) -> None:
        idx = np.fromiter((s for _, s in chunk), np.int32,
                          count=len(chunk))
        # pad by repeating the first victim slot: duplicate READS are
        # benign, and the padded rows are dropped host-side
        padded = np.concatenate(
            [idx, np.full(W - len(idx), idx[0], np.int32)])
        placed = mh.globalize(self.mesh, P(), padded)
        rows = self.handle.gather(clients, placed)
        completers = {f: mh.async_gather_host(getattr(rows, f))
                      for f in self.fields}
        # ORDERING, not politeness: when the restore scatter that
        # follows DONATES the block it writes in place, and nothing in
        # its dataflow depends on this gather — without the barrier
        # the in-place write races the gather's read of the same
        # buffer (observed as heap corruption / garbage rows on the
        # CPU thunk runtime). The barrier waits only for the gather's
        # compute; the device->host copy and tail commit stay on the
        # writer thread. When the scatter does NOT donate (donation
        # off, or pipeline+tiered — round.py keeps the block alive for
        # the deferred boundary checkpoint there) no in-place write
        # exists and the barrier would stall pipelined staging on the
        # PREVIOUS span's whole program, so it is skipped.
        if self.handle.scatter_donate_argnums:
            jax.block_until_ready(rows)
        ids = [cid for cid, _ in chunk]
        with self._lock:
            for i, cid in enumerate(ids):
                self._pending[cid] = (completers, i)
                self._warm.pop(cid, None)
        self.spill_bytes += len(ids) * self.D * 4 * len(self.fields)

        def commit():
            host = {f: np.asarray(completers[f]())
                    for f in self.fields}
            rows = {f: host[f][:len(ids)] for f in self.fields}
            # per-row checksums BEFORE the lock (crc32 over host
            # bytes, no shared state touched): the tail write and the
            # sums record then land in one locked transaction, so a
            # concurrent verify can never see a row without its sum
            sums = {cid: {f: _row_crc(rows[f][i])
                          for f in self.fields}
                    for i, cid in enumerate(ids)}
            with self._lock:
                self._tail.put(ids, rows)
                self._sums.update(sums)
                for cid in ids:
                    ent = self._pending.get(cid)
                    if ent is not None and ent[0] is completers:
                        del self._pending[cid]

        self._writer.submit(commit)

    def _init_row(self, field: str) -> np.ndarray:
        """The init-base row a quarantined (or never-seen) client's
        field re-initializes from: zeros, except topk_down weights
        which restore from the init-weights vector."""
        if field == "weights" and self._init_weights is not None:
            return np.array(self._init_weights, np.float32)
        return np.zeros(self.D, np.float32)

    def _verify_tail_bulk(self, ids, rows: dict) -> None:
        """Checksum-verify tail rows (field -> [n, D], copies from a
        get_many) against the sums recorded at spill time. LOCK HELD.
        A mismatching field is QUARANTINED: re-initialized from its
        init base in place, healed back into the tail with a fresh
        sum (so one corruption fires one event, not one per read),
        counted, and queued for the `state_quarantine` journal drain
        (take_quarantine_events). Rows with no recorded sum — a
        legacy import, or a pre-16 resume — verify vacuously:
        unknown-but-loadable, matching the checkpoint manifest's
        missing-finite-bit contract."""
        for i, cid in enumerate(int(c) for c in ids):
            expect = self._sums.get(cid)
            if not expect:
                continue
            bad = [f for f in self.fields
                   if f in expect and _row_crc(rows[f][i]) != expect[f]]
            if not bad:
                continue
            # the three heal-writes below mutate guarded state; the
            # guard is held by EVERY caller (_rows_for, the prefetch
            # warm fill, checkpoint_rows — all call under
            # `with self._lock:`), it just isn't lexical here, which
            # is what SY001 checks
            for f in bad:
                rows[f][i] = self._init_row(f)
                self.quarantines += 1
                self._quarantined.append(  # graftsync: disable=SY001 -- caller holds self._lock
                    {"client": cid, "field": f})
            self._tail.put(  # graftsync: disable=SY001 -- caller holds self._lock
                [cid], {f: rows[f][i][None] for f in self.fields})
            self._sums[cid] = {  # graftsync: disable=SY001 -- caller holds self._lock
                f: _row_crc(rows[f][i]) for f in self.fields}

    def _verify_tail_row(self, cid: int, rows: dict) -> dict:
        """Single-client wrapper over _verify_tail_bulk (LOCK HELD);
        returns verified (possibly re-initialized) rows. Copies first:
        a RAM tail's get() hands back table views, and verification
        must never scribble re-init values through a view before the
        heal-write commits them."""
        stacked = {f: np.array(rows[f], np.float32)[None]
                   for f in self.fields}
        self._verify_tail_bulk([cid], stacked)
        return {f: stacked[f][0] for f in self.fields}

    def _rows_for(self, cid: int) -> dict:
        """The authoritative host-side rows (ALL tracked fields at
        once) for a non-resident client: a still-in-flight spill, the
        prefetch cache, the tail, or the init values for a never-seen
        client. Every source materializes the whole row set per
        client — a tail .get reads one record, a pending spill's
        completers share one gathered block — so fetching per-field
        would multiply that work by len(fields). All f32 round trips
        — restores are bit-exact."""
        with self._lock:
            ent = self._pending.get(cid)
            warm = self._warm.get(cid)
            if ent is None and warm is None and self._tail.has(cid):
                # checksum-verify-before-restore (graftsync ORDERING_
                # EDGES): the tail bytes are validated HERE, before
                # this row can reach the restore scatter below
                return self._verify_tail_row(cid, self._tail.get(cid))
        if ent is not None:
            completers, i = ent
            return {f: np.asarray(completers[f]())[i]
                    for f in self.fields}
        if warm is not None:
            return warm
        zero = np.zeros(self.D, np.float32)
        rows = {f: zero for f in self.fields}
        if "weights" in rows and self._init_weights is not None:
            rows["weights"] = self._init_weights
        return rows

    def _restore_chunk(self, clients, chunk, W: int):
        m = len(chunk)
        idx = np.fromiter((s for _, s in chunk), np.int32, count=m)
        # pad by repeating the FIRST restore's slot AND row: duplicate
        # scatter writes of an identical value are deterministic
        padded = np.concatenate(
            [idx, np.full(W - m, idx[0], np.int32)])
        values = {f: np.empty((W, self.D), np.float32)
                  for f in self.fields}
        for i, (cid, _) in enumerate(chunk):
            rows = self._rows_for(cid)
            for f in self.fields:
                values[f][i] = rows[f]
        for f in self.fields:
            values[f][m:] = values[f][0]
        dummy = np.zeros(W, np.float32)
        cohort = fround.CohortState(
            errors=values.get("errors", dummy),
            velocities=values.get("velocities", dummy),
            weights=values.get("weights", dummy))
        # explicit placement with the gather program's own cohort
        # shardings (round.make_train_fn exposes them on the handle),
        # so the restore hits the same compiled scatter the post-round
        # writeback uses and dispatch stays transfer-guard-clean
        cohort = jax.device_put(cohort, self.handle.cohort_shardings)
        placed = mh.globalize(self.mesh, P(), padded)
        self.restore_bytes += m * self.D * 4 * len(self.fields)
        return self.handle.scatter(clients, placed, cohort)

    # ---------------- scheduler prefetch ---------------------------------
    def prefetch_host_rows(self, client_ids) -> None:
        """Working-set-aware prefetch of an upcoming plan's cohort
        (scheduler.RoundScheduler wires this): warm the HOST side of
        the coming restores — in-flight spill materialization and
        tail reads (a disk-backed tail pages its rows into RAM here)
        — without touching the LRU or the device, so prefetch timing
        can never change the eviction stream or the training bits."""
        for cid in (int(c) for c in np.asarray(client_ids).reshape(-1)):
            if cid in self._lru or cid in self._warm:
                continue
            with self._lock:
                ent = self._pending.get(cid)
                in_tail = ent is None and self._tail.has(cid)
            if ent is not None:
                completers, i = ent
                # materialize the in-flight spill OUTSIDE the lock
                # (the completer blocks on the device->host copy —
                # the SY004 hostage class); only the cache insert
                # needs the guard
                rows = {f: np.array(np.asarray(completers[f]())[i])
                        for f in self.fields}
                with self._lock:
                    self._warm[cid] = rows
            elif in_tail:
                with self._lock:
                    self._warm[cid] = self._verify_tail_row(
                        cid, self._tail.get(cid))
            # never-seen clients restore from init — nothing to warm
        # the cache is consumed by _rows_for and bounded: drop entries
        # once it exceeds a few cohorts' worth (under the guard — the
        # commit thread's _rows_for reads _warm concurrently)
        with self._lock:
            if len(self._warm) > 4 * max(self.cfg.num_workers, 1):
                for cid in list(self._warm)[:len(self._warm) // 2]:
                    del self._warm[cid]

    # ---------------- telemetry ------------------------------------------
    def take_journal_fields(self) -> dict:
        """Delta counters since the last take — the payload of one
        `state_tier` journal event (telemetry/journal.py schema)."""
        totals = {"hits": self.hits, "misses": self.misses,
                  "spills": self.spills, "restores": self.restores,
                  "spill_bytes": self.spill_bytes,
                  "restore_bytes": self.restore_bytes,
                  "quarantines": self.quarantines}
        out = {k: totals[k] - self._emitted[k] for k in totals}
        self._emitted = totals
        out["resident"] = len(self._lru)
        out["working_set"] = self.slots
        return out

    def take_quarantine_events(self) -> List[dict]:
        """Drain the pending quarantine records — one {client, field}
        dict per re-initialized row — for the caller to journal as
        `state_quarantine` events (federated/api does this at the
        same boundary it journals `state_tier` deltas)."""
        with self._lock:
            out, self._quarantined = self._quarantined, []
        return out

    # ---------------- checkpoint round-trip (bit-exact) -------------------
    def snapshot_tier(self) -> dict:
        """Cheap host copies of the tier bookkeeping at one span's
        boundary — the pipelined staging loop captures this right
        after a span's dispatch (training/scanloop take_snapshot), so
        a ONE-SPAN-LATE save builds the payload for the RIGHT span:
        the next span's staging advances the LRU and enqueues new
        spills, but those spills capture rows from this span's result
        block, so the deferred tail reads stay span-consistent."""
        return {
            "lru_ids": np.fromiter(self._lru.keys(), np.int64,
                                   count=len(self._lru)),
            "lru_slots": np.fromiter(self._lru.values(), np.int64,
                                     count=len(self._lru)),
            "touched": np.asarray(self.touched_ids(), np.int64),
        }

    def checkpoint_rows(self, clients, tier: Optional[dict] = None
                        ) -> dict:
        """The crows_* payload under the tiered store (satellite fix:
        O(working set) device work per save). Drains the spill queue
        (the tail is then authoritative for every evicted id), gathers
        ONLY the resident rows from the device block — a padded-256
        slot gather bounded by the working set, never the touched
        population — and reads every evicted row straight from the
        host tail. Also records the LRU recency order + slot map
        (`lru_ids`/`lru_slots`) so a resume replays the exact eviction
        stream. `tier`: an earlier snapshot_tier() dict — the
        pipelined one-span-late save passes the boundary-time
        bookkeeping while `clients` is that boundary's block."""
        self.flush()
        if tier is None:
            tier = self.snapshot_tier()
        lru_ids = np.asarray(tier["lru_ids"], np.int64)
        lru_slots = np.asarray(tier["lru_slots"], np.int64)
        resident = set(int(c) for c in lru_ids)
        evicted = [int(c) for c in np.asarray(tier["touched"])
                   if int(c) not in resident]
        all_ids = np.sort(np.concatenate(
            [lru_ids, np.asarray(evicted, np.int64)])
            if len(lru_ids) or evicted else np.zeros((0,), np.int64))
        payload = {"ids": all_ids,
                   "lru_ids": lru_ids, "lru_slots": lru_slots}
        if self._init_weights is not None:
            payload["base_weights"] = self._init_weights
        device_rows: Dict[str, np.ndarray] = {}
        if len(lru_ids):
            padded = np.pad(lru_slots.astype(np.int32),
                            (0, (-len(lru_slots)) % 256), mode="edge")
            gidx = mh.globalize(self.mesh, P(), padded)
            for f in self.fields:
                block = getattr(clients, f)
                device_rows[f] = np.asarray(
                    mh.gather_host(block[gidx]))[:len(lru_ids)]
        # vectorized assembly — the touched population is the design
        # point (~1e6 ids), so the merge must be fancy-indexed numpy,
        # not a per-client Python loop, and the tail is read in ONE
        # bulk get per field (the lock is held only for that read,
        # not a per-client comprehension that would stall the writer)
        res_mask = np.isin(all_ids, lru_ids)
        pos_in_lru = {int(c): i for i, c in enumerate(lru_ids)}
        res_pos = np.fromiter(
            (pos_in_lru[int(c)] for c in all_ids[res_mask]),
            np.int64, count=int(res_mask.sum()))
        evicted_sel = all_ids[~res_mask]
        with self._lock:
            if len(evicted_sel):
                tail_rows = self._tail.get_many(evicted_sel)
                # the checkpoint payload must carry VERIFIED rows — a
                # corrupt tail row is quarantined here, not persisted
                self._verify_tail_bulk(evicted_sel, tail_rows)
            else:
                tail_rows = {f: np.zeros((0, self.D), np.float32)
                             for f in self.fields}
        empty = np.zeros((0,), np.float32)
        for name in STATE_FIELDS:
            if name not in self.fields:
                payload[name] = empty
                continue
            out = np.empty((len(all_ids), self.D), np.float32)
            if len(res_pos):
                out[res_mask] = device_rows[name][res_pos]
            out[~res_mask] = tail_rows[name]
            payload[name] = out
        return payload

    def load_rows(self, clients, rows: dict):
        """Rebuild the tiers from a crows_* checkpoint payload:
        resident rows scatter back into their recorded slots (the
        same eviction stream then replays), everything else lands in
        the host tail. A payload without lru_* keys — written by a
        state_tier=device run — restores with a COLD working set
        (all rows in the tail), which is still bit-exact: tier
        residency never changes row values. Returns the new device
        block."""
        import jax.numpy as jnp

        self._reset()
        ids = np.asarray(rows["ids"], np.int64).reshape(-1)
        self._ever = set(int(c) for c in ids)
        self._ever_sorted = None
        lru_ids = np.asarray(rows.get("lru_ids", ()),
                             np.int64).reshape(-1)
        lru_slots = np.asarray(rows.get("lru_slots", ()),
                               np.int64).reshape(-1)
        compatible = (len(lru_ids) == len(lru_slots)
                      and len(lru_ids) <= self.slots
                      and (len(lru_slots) == 0
                           or int(lru_slots.max()) < self.slots))
        if not compatible:
            # a resume under a different --state_working_set: cold
            # working set, rows all in the tail — values unchanged
            lru_ids = np.zeros((0,), np.int64)
            lru_slots = np.zeros((0,), np.int64)
        pos = {int(c): j for j, c in enumerate(ids)}
        field_rows = {name: np.asarray(rows.get(name, ()), np.float32)
                      for name in self.fields}
        # vectorized: the payload rows are in `ids` order, so the tail
        # entries are one mask + fancy-index per field — resume over a
        # million-client payload must not loop per row in Python
        tail_mask = ~np.isin(ids, lru_ids)
        if tail_mask.any():
            tail_ids = ids[tail_mask]
            tail_vals = {name: field_rows[name][tail_mask]
                         for name in self.fields}
            sums = {int(cid): {f: _row_crc(tail_vals[f][i])
                               for f in self.fields}
                    for i, cid in enumerate(tail_ids)}
            with self._lock:
                self._tail.put(tail_ids, tail_vals)
                self._sums.update(sums)
        for cid, slot in zip(lru_ids, lru_slots):
            self._lru[int(cid)] = int(slot)
        used = set(self._lru.values())
        self._free = deque(s for s in range(self.slots)
                           if s not in used)
        if len(lru_ids):
            gidx = jnp.asarray(lru_slots.astype(np.int32))
            new = clients
            for name in self.fields:
                data = np.stack([field_rows[name][pos[int(c)]]
                                 for c in lru_ids])
                field = getattr(new, name)
                placed = mh.globalize(self.mesh, P(), data)
                new = new._replace(
                    **{name: field.at[gidx].set(placed)})
            clients = new
        return clients

    def import_dense(self, dense_rows: Dict[str, np.ndarray]):
        """Legacy dense checkpoint (client_* blocks) into the tiered
        store: every row differing from its init value goes to the
        host tail (a vectorized diff recovers the touched set the
        dense format never recorded), the working set starts cold.
        `dense_rows` maps tracked field -> host [rows, D] block."""
        self._reset()
        n = min(self.num_clients,
                *(dense_rows[f].shape[0] for f in self.fields))
        diff = np.zeros(n, bool)
        for f in self.fields:
            block = np.asarray(dense_rows[f][:n], np.float32)
            init = (self._init_weights if f == "weights"
                    and self._init_weights is not None
                    else np.zeros(self.D, np.float32))
            diff |= (block != init[None, :]).any(axis=1)
        touched = np.nonzero(diff)[0]
        if len(touched):
            vals = {f: np.asarray(dense_rows[f][touched], np.float32)
                    for f in self.fields}
            sums = {int(cid): {f: _row_crc(vals[f][i])
                               for f in self.fields}
                    for i, cid in enumerate(touched)}
            with self._lock:
                self._tail.put(touched, vals)
                self._sums.update(sums)
        self._ever = set(int(c) for c in touched)
        self._ever_sorted = None
        return [int(c) for c in touched]

    def set_init_weights(self, vec: Optional[np.ndarray]) -> None:
        """Rebase the init-weights row untouched topk_down clients
        restore from (load_state installs the checkpoint's saved
        base)."""
        if self.cfg.do_topk_down and vec is not None:
            self._init_weights = np.asarray(vec, np.float32)

    def touched_ids(self) -> np.ndarray:
        """Every client whose row may differ from init: residents plus
        the spilled tail (pending spills are already in the LRU-exit
        path — flush before reading for checkpoint purposes). Served
        from the incrementally-maintained `_ever` set (== the live
        lru ∪ pending ∪ tail union; see __init__), cached sorted —
        snapshot_tier() calls this at every pipelined span boundary,
        where re-sorting the touched population each time would stall
        staging."""
        if self._ever_sorted is None:
            self._ever_sorted = np.fromiter(
                sorted(self._ever), np.int64, count=len(self._ever))
        return self._ever_sorted

    def _reset(self) -> None:
        self.flush()
        self._lru.clear()
        self._free = deque(range(self.slots))
        self._ever = set()
        self._ever_sorted = None
        with self._lock:
            self._tail.clear()
            self._pending.clear()
            self._warm.clear()
            self._sums.clear()

    # ---------------- lifecycle ------------------------------------------
    def flush(self) -> None:
        """Block until every queued spill is committed to the tail
        (checkpoint payloads and crash paths call this); re-raises
        writer-side failures."""
        self._writer.drain()

    def close(self) -> None:
        self._writer.close()
        self._tail.close()

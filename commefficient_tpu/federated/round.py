"""The federated round engine: one round = one jitted SPMD program.

This module is the TPU-native fusion of the reference's entire process
topology (reference: CommEfficient/fed_aggregator.py:213-335 `_call_train`
+ fed_worker.py:14-138 `worker_loop` + fed_aggregator.py:429-458
`FedOptimizer.step`). The reference needs three communication planes —
multiprocessing queues for batch dispatch, POSIX shared memory for PS
weights and per-client state, and a NCCL sum-reduce of the compressed
update (SURVEY.md §1). Here all three collapse into one `shard_map`
over the `clients` mesh axis:

  * batch dispatch        -> sharded batch arrays, P('clients')
  * shared-memory weights -> replicated ps_weights operand, P()
  * NCCL reduce           -> `lax.psum` of the compressed quantity

Per-client persistent state (errors/velocities/stale weights,
reference fed_aggregator.py:105-129) lives as [padded_population, ...]
device arrays sharded `P('clients', None)` across hosts. Since ISSUE 9
the participant-row motion happens OUTSIDE the jitted round: a
dedicated cohort-GATHER program pulls the sampled rows into a
[num_workers, ...] CohortState before dispatch, and a SCATTER-BACK
program writes the updated rows after — so the three traced round
programs see only O(cohort) operands, never a population-shaped
buffer (graftaudit AU004 now hard-errors on one), and device traffic
per round is O(active) regardless of the population size. The
gather/scatter pair (SURVEY.md hard part #3) are the only two
programs allowed to touch the [population, D] blocks.

True-top-k momentum factor masking of client velocities — broken in
the reference via an unset global (SURVEY.md §7.4 D6) — is just data
flow here: the server helper returns a mask, and the round engine
applies it to the participating rows in the same jitted program.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from commefficient_tpu.parallel.compat import pcast, shard_map

from commefficient_tpu import compress
from commefficient_tpu.config import Config
from commefficient_tpu.federated import client as fclient
from commefficient_tpu.federated import server as fserver
from commefficient_tpu.ops.flat import masked_topk
from commefficient_tpu.telemetry import metrics as tmetrics
from commefficient_tpu.telemetry.trace import TRACE


class ServerState(NamedTuple):
    """All PS-side mutable state (reference globals g_ps_weights /
    FedOptimizer.Vvelocity / .Verror, fed_aggregator.py:37-44,408-409)."""
    ps_weights: jax.Array        # [D] replicated
    Vvelocity: jax.Array         # [D] or [r, c]
    Verror: jax.Array            # [D] or [r, c]
    round_idx: jax.Array         # scalar int32


class ClientState(NamedTuple):
    """Per-client persistent state, [padded_population, ...] rows
    (reference shared-memory arrays at fed_aggregator.py:105-129),
    sharded over the mesh's clients axis (CLIENT_STATE_RULES). Fields
    are zero-size placeholders when the config doesn't need them.

    The jitted round NEVER takes this treedef as an operand: only the
    cohort-gather and scatter-back state-motion programs touch it
    (module docstring; graftaudit AU004 enforces the contract).

    Under `Config.state_tier=host` (ISSUE 11) the same treedef holds
    the bounded [working_set, ...] device block instead — rows are
    indexed by LRU slot, not client id, and the cold tail lives on
    the host (federated/statestore.py; client_state_rows picks the
    allocation size)."""
    errors: jax.Array            # [padded_population, D] or [0]
    velocities: jax.Array        # [padded_population, D] or [0]
    weights: jax.Array           # [padded_population, D] or [0]


class CohortState(NamedTuple):
    """The gathered participant rows one round operates on —
    [num_workers, D] per tracked block, or a [num_workers] f32 dummy
    when the config doesn't track that block (the dummies keep the
    shard_map operand count static; they are never read).

    Produced by the cohort-gather program, consumed and returned
    (merged: dropped clients keep their gathered values) by the jitted
    round, written back by the scatter-back program. O(cohort) in every
    dimension — this treedef is what makes the round programs
    population-free."""
    errors: jax.Array            # [num_workers, D] or [num_workers]
    velocities: jax.Array        # [num_workers, D] or [num_workers]
    weights: jax.Array           # [num_workers, D] or [num_workers]


# partition rules for the persistent client-state blocks — the
# match_partition_rules pattern (SNIPPETS.md [1], parallel/multihost)
# applied to the one treedef that matters at population scale: every
# live [padded_population, D] row block shards over the clients axis,
# placeholders/scalars replicate (the helper's ndim guard).
CLIENT_STATE_RULES = (
    (r"\.(errors|velocities|weights)$", P("clients", None)),
)


def client_state_specs(state) -> "ClientState":
    """PartitionSpec tree for a ClientState (or any same-treedef value)
    via CLIENT_STATE_RULES."""
    from commefficient_tpu.parallel import multihost as mh
    return mh.match_partition_rules(CLIENT_STATE_RULES, state)


class RoundBatch(NamedTuple):
    """One round's input: `num_workers` participating clients, each
    with a padded local batch (static shapes; SURVEY.md §7.3 #2).

    survivors: optional [num_workers] f32 {0,1} mask — 0 marks a
    sampled client that FAILED to complete the round (client dropout,
    Config.client_dropout / utils.faults). Dropped clients contribute
    nothing to the aggregate (survivor-count reweighting), their
    persistent state rows are written back bit-untouched, and a
    zero-survivor round leaves ps_weights/Vvelocity/Verror bit-exact
    (only round_idx advances, so the PRNG stream moves on). None —
    the default, and the only treedef dropout-free callers ever build
    — traces the original mask-free program: dropout machinery is
    free when disabled.

    work: optional [num_workers] f32 work fractions in (0, 1] —
    stragglers (Config.straggler_* / utils.faults). A client with
    fraction f completes only its first ceil(f * valid) examples
    (single-step modes) or ceil(f * steps) local SGD steps (fedavg);
    the aggregate weights by examples actually processed, so partial
    work doesn't bias the average (FedNova-style). None — the default
    — traces the work-free program (the surv-only dropout program or
    the original mask-free one), so straggler machinery is free when
    disabled. Below-cutoff fractions never appear here: the host
    (api._faults_for_round) degrades them to dropout and re-normalizes
    an all-ones work vector back to None.

    poison: optional [num_workers] f32 {0,1} — value-fault injection
    (ISSUE 16, Config.poison_rate / utils.faults.FaultSchedule.
    poison). A flagged client's TRANSMITTED update is corrupted
    device-side per Config.poison_kind after its local compute (its
    losses and persistent state rows stay clean — only the wire is
    poisoned). Presence of this operand selects the SCREENED program
    family: the host supplies it (zeros-filled) whenever screening or
    poisoning is configured, together with a survivors operand
    (ones-filled) and the `screen` flag below. None — the default —
    keeps the three original programs byte-identical.

    screen: optional scalar f32 {0,1} — whether the in-round
    admission screen APPLIES this round. Traced as data (not static
    config) so the finite-frontier rollback can force screening on
    for Config.rollback_screen_rounds without retracing, and a
    poison-only run (screen 0) lets the corruption through to the
    server state — the injection path the numeric-trip drill
    exercises. Rides if-and-only-if `poison` does."""
    client_ids: jax.Array        # [num_workers] int32
    data: Tuple[jax.Array, ...]  # pytree of [num_workers, B, ...]
    mask: jax.Array              # [num_workers, B] f32 validity
    survivors: Optional[jax.Array] = None  # [num_workers] f32 or None
    work: Optional[jax.Array] = None       # [num_workers] f32 or None
    poison: Optional[jax.Array] = None     # [num_workers] f32 or None
    screen: Optional[jax.Array] = None     # scalar f32 or None


class RoundMetrics(NamedTuple):
    """Per-round outputs that are NOT training state. `telemetry` is
    the fixed-shape named f32 vector of telemetry/metrics.METRIC_NAMES
    (zero-size when Config.telemetry is off, so the treedef per config
    is stable) — pure observation computed from values the round
    already produced; it feeds nothing back, so ServerState is
    bit-identical with telemetry on or off.

    admitted: the EFFECTIVE survivor mask after in-round admission
    ([num_workers] f32 {0,1}; screened-family programs only, None —
    no new leaves — everywhere else). host survivors x device admit:
    the mask accounting and the journal must see so a screened client
    is charged exactly like a dropped one (federated/api reads it
    back at commit/collect time).

    contributors (ISSUE 17, robust aggregators only): the subset of
    `admitted` whose values actually reached the robust aggregate —
    a client β-trimmed out of EVERY cell is admitted but contributes
    nothing, and the accountant must not bill upload bytes for it
    (screened==dropped bit-exactness extended to bytes). Identical
    to `admitted` for coord_median/norm_clip (every admitted client
    is order-statistic / clipped-sum material).

    agg_stats (robust aggregators only): [4] f32 —
    (clients trimmed per cell on average, clients norm-clipped,
    l2 residual of robust-vs-mean aggregate, contributing clients) —
    the per-round `aggregator` journal event's payload; the residual
    is the attack-severity gauge (large when the mean is being
    dragged somewhere the order statistics refuse to follow)."""
    losses: jax.Array            # [num_workers] per-client mean loss
    metrics: Tuple[jax.Array, ...]  # per-client means, each [num_workers]
    num_examples: jax.Array      # [num_workers]
    telemetry: jax.Array = None  # [telemetry.metrics.NUM_METRICS] or [0]
    admitted: Optional[jax.Array] = None  # [num_workers] f32 or None
    contributors: Optional[jax.Array] = None  # [num_workers] f32 or None
    agg_stats: Optional[jax.Array] = None     # [4] f32 or None


def init_server_state(cfg: Config, ps_weights: jax.Array,
                      mesh: Optional[Mesh] = None) -> ServerState:
    """Server-state pytree. With a mesh, every field is built as a
    GLOBAL replicated array — required in multi-controller runs, a
    no-op placement in single-process ones (parallel/multihost.py)."""
    shape = cfg.state_shape
    if mesh is None:
        return ServerState(
            ps_weights=ps_weights.astype(jnp.float32),
            Vvelocity=jnp.zeros(shape, jnp.float32),
            Verror=jnp.zeros(shape, jnp.float32),
            round_idx=jnp.zeros((), jnp.int32),
        )
    from commefficient_tpu.parallel import multihost as mh
    return ServerState(
        ps_weights=mh.globalize(
            mesh, P(), jnp.asarray(ps_weights, jnp.float32)),
        Vvelocity=mh.zeros(mesh, P(), shape),
        Verror=mh.zeros(mesh, P(), shape),
        round_idx=mh.globalize(mesh, P(), jnp.zeros((), jnp.int32)),
    )


def init_client_state(cfg: Config, num_clients: int,
                      ps_weights: Optional[jax.Array] = None,
                      mesh: Optional[Mesh] = None) -> ClientState:
    """Allocate per-client state rows (sharded over the mesh's clients
    axis when a mesh is given, since at 17K+ clients these arrays are
    the memory hazard — SURVEY.md §7.0).

    The row count is padded up to a multiple of the mesh axis so any
    num_clients shards (e.g. CIFAR's 10 natural clients on an 8-device
    mesh, which the reference handles with 8 GPU workers too). Padding
    rows are inert: the round engine gathers/scatters participant rows
    by client id, and ids are always < the true num_clients."""
    D = cfg.grad_size
    n = mesh.shape["clients"] if mesh is not None else 1
    rows = -(-num_clients // n) * n

    if mesh is not None:
        from commefficient_tpu.parallel import multihost as mh

        # even the zero-size placeholders must be global arrays in a
        # multi-controller run (every jit operand needs a sharding on
        # the global mesh). One DISTINCT buffer per placeholder field:
        # donation (Config.donate_round_state) marks every leaf of the
        # client-state operand donatable, and XLA rejects the same
        # buffer donated twice.
        def empty():
            return mh.zeros(mesh, P(), (0,))

        def alloc(shape):
            # global sharded allocation: shard-local zeros only — in a
            # multi-controller run no host ever materializes the full
            # [num_clients, D] block
            return mh.zeros(mesh, P("clients", None), shape)
    else:
        def empty():
            return jnp.zeros((0,), jnp.float32)

        def alloc(shape):
            return jnp.zeros(shape, jnp.float32)

    errors = alloc((rows, D)) if _has_errors(cfg) else empty()
    velocities = (alloc((rows, D)) if _has_velocities(cfg)
                  else empty())
    if cfg.do_topk_down:
        assert ps_weights is not None
        if mesh is not None:
            weights = mh.tile_rows(mesh, ps_weights, rows)
        else:
            weights = jnp.broadcast_to(ps_weights, (rows, D)).copy()
    else:
        weights = empty()
    return ClientState(errors, velocities, weights)


# which per-client [population, D] state blocks the config tracks —
# a plugin decision since ISSUE 19 (powersgd repurposes the velocity
# block for its warm-started Q factor); the classic plugins answer
# with the original error_type/local_momentum checks, so default
# allocations are unchanged
def _has_errors(cfg):
    return compress.get_compressor(cfg.mode).has_errors(cfg)


def _has_velocities(cfg):
    return compress.get_compressor(cfg.mode).has_velocities(cfg)


def client_state_rows(cfg: Config, num_clients: int) -> int:
    """How many client rows this config's ClientState blocks are
    allocated for: the full population under the default
    `state_tier=device`, or the bounded LRU working set under
    `state_tier=host` (ISSUE 11) — the blocks then hold only
    recently-active clients' rows while the cold tail lives on the
    host (federated/statestore.py), and the SAME gather/scatter
    state-motion programs move rows by device SLOT index instead of
    global client id. Every allocator of a ClientState (FedModel, the
    audit tiers, bench sweeps) routes through this so the audited
    gather/scatter programs are the dispatched ones."""
    if cfg.state_tier != "device":
        return int(cfg.state_working_set)
    return int(num_clients)


# ---------------------------------------------------------------------------
# program registry: the contract surface graftaudit (analysis/audit)
# traces and checks. Exactly three single-round programs exist per
# config — one per RoundBatch treedef — and the two dispatch entry
# points declare which of their inputs are DEAD after dispatch (safe
# to donate: the caller never reads them again).

# the three traced round programs, in the order the fault machinery
# grows them (ROADMAP invariant; analysis/runtime.assert_program_count
# proves the count dynamically, graftaudit walks each one statically).
# Since ISSUE 9 each round program operates on CohortState rows; the
# cohort-gather and scatter-back STATE-MOTION programs compile once
# per config alongside them (STATE_MOTION_PROGRAMS) and are the only
# programs whose operands may carry the population dimension.
PROGRAM_VARIANTS = ("mask_free", "dropout", "dropout_stragglers")

# ISSUE 16 screened family: when value-fault screening OR poison
# injection is configured the host supplies the survivor mask
# (ones-filled), a poison mask (zeros-filled), and the traced
# screen-enable scalar on EVERY dispatch, so exactly two programs
# exist — screened, and screened+stragglers — and the per-round
# decision "does the admission screen apply" is data, never a
# retrace. Default configs never build this treedef, keeping the
# three programs above byte-identical. ISSUE 17 extends the family
# (same two variant NAMES, config-keyed program bodies): byzantine
# adversaries ride the poison operand with an ATTACK transform
# instead of a corruption kind, robust aggregators replace the
# psum-mean tail with in-round order statistics over the gathered
# client tables, and under adaptive screening the screen scalar's
# VALUE is the live norm multiplier — all static config branches, so
# a PR-16 screened config still traces its exact pre-17 programs.
SCREENED_PROGRAM_VARIANTS = ("screened", "screened_stragglers")

# multiplier applied by the "scale" poison kind: large enough that a
# single poisoned client blows past any sane norm screen and (through
# error feedback) trips the finite/driver telemetry watch, small
# enough to stay finite in f32 so the norm screen (not just the
# finite screen) is what catches it.
POISON_SCALE = 2.0 ** 40

# the two state-motion programs every TrainRound dispatch brackets the
# round program with (compiled once; cache hits thereafter)
STATE_MOTION_PROGRAMS = ("gather", "scatter")

# per-round dispatch (TrainRound.__call__, three programs): the
# gathered CohortState is dead after the round program — the caller
# scatters the RETURNED rows — and the full ClientState is dead after
# scatter-back (the caller reassigns it from the result), so both are
# donated. ServerState is NOT: _call_train reads the previous
# ps_weights AFTER dispatch for the one-round-lagged accounting bitset,
# so donating it would hand accounting a deleted buffer. graftaudit's
# donation audit uses exactly these declarations.
ROUND_DEAD_ARGNUMS = (1,)      # round program: the CohortState operand
SCATTER_DEAD_ARGNUMS = (0,)    # scatter-back: the full ClientState
# scanned-span dispatch (TrainRound.train_rounds): both state operands
# are dead — run_rounds computes the change bitset INSIDE the span and
# assigns all state from the result.
SPAN_DEAD_ARGNUMS = (0, 1)


def screened_family(cfg: Config) -> bool:
    """Whether `cfg` steady-state dispatches the SCREENED program
    family (in-round admission, value-fault injection, byzantine
    adversaries, or a robust aggregator configured — the latter two
    because attacks ride the poison operand and the robust reductions
    need the per-client transmits plus the admission mask, so both
    always take the per-client screened path). A default config can
    still dispatch screened programs transiently — the finite-frontier
    rollback force-enables screening for a bounded window — but its
    audited steady-state program set is the three defaults."""
    return (cfg.update_screen != "off" or cfg.poison_rate > 0
            or cfg.byzantine_rate > 0 or cfg.robust_aggregation)


def program_variants_for(cfg: Config) -> tuple:
    """The steady-state traced round-program set for `cfg` — the
    contract surface graftaudit/graftmesh walk and the program-count
    pins assert."""
    return (SCREENED_PROGRAM_VARIANTS if screened_family(cfg)
            else PROGRAM_VARIANTS)


def program_variant(batch: RoundBatch) -> str:
    """Which traced program `batch`'s treedef selects."""
    if batch.poison is not None:
        return ("screened_stragglers" if batch.work is not None
                else "screened")
    if batch.work is not None:
        return "dropout_stragglers"
    if batch.survivors is not None:
        return "dropout"
    return "mask_free"


def audit_batch_variants(batch: RoundBatch,
                         cfg: Optional[Config] = None) -> dict:
    """The RoundBatch treedef variants derived from one concrete
    batch — the exact programs a run with the config's fault machinery
    enabled dispatches: the three default programs, or (when `cfg` is
    given and selects the screened family) the two screened ones.
    Survivor/work/poison operands are inert values (all-survive,
    half-work, poison-nobody, screen-on) chosen only to pin the
    treedef; graftaudit traces each variant abstractly so the values
    never execute."""
    ones = jnp.ones(batch.client_ids.shape[0], jnp.float32)
    if cfg is not None and screened_family(cfg):
        zeros = jnp.zeros_like(ones)
        on = jnp.ones((), jnp.float32)
        return {
            "screened": batch._replace(
                survivors=ones, work=None, poison=zeros, screen=on),
            "screened_stragglers": batch._replace(
                survivors=ones, work=ones * 0.5, poison=zeros,
                screen=on),
        }
    return {
        "mask_free": batch._replace(survivors=None, work=None,
                                    poison=None, screen=None),
        "dropout": batch._replace(survivors=ones, work=None,
                                  poison=None, screen=None),
        "dropout_stragglers": batch._replace(survivors=ones,
                                             work=ones * 0.5,
                                             poison=None, screen=None),
    }


def stack_batch_for_span(batch: RoundBatch, n_rounds: int) -> RoundBatch:
    """A scanned-span RoundBatch from one single-round batch: every
    field gains a leading [n_rounds] axis carrying the same round
    repeated — the treedef `train_rounds` dispatches. Audit hook
    (graftaudit/graftmesh trace the span program through this;
    FedModel.trace_round_programs(include_span=True) is the
    real-workload surface): values never execute, only the
    shapes/treedef matter."""
    def stack(x):
        return None if x is None else jnp.stack([x] * n_rounds)
    return RoundBatch(
        stack(batch.client_ids),
        jax.tree.map(stack, batch.data),
        stack(batch.mask),
        stack(batch.survivors),
        stack(batch.work),
        stack(batch.poison),
        stack(batch.screen))


def make_round_fns(loss_fn: fclient.LossFn, unravel: Callable,
                   cfg: Config, mesh: Mesh, grad_mask=None):
    """Build the jitted (train-round, eval) pair. Thin wrapper over the
    split factories below, kept for callers that want both from one
    loss function."""
    return (make_train_fn(loss_fn, unravel, cfg, mesh, grad_mask),
            make_eval_fn(loss_fn, unravel, cfg, mesh))


def make_train_fn(loss_fn: fclient.LossFn, unravel: Callable,
                  cfg: Config, mesh: Mesh, grad_mask=None):
    """Build the jitted train-round function.

    loss_fn(params_pytree, batch_tuple, mask) -> (loss, metrics_tuple)
    is the workload callback — the API contract preserved from the
    reference (SURVEY.md §3.5): FedModel(model, compute_loss, args).

    grad_mask: optional [D] f32 mask multiplied into every client
    gradient *before* compression — frozen (finetune-transferred)
    coordinates are zeroed at the source, so they consume no k-budget
    or sketch capacity. This matches the reference's freezing
    semantics, where requires_grad=False params never produce
    gradients at all (cv_train.py:377-384).
    """
    cfg.validate()
    # the fused path produces a dense shard gradient sum; in sketch
    # mode the shared aggregation tail must therefore be the one to
    # encode it. Today fused_client_backward's gate is a strict subset
    # of defer_sketch_encode's — this assert keeps that implication
    # from silently breaking if either gate gains a condition.
    if cfg.fused_client_backward and cfg.mode == "sketch":
        assert cfg.defer_sketch_encode, (
            "fused_client_backward requires defer_sketch_encode in "
            "sketch mode (dense shard sum must be encoded in the "
            "shared tail)")
    flat_grad = fclient.make_flat_grad_fn(
        loss_fn, unravel,
        compute_dtype=jnp.bfloat16 if cfg.do_bf16 else None)
    flat_loss = (fclient.make_flat_loss_fn(
        loss_fn, unravel,
        compute_dtype=jnp.bfloat16 if cfg.do_bf16 else None)
        if cfg.fused_client_backward else None)
    if grad_mask is not None:
        grad_mask = jnp.asarray(grad_mask, jnp.float32)
    # clients sharded over the `clients` axis only — further axes
    # (tensor-parallel `model`) don't divide the client population
    n_shards = mesh.shape["clients"]
    # the mode's Compressor plugin (ISSUE 19) — static config,
    # resolved once per traced-program family
    comp = compress.get_compressor(cfg.mode)

    # ---------------- per-shard client phase ----------------------------
    def shard_train(ps_weights, data, mask, err_rows, vel_rows, w_rows,
                    keys, lr, surv=None, work=None, pois=None,
                    screen=None):
        """Runs on one shard: simulate W = num_workers/n_shards clients
        (vmap), locally sum their compressed updates, psum across the
        clients axis (the reference's per-GPU client loop
        fed_worker.py:60-131 + NCCL reduce :138).

        surv: optional [W_shard] f32 survivor mask — a dropped client's
        transmit and example count are zeroed BEFORE the local sum, so
        the psum'd aggregate and its divide-by-total reweighting see
        survivors only. Its per-client loss/metric rows are still
        reported (simulation diagnostics), but num_examples is zeroed
        so count-weighted consumers exclude it.

        work: optional [W_shard] f32 work fractions (stragglers). For
        the single-local-step modes the fraction truncates the
        client's VALIDITY MASK to its first ceil(f * valid) examples
        before any compute — its mean gradient, its example count,
        and therefore its weight in the psum'd aggregate all reflect
        examples actually processed (the divide-by-total below is
        then exactly the FedNova-style processed-example reweighting).
        For fedavg the fraction is a completed-steps budget applied
        inside fedavg_step instead (truncating the dataset would
        change WHICH examples every epoch sees, not how far local
        training got).

        pois/screen (ISSUE 16, screened family only — ride together):
        pois is the [W_shard] f32 {0,1} value-fault mask; a flagged
        client's TRANSMIT is corrupted per Config.poison_kind after
        its local compute, so losses/metrics/state rows stay clean.
        screen is the traced scalar admission flag: when > 0 the
        per-client admit mask (finite check over every transmit leaf,
        plus the cohort-median norm-outlier check under
        update_screen=norm) multiplies into the survivor mask BEFORE
        aggregation — a screened client takes the dropped-client path
        exactly. When 0 the admit mask is computed but NOT applied,
        so injected corruption reaches the server (the rollback
        drill's trip path). NaN-safety: screened-family aggregation
        zeroes excluded clients with `where`, never multiplication
        (NaN * 0 is NaN)."""
        # Cast the replicated weights to shard-varying before any
        # jax.grad: differentiating w.r.t. an *unvarying* operand under
        # shard_map makes JAX psum the cotangent across shards (correct
        # for grad-through-shard_map, wrong here — each client needs its
        # own local gradient, not the cross-client sum).
        ps_weights = pcast(ps_weights, "clients", to="varying")

        if work is not None and not comp.local_sgd:
            # completed-examples budget: keep each client's first
            # ceil(f * valid) valid examples (cumsum walks valid
            # examples in order, so padding rows stay excluded and a
            # straggler's partial batch is a prefix — the examples it
            # got through before the deadline)
            def budget(m, f):
                kept = jnp.cumsum(m) <= jnp.ceil(f * m.sum())
                return m * kept.astype(m.dtype)
            mask = jax.vmap(budget)(mask, work)

        def one_client(cdata, cmask, err, vel, w_stale, key, cwork=None):
            if cfg.do_topk_down:
                # download compression: client only receives the top-k
                # of its weight staleness gap (fed_worker.py:232-247);
                # down_k decouples the download budget from the
                # upload/server k (Config.down_k)
                diff = ps_weights - w_stale
                weights = w_stale + masked_topk(diff,
                                                k=cfg.down_k or cfg.k)
            else:
                weights = ps_weights

            if comp.local_sgd:
                res = fclient.fedavg_step(
                    flat_grad, weights, cdata, cmask, cfg, lr, key,
                    grad_mask=grad_mask, work=cwork)
            else:
                res = fclient.local_step(
                    flat_grad, weights, cdata, cmask, err, vel, cfg, key,
                    grad_mask=grad_mask)
            new_w = (weights if cfg.do_topk_down
                     else jnp.zeros_like(cmask, shape=()))
            return res, new_w

        # only the client-compute step branches; the encode/psum
        # aggregation tail below is shared, so the fused and
        # per-client paths cannot drift apart. The screened family
        # needs per-client transmits (poison lands on the wire, the
        # admit mask inspects it), so it always takes the per-client
        # path even on fused-eligible configs.
        if cfg.fused_client_backward and pois is None:
            # one backward for the whole shard (gate guarantees
            # equality with the per-client path — Config property and
            # fclient.fused_shard_grads docstrings); survivors weight
            # each client's term of the fused objective, so dropped
            # clients contribute exactly nothing to the shard gradient
            local_sum, losses, metrics, counts = fclient.fused_shard_grads(
                flat_loss, ps_weights, data, mask, cfg,
                grad_mask=grad_mask, survivors=surv)
            dummy = jnp.zeros_like(mask, shape=mask.shape[:1])
            new_err = new_vel = new_w_rows = dummy
        else:
            if work is not None and comp.local_sgd:
                results, new_w_rows = jax.vmap(one_client)(
                    data, mask, err_rows, vel_rows, w_rows, keys, work)
            else:
                results, new_w_rows = jax.vmap(one_client)(
                    data, mask, err_rows, vel_rows, w_rows, keys)
            if pois is not None:
                # ---- screened family (ISSUE 16 / ISSUE 17) ----
                # fault injection first: corrupt flagged clients'
                # transmits. With an all-zero mask every `where`
                # passes the clean value through bit-exactly, so a
                # screened run without live poison computes the
                # identical wire values. Under Config.byzantine_rate
                # the SAME operand carries adversary flags instead
                # (validate() keeps the two mutually exclusive) and
                # the transform is the scripted ATTACK — a static
                # branch, so PR-16 screened programs are untouched.
                def corrupt(t):
                    flag = pois.reshape(
                        pois.shape + (1,) * (t.ndim - 1)) > 0
                    if cfg.poison_kind == "scale":
                        return t * jnp.where(
                            flag, jnp.asarray(POISON_SCALE, t.dtype),
                            jnp.ones((), t.dtype))
                    bad = (jnp.inf if cfg.poison_kind == "inf"
                           else jnp.nan)
                    return jnp.where(flag, jnp.asarray(bad, t.dtype), t)

                def attack(trans):
                    """Byzantine adversary transform (ISSUE 17):
                    flagged clients REPLACE their transmit per
                    Config.attack. sign_flip/scaled are per-client
                    (gradient reversal / magnitude domination — both
                    caught by a norm screen); colluding submits ONE
                    coordinated crafted update — the negated honest
                    mean direction at a 0.9 margin UNDER the norm
                    screen's admission threshold (mult x cohort
                    median; high-norm attackers can only push the
                    cohort median above the honest median, so
                    0.9*mult*med_honest <= mult*med_cohort and the
                    screen provably admits it): finite, norm-
                    plausible, and maximally damaging — the class
                    admission screening provably cannot catch, the
                    negative control that justifies the robust
                    aggregators. little_is_
                    enough stays inside one honest standard deviation
                    per coordinate (Baruch et al.) — mild per-cell,
                    damaging in aggregate. The honest-cohort stats
                    are computed over the all_gathered per-client
                    tables, so every shard crafts the identical
                    update."""
                    leaves, treedef = jax.tree.flatten(trans)
                    W = leaves[0].shape[0]
                    V = jnp.concatenate(
                        [t.reshape(W, -1).astype(jnp.float32)
                         for t in leaves], axis=1)
                    if cfg.attack == "sign_flip":
                        A = -V
                    elif cfg.attack == "scaled":
                        A = V * jnp.float32(100.0)
                    else:
                        allV = jax.lax.all_gather(
                            V, "clients").reshape(-1, V.shape[1])
                        allF = jax.lax.all_gather(
                            pois, "clients").reshape(-1) > 0
                        allS = jax.lax.all_gather(
                            surv, "clients").reshape(-1) > 0
                        honest = ((~allF) & allS
                                  & jnp.isfinite(allV).all(axis=1))
                        nh = jnp.maximum(honest.sum(), 1)
                        hmean = jnp.where(
                            honest[:, None], allV, 0.0).sum(0) / nh
                        if cfg.attack == "little_is_enough":
                            hvar = jnp.where(
                                honest[:, None],
                                jnp.square(allV - hmean[None, :]),
                                0.0).sum(0) / nh
                            crafted = hmean - jnp.sqrt(hvar)
                        else:  # colluding
                            hnorm = jnp.sqrt(jnp.square(allV).sum(1))
                            med = jnp.nanmedian(
                                jnp.where(honest, hnorm, jnp.nan))
                            med = jnp.where(honest.sum() > 0, med,
                                            jnp.float32(1.0))
                            # the admission envelope the adversary
                            # provably fits under (the screen's own
                            # mult expression; >= 1 keeps the attack
                            # meaningful when screening is off)
                            amult = jnp.maximum(
                                (screen if cfg.adaptive_screen
                                 else jnp.float32(
                                     cfg.screen_norm_mult)),
                                jnp.float32(1.0))
                            d = -hmean
                            crafted = d * (
                                jnp.float32(0.9) * amult * med
                                / jnp.maximum(
                                    jnp.sqrt(jnp.square(d).sum()),
                                    jnp.float32(1e-12)))
                        A = jnp.broadcast_to(crafted[None, :], V.shape)
                    out_flat = jnp.where(pois[:, None] > 0, A, V)
                    outs, off = [], 0
                    for t in leaves:
                        n = t[0].size
                        outs.append(out_flat[:, off:off + n].reshape(
                            t.shape).astype(t.dtype))
                        off += n
                    return jax.tree.unflatten(treedef, outs)

                if cfg.byzantine_rate > 0:
                    tx = attack(results.transmit)
                else:
                    tx = jax.tree.map(corrupt, results.transmit)

                # admission screen: per-client finite bit over every
                # transmit leaf ...
                leaves = jax.tree.leaves(tx)
                ok = None
                for t in leaves:
                    fin_t = jnp.isfinite(t).reshape(
                        t.shape[0], -1).all(axis=1)
                    ok = fin_t if ok is None else ok & fin_t
                if cfg.update_screen == "norm":
                    # ... plus the norm-outlier check: update l2
                    # against the COHORT median (all_gather across the
                    # clients axis so every shard sees the same
                    # median). Only surviving, finite, nonzero-l2
                    # clients are eligible median material; a round
                    # with no eligible clients admits everyone rather
                    # than comparing against NaN.
                    l2sq = None
                    for t in leaves:
                        s = jnp.square(t.astype(jnp.float32)).reshape(
                            t.shape[0], -1).sum(axis=1)
                        l2sq = s if l2sq is None else l2sq + s
                    l2 = jnp.sqrt(l2sq)
                    all_l2 = jax.lax.all_gather(
                        l2, "clients").reshape(-1)
                    all_surv = jax.lax.all_gather(
                        surv, "clients").reshape(-1)
                    elig = ((all_surv > 0) & jnp.isfinite(all_l2)
                            & (all_l2 > 0))
                    med = jnp.nanmedian(
                        jnp.where(elig, all_l2, jnp.nan))
                    # adaptive screening (ISSUE 17): the screen
                    # operand's VALUE is the live norm multiplier —
                    # the AdaptiveScreenController's plan-journaled
                    # adjustments reach the traced program as data,
                    # never a retrace. Static branch: non-adaptive
                    # configs trace the exact PR-16 constant.
                    mult = (screen if cfg.adaptive_screen
                            else cfg.screen_norm_mult)
                    norm_ok = jnp.where(
                        elig.sum() > 0, l2 <= mult * med, True)
                    ok = ok & norm_ok
                # the traced enable flag: screen off -> admit mask
                # computed but not applied (corruption flows through
                # to the server state — the trip-drill injection path)
                admit = jnp.where(screen > 0,
                                  ok.astype(jnp.float32), 1.0)
                surv_eff = surv * admit
                counts = results.num_examples * surv_eff
                admitted = surv_eff
                if cfg.robust_aggregation:
                    # ---- robust cross-client reduction (ISSUE 17) --
                    # Order statistics over the gathered per-client
                    # tables replace the psum-mean: per-cell
                    # coordinate-median / β-trimmed-mean, or
                    # norm-clipping-to-cohort-median. Computed in
                    # AGGREGATION SPACE — in sketch mode each client's
                    # transmit is encoded (and wire-quantized)
                    # individually first, so the reduction runs over
                    # [N, r, c] sketch tables exactly as FetchSGD's
                    # linearity suggests; the deferred shard-sum
                    # encode below is bypassed (an order statistic
                    # does not distribute over the sum). Screened or
                    # dropped clients are excluded per cell via
                    # `where` masks (zero-survivor safe, NaN-safe);
                    # ranks are taken on the per-client MEAN updates
                    # (example weights normalize out) while the kept
                    # aggregate stays example-weighted, preserving
                    # the FedNova work-reweighting. (trimmed_mean
                    # with trim_beta == 0.0 never reaches this block:
                    # Config.robust_aggregation strength-reduces it
                    # to the plain mean program, which is the only
                    # way to stay bit-identical under the deferred
                    # shard-sum encode below.)
                    txa = tx
                    if cfg.defer_sketch_encode:
                        txa = jax.vmap(
                            fserver.args2sketch(cfg).encode)(txa)
                    if (cfg.mode == "sketch"
                            and cfg.sketch_table_dtype != "f32"):
                        from commefficient_tpu.ops.kernels import (
                            wire_roundtrip,
                        )
                        txa = wire_roundtrip(txa,
                                             cfg.sketch_table_dtype)
                    leaves_a, treedef_a = jax.tree.flatten(txa)
                    Wl = leaves_a[0].shape[0]
                    V = jnp.concatenate(
                        [t.reshape(Wl, -1).astype(jnp.float32)
                         for t in leaves_a], axis=1)
                    allV = jax.lax.all_gather(
                        V, "clients").reshape(-1, V.shape[1])
                    n_w = jax.lax.all_gather(
                        counts, "clients").reshape(-1)
                    adm = jax.lax.all_gather(
                        surv_eff, "clients").reshape(-1) > 0
                    # per-cell eligibility: admitted AND finite (a
                    # screen-off round may admit NaN/Inf transmits;
                    # order statistics must stay well-defined)
                    E = adm[:, None] & jnp.isfinite(allV)
                    wcol = n_w[:, None]
                    total_w = n_w.sum()
                    # per-client mean updates: the rank/norm material
                    U = allV / jnp.maximum(n_w, 1.0)[:, None]
                    mean_agg = (jnp.where(E, allV, 0.0).sum(0)
                                / jnp.maximum(total_w, 1.0))
                    n_trim = n_clip = jnp.float32(0.0)
                    keep = E
                    if cfg.aggregator == "coord_median":
                        med = jnp.nanmedian(
                            jnp.where(E, U, jnp.nan), axis=0)
                        agg = jnp.where(E.any(axis=0), med, 0.0)
                    elif cfg.aggregator == "trimmed_mean":
                        vals = jnp.where(E, U, jnp.inf)
                        order = jnp.argsort(vals, axis=0)
                        ranks = jnp.argsort(order, axis=0)
                        n_e = E.sum(axis=0)
                        # trim floor(β·n_e) per side, clamped so at
                        # least one value survives per nonempty cell
                        m = jnp.minimum(
                            jnp.floor(cfg.trim_beta
                                      * n_e).astype(jnp.int32),
                            jnp.maximum(n_e - 1, 0) // 2)
                        keep = (E & (ranks >= m[None, :])
                                & (ranks < (n_e - m)[None, :]))
                        ksum = jnp.where(keep, wcol, 0.0).sum(0)
                        agg = (jnp.where(keep, allV, 0.0).sum(0)
                               / jnp.maximum(ksum, 1.0))
                        n_trim = (jnp.where(E & ~keep, 1.0, 0.0).sum()
                                  / jnp.float32(V.shape[1]))
                    else:  # norm_clip
                        l2u = jnp.sqrt(
                            jnp.where(E, jnp.square(U), 0.0).sum(1))
                        elign = adm & (l2u > 0) & jnp.isfinite(l2u)
                        medn = jnp.nanmedian(
                            jnp.where(elign, l2u, jnp.nan))
                        clip = jnp.where(
                            elign & (l2u > medn),
                            medn / jnp.maximum(l2u,
                                               jnp.float32(1e-30)),
                            jnp.float32(1.0))
                        n_clip = (clip < 1.0).sum().astype(jnp.float32)
                        agg = (jnp.where(E, allV * clip[:, None],
                                         0.0).sum(0)
                               / jnp.maximum(total_w, 1.0))
                    resid = jnp.sqrt(jnp.square(agg - mean_agg).sum())
                    contrib_all = (adm & keep.any(axis=1)).astype(
                        jnp.float32)
                    contrib = jax.lax.dynamic_slice_in_dim(
                        contrib_all,
                        jax.lax.axis_index("clients") * Wl, Wl)
                    agg_stats = jnp.stack(
                        [n_trim, n_clip, resid, contrib_all.sum()])
                    outs, off = [], 0
                    for t in leaves_a:
                        n = t[0].size
                        outs.append(agg[off:off + n].reshape(
                            t.shape[1:]).astype(t.dtype))
                        off += n
                    robust_tx = jax.tree.unflatten(treedef_a, outs)
                    local_sum = None
                else:
                    # `where`, NOT multiplication: a poisoned excluded
                    # client's NaN/Inf must become an exact zero in
                    # the local sum (NaN * 0 is NaN) — this is also
                    # what makes a screened client bit-identical to a
                    # dropped one
                    local_sum = jax.tree.map(
                        lambda t: jnp.where(
                            surv_eff.reshape(
                                surv_eff.shape
                                + (1,) * (t.ndim - 1)) > 0,
                            t, jnp.zeros_like(t)).sum(axis=0),
                        tx)
            elif surv is not None:
                # zero dropped clients' uploads BEFORE the local sum —
                # the psum'd aggregate and the divide-by-total see
                # survivors only (survivor-count reweighting)
                local_sum = jax.tree.map(
                    lambda t: (t * surv.reshape(
                        surv.shape + (1,) * (t.ndim - 1))).sum(axis=0),
                    results.transmit)
                counts = results.num_examples * surv
            else:
                local_sum = jax.tree.map(
                    lambda t: t.sum(axis=0), results.transmit)
                counts = results.num_examples
            losses, metrics = results.loss, results.metrics
            new_err, new_vel = results.error, results.velocity

        if pois is not None and cfg.robust_aggregation:
            # robust aggregate (ISSUE 17): already encoded,
            # quantized, normalized and replicated (a pure function
            # of the all_gathered tables — every shard computed the
            # identical value, so no psum is needed); `total` still
            # reports the admitted example mass for the round_step
            # alive gate and telemetry parity
            total = jax.lax.psum(counts.sum(), "clients")
            out = (robust_tx, total, new_err, new_vel, new_w_rows,
                   losses, metrics, counts, admitted, contrib,
                   agg_stats)
            return out
        if cfg.defer_sketch_encode:
            # sketch linearity: encode the per-shard client sum ONCE
            # (clients returned dense gradients; see Config property
            # docstring). The psum below then moves the [r, c] table —
            # upload compression on the wire, exactly like the
            # reference's NCCL reduce of sketch tables.
            local_sum = fserver.args2sketch(cfg).encode(local_sum)
        if cfg.mode == "sketch" and cfg.sketch_table_dtype != "f32":
            # quantized sketch transport (--sketch_table_dtype): the
            # shard's client-sum table rides the wire at bf16/int8 —
            # quantize at the sender, dequantize before the
            # aggregation/decode. wire_roundtrip is the IDENTITY for
            # f32, and the branch itself is static config, so the
            # default traces the exact pre-quantization program. The
            # rounding noise lands in the server's virtual error
            # accumulator like any other compression noise
            # (ops/kernels/quant.py); the accountant bills the wire
            # bytes (Config.upload_bytes).
            from commefficient_tpu.ops.kernels import wire_roundtrip
            local_sum = wire_roundtrip(local_sum,
                                       cfg.sketch_table_dtype)
        transmit = jax.lax.psum(local_sum, "clients")
        total = jax.lax.psum(counts.sum(), "clients")
        out = (transmit, total, new_err, new_vel, new_w_rows,
               losses, metrics, counts)
        if pois is not None:
            # screened programs additionally report the effective
            # (post-admission) survivor mask so the host accounting
            # and journal charge screened clients as dropped ones
            out = out + (admitted,)
        return out

    state_spec = P("clients")

    shard_train_mapped = shard_map(
        shard_train, mesh=mesh,
        in_specs=(P(), P("clients"), P("clients"), P("clients"),
                  P("clients"), P("clients"), P("clients"), P()),
        out_specs=(P(), P(), state_spec, state_spec, state_spec,
                   P("clients"), P("clients"), P("clients")),
        # manual only over `clients`; any further mesh axes (`model`
        # for tensor parallelism) stay AUTO — GSPMD partitions the
        # client computation over them, steered by the workload's
        # with_sharding_constraint calls (parallel/tp.py)
        axis_names=frozenset({"clients"}),
    )

    # dropout variant: same program plus a [W] survivor-mask operand,
    # sharded like every other per-client row. Built as a SEPARATE
    # mapped fn (rather than a ones-mask default operand) so the
    # dropout-free treedef traces the original mask-free program —
    # client_dropout=0.0 stays bit-identical to a build without the
    # feature.
    shard_train_surv_mapped = shard_map(
        shard_train, mesh=mesh,
        in_specs=(P(), P("clients"), P("clients"), P("clients"),
                  P("clients"), P("clients"), P("clients"), P(),
                  P("clients")),
        out_specs=(P(), P(), state_spec, state_spec, state_spec,
                   P("clients"), P("clients"), P("clients")),
        axis_names=frozenset({"clients"}),
    )

    # straggler variant: survivor mask + per-client work fractions.
    # Work always rides WITH a survivor operand (the host supplies
    # ones when nothing dropped) so there are exactly three programs:
    # mask-free, dropout, dropout+stragglers — and the first two stay
    # bit-identical to their pre-straggler builds.
    shard_train_work_mapped = shard_map(
        shard_train, mesh=mesh,
        in_specs=(P(), P("clients"), P("clients"), P("clients"),
                  P("clients"), P("clients"), P("clients"), P(),
                  P("clients"), P("clients")),
        out_specs=(P(), P(), state_spec, state_spec, state_spec,
                   P("clients"), P("clients"), P("clients")),
        axis_names=frozenset({"clients"}),
    )

    # screened family (ISSUE 16): survivors + poison mask + traced
    # screen-enable scalar, with the effective admitted mask as a
    # ninth output. Two programs — with and without the straggler
    # work operand — mirroring the default family's structure so
    # screening composes with every fault axis for free. Robust
    # aggregators (ISSUE 17) extend BOTH with two further outputs —
    # the contributors mask (per-client, sharded) and the replicated
    # [4] aggregation-stats vector — a static config branch, so
    # PR-16 screened configs keep their exact output arity.
    screened_out = (P(), P(), state_spec, state_spec, state_spec,
                    P("clients"), P("clients"), P("clients"),
                    P("clients"))
    if cfg.robust_aggregation:
        screened_out = screened_out + (P("clients"), P())

    def _shard_train_screened(ps_weights, data, mask, err_rows,
                              vel_rows, w_rows, keys, lr, surv, pois,
                              screen):
        return shard_train(ps_weights, data, mask, err_rows, vel_rows,
                           w_rows, keys, lr, surv, None, pois, screen)

    shard_train_screened_mapped = shard_map(
        _shard_train_screened, mesh=mesh,
        in_specs=(P(), P("clients"), P("clients"), P("clients"),
                  P("clients"), P("clients"), P("clients"), P(),
                  P("clients"), P("clients"), P()),
        out_specs=screened_out,
        axis_names=frozenset({"clients"}),
    )

    shard_train_screened_work_mapped = shard_map(
        shard_train, mesh=mesh,
        in_specs=(P(), P("clients"), P("clients"), P("clients"),
                  P("clients"), P("clients"), P("clients"), P(),
                  P("clients"), P("clients"), P("clients"), P()),
        out_specs=screened_out,
        axis_names=frozenset({"clients"}),
    )

    # ---------------- cohort gather / scatter-back -----------------------
    # The participant-row motion lives in two dedicated STATE-MOTION
    # programs OUTSIDE the jitted round (module docstring): the round
    # programs therefore never see a population-shaped operand —
    # graftaudit AU004's hard-error contract — and the only programs
    # touching the sharded [padded_population, D] blocks move exactly
    # O(cohort) rows each. Both compile once per config and are cache
    # hits on every later dispatch (tests pin the counts).

    def gather_cohort(clients: ClientState, ids) -> CohortState:
        """Pull the sampled cohort's rows out of the sharded population
        blocks. Untracked blocks yield [W] f32 dummies (distinct
        buffers — the round jit donates the whole CohortState, and XLA
        rejects one buffer donated twice) that keep the shard_map
        operand count static; they are never read."""
        W = ids.shape[0]
        return CohortState(
            errors=(clients.errors[ids] if _has_errors(cfg)
                    else jnp.zeros((W,))),
            velocities=(clients.velocities[ids] if _has_velocities(cfg)
                        else jnp.zeros((W,))),
            weights=(clients.weights[ids] if cfg.do_topk_down
                     else jnp.zeros((W,))))

    def scatter_back(clients: ClientState, ids,
                     cohort: CohortState) -> ClientState:
        """Write the round's merged cohort rows back into the sharded
        population blocks. The rows already encode the dropout
        contract (round_step merged dropped clients' gathered values
        back), so this is an unconditional per-slot write; untracked
        placeholder fields pass through."""
        new_clients = clients
        if _has_errors(cfg):
            new_clients = new_clients._replace(
                errors=new_clients.errors.at[ids].set(cohort.errors))
        if _has_velocities(cfg):
            new_clients = new_clients._replace(
                velocities=new_clients.velocities.at[ids].set(
                    cohort.velocities))
        if cfg.do_topk_down:
            new_clients = new_clients._replace(
                weights=new_clients.weights.at[ids].set(cohort.weights))
        return new_clients

    # ---------------- full train round ----------------------------------
    def round_step(server: ServerState, cohort: CohortState,
                   batch: RoundBatch, lr, key):
        num_workers = batch.client_ids.shape[0]
        if num_workers % n_shards != 0:
            raise ValueError(
                f"num_workers={num_workers} must be divisible by the "
                f"{n_shards}-way clients mesh axis")

        # the gathered participant rows (cohort-gather ran before
        # dispatch; zero population-shaped operands in this program)
        err_rows = cohort.errors
        vel_rows = cohort.velocities
        w_rows = cohort.weights

        round_key = jax.random.fold_in(key, server.round_idx)
        client_keys = jax.vmap(
            lambda i: jax.random.fold_in(round_key, i)
        )(jnp.arange(num_workers))

        surv = batch.survivors
        work = batch.work
        pois = batch.poison
        admitted = contributors = agg_stats = None
        if pois is not None:
            # screened family (ISSUE 16): survivors and the traced
            # screen flag always ride with the poison operand (the
            # host ones-fills / zero-fills whichever is inert) — two
            # programs total, and the per-round screen decision is
            # data, never a retrace (RoundBatch docstring)
            surv = (jnp.ones(num_workers, jnp.float32) if surv is None
                    else surv.astype(jnp.float32))
            pois = pois.astype(jnp.float32)
            screen = (jnp.ones((), jnp.float32)
                      if batch.screen is None
                      else jnp.asarray(batch.screen, jnp.float32))
            if work is not None:
                res = shard_train_screened_work_mapped(
                    server.ps_weights, batch.data, batch.mask,
                    err_rows, vel_rows, w_rows, client_keys, lr, surv,
                    work.astype(jnp.float32), pois, screen)
            else:
                res = shard_train_screened_mapped(
                    server.ps_weights, batch.data, batch.mask,
                    err_rows, vel_rows, w_rows, client_keys, lr, surv,
                    pois, screen)
            (transmit, total, new_err, new_vel, new_w, losses,
             metrics, counts, admitted) = res[:9]
            if cfg.robust_aggregation:
                # robust programs (ISSUE 17) report the contributors
                # mask and the aggregation-stats vector alongside
                contributors, agg_stats = res[9], res[10]
            # a fully-screened round is a zero-survivor round: the
            # whole server update gates off and state comes through
            # bit-untouched
            alive = admitted.sum() > 0
        elif work is not None:
            # stragglers active: the work program always carries a
            # survivor operand too (below-cutoff degradation composes
            # the two), so substitute ones when nothing dropped
            surv = (jnp.ones(num_workers, jnp.float32) if surv is None
                    else surv.astype(jnp.float32))
            (transmit, total, new_err, new_vel, new_w, losses, metrics,
             counts) = shard_train_work_mapped(
                server.ps_weights, batch.data, batch.mask,
                err_rows, vel_rows, w_rows, client_keys, lr, surv,
                work.astype(jnp.float32))
            alive = surv.sum() > 0
        elif surv is None:
            (transmit, total, new_err, new_vel, new_w, losses, metrics,
             counts) = shard_train_mapped(
                server.ps_weights, batch.data, batch.mask,
                err_rows, vel_rows, w_rows, client_keys, lr)
            alive = None
        else:
            surv = surv.astype(jnp.float32)
            (transmit, total, new_err, new_vel, new_w, losses, metrics,
             counts) = shard_train_surv_mapped(
                server.ps_weights, batch.data, batch.mask,
                err_rows, vel_rows, w_rows, client_keys, lr, surv)
            # zero-survivor round -> gate the whole server update off
            # (get_server_update applies it): momentum/error state and
            # ps_weights come through bit-untouched
            alive = surv.sum() > 0

        # mean over the global batch (reference fed_aggregator.py:332):
        # with dropout, `total` already counts survivor examples only,
        # so the mean reweights by survivor count automatically; with
        # stragglers, each transmit was scaled by (and `total` counts)
        # examples ACTUALLY processed, so heterogeneous work fractions
        # normalize out FedNova-style — a half-work client carries
        # half weight, not a half-magnitude bias. A robust aggregator
        # (ISSUE 17) already produced the NORMALIZED location estimate
        # inside shard_train (an order statistic does not distribute
        # over the psum/divide split), so the divide is skipped.
        # compressor post-aggregation hook (ISSUE 19): once per round
        # on the aggregate, before the divide — dp_sketch adds its
        # calibrated Gaussian noise here, on the "dp" domain of the
        # round key; the identity (zero traced ops) for every other
        # plugin, so default programs are byte-unchanged
        transmit = comp.post_aggregate(cfg, transmit, round_key)
        if cfg.robust_aggregation and pois is not None:
            gradient = transmit
        else:
            gradient = transmit / jnp.maximum(total, 1.0)

        # server aggregation + decompression
        upd = fserver.get_server_update(
            gradient, server.Vvelocity, server.Verror, cfg, lr,
            key=jax.random.fold_in(round_key, num_workers),
            alive=alive)

        if alive is None:
            new_ps = server.ps_weights - upd.update
        else:
            # `where` (not `- 0.0`) so a dead round is bit-exact
            new_ps = jnp.where(alive, server.ps_weights - upd.update,
                               server.ps_weights)
        # round_idx advances even on a zero-survivor round: it indexes
        # the PRNG stream (round_key above), and a frozen index would
        # replay the identical dropout draw forever
        new_server = ServerState(new_ps, upd.Vvelocity, upd.Verror,
                                 server.round_idx + 1)

        # merge the updated participant rows with the gathered ones: a
        # dropped client's rows come through as their GATHERED values,
        # i.e. the scatter-back lands them bit-untouched (its error
        # feedback simply waits for the next round it completes). The
        # merged CohortState is this program's carried row output —
        # the scatter-back state-motion program writes it into the
        # population blocks after dispatch.
        # the EFFECTIVE mask: host survivors x device admission —
        # identical to surv outside the screened family, so the three
        # default programs trace byte-identically
        eff = admitted if admitted is not None else surv
        keep = None if eff is None else eff[:, None] > 0
        new_cohort = cohort
        if _has_errors(cfg):
            if keep is not None:
                new_err = jnp.where(keep, new_err, err_rows)
            new_cohort = new_cohort._replace(errors=new_err)
        if _has_velocities(cfg):
            if upd.velocity_mask is not None:
                # true_topk momentum factor masking (fixes ref D6)
                new_vel = new_vel * upd.velocity_mask[None, :]
            if keep is not None:
                new_vel = jnp.where(keep, new_vel, vel_rows)
            new_cohort = new_cohort._replace(velocities=new_vel)
        if cfg.do_topk_down:
            # persist each participant's post-download weights so its
            # staleness is tracked (the reference computes but never
            # stores these — deliberate fix, see module docstring);
            # a dropped client never received the download, so its
            # stale-weight row is kept too
            if keep is not None:
                new_w = jnp.where(keep, new_w, w_rows)
            new_cohort = new_cohort._replace(weights=new_w)

        # on-device telemetry (telemetry/metrics.py): pure observation
        # of values already computed — reads the applied delta and the
        # NEW accumulator state, writes nothing back, so the state
        # outputs above are bit-identical with cfg.telemetry off (the
        # zero-size placeholder keeps the treedef stable per config)
        if cfg.telemetry:
            tele = tmetrics.round_vector(
                losses=losses, counts=counts,
                delta=new_ps - server.ps_weights,
                verror=upd.Verror, vvelocity=upd.Vvelocity,
                survivors=(jnp.float32(num_workers) if eff is None
                           else eff.sum()))
        else:
            tele = tmetrics.empty_vector()

        return new_server, new_cohort, RoundMetrics(
            losses, metrics, counts, tele, admitted, contributors,
            agg_stats)

    def round_full(server: ServerState, clients: ClientState,
                   batch: RoundBatch, lr, key):
        """The COMPOSED per-round body — cohort gather, cohort round,
        scatter-back in ONE traced program. This is the scanned span's
        step (client state rides the scan carry, so the gather/scatter
        happen per scanned round exactly as before the split) and the
        bit-identity twin tests compare the three-program dispatch
        against."""
        cohort = gather_cohort(clients, batch.client_ids)
        server, new_cohort, metrics = round_step(
            server, cohort, batch, lr, key)
        clients = scatter_back(clients, batch.client_ids, new_cohort)
        return server, clients, metrics

    # explicit output placement for the state-motion programs (the
    # shard-and-gather-fn half of the SNIPPETS.md pattern): gathered
    # rows land sharded over the clients axis — the exact layout the
    # round program's shard_map consumes, so GSPMD never reshards the
    # cohort between the two dispatches — and the scattered population
    # blocks keep their CLIENT_STATE_RULES placement.
    def _cohort_sharding():
        from commefficient_tpu.parallel import multihost as mh

        def spec(tracked):
            return P("clients", None) if tracked else P("clients")
        return mh.shardings(mesh, CohortState(
            spec(_has_errors(cfg)), spec(_has_velocities(cfg)),
            spec(cfg.do_topk_down)))

    def _state_sharding():
        from commefficient_tpu.parallel import multihost as mh

        def spec(tracked):
            return P("clients", None) if tracked else P()
        return mh.shardings(mesh, ClientState(
            spec(_has_errors(cfg)), spec(_has_velocities(cfg)),
            spec(cfg.do_topk_down)))

    # buffer donation (Config.donate_round_state, default on): the
    # dead-after-dispatch state operands are donated so XLA reuses
    # their HBM for the matching outputs in place — at population
    # scale the client rows are the dominant allocation, and an
    # un-donated scatter-back transiently doubles it. The dead sets are
    # the registry constants above; donated operands are INVALID after
    # the call (see TrainRound docstring for the caller contract).
    round_donate = (ROUND_DEAD_ARGNUMS if cfg.donate_round_state
                    else ())
    # pipelined TIERED staging (ISSUE 11 + ISSUE 10): span t+1's
    # restore-scatters run against span t's result block while the
    # deferred span-boundary checkpoint still reads it, so the
    # scatter keeps its operand alive — transiently doubled block
    # HBM, bounded by the working set (the same trade the span jit
    # makes below)
    scatter_donate = (SCATTER_DEAD_ARGNUMS
                      if cfg.donate_round_state
                      and not (cfg.pipeline
                               and cfg.state_tier != "device")
                      else ())
    # pipelined spans (Config.pipeline, ISSUE 10) keep their state
    # operands ALIVE: span t+1 dispatches while span t's result state
    # is still needed by the deferred span-boundary checkpoint, so
    # donating it would hand the persistence path deleted buffers —
    # double buffering pays with transiently doubled state HBM instead
    span_donate = (SPAN_DEAD_ARGNUMS
                   if cfg.donate_round_state and not cfg.pipeline
                   else ())
    _gather_jit = jax.jit(gather_cohort,
                          out_shardings=_cohort_sharding())
    _scatter_jit = jax.jit(scatter_back, donate_argnums=scatter_donate,
                           out_shardings=_state_sharding())
    _train_round_jit = jax.jit(round_step, donate_argnums=round_donate)

    # ---------------- scanned multi-round driver -------------------------
    def train_rounds(server: ServerState, clients: ClientState,
                     batches: RoundBatch, lrs, key):
        """Run N rounds as ONE device program (`lax.scan` over rounds):
        `batches` is a RoundBatch whose fields carry a leading [N]
        axis, `lrs` is [N]. Amortizes host dispatch — the reference
        pays a full host round-trip (queues + NCCL + shared-memory
        writeback, fed_aggregator.py:303-332) every round by
        construction; here an entire epoch can stay on-device.

        Also returns the per-round packed change bitset of the weight
        update ([N, D/32] uint32) so host-side communication
        accounting can replay the rounds without the weights ever
        leaving the device (see accounting.pack_change_bits).
        """
        from commefficient_tpu.federated.accounting import pack_change_bits

        def body(carry, xs):
            server, clients = carry
            batch, lr = xs
            prev = server.ps_weights
            # the composed body: gather -> cohort round -> scatter all
            # inside the scanned program, client state on the carry —
            # the population blocks never leave the device between
            # rounds, exactly as before the state-motion split
            server, clients, metrics = round_full(
                server, clients, batch, lr, key)
            bits = pack_change_bits(server.ps_weights - prev)
            return (server, clients), (metrics, bits)

        (server, clients), (metrics, bits) = jax.lax.scan(
            body, (server, clients), (batches, lrs))
        return server, clients, metrics, bits

    train_rounds = jax.jit(train_rounds, donate_argnums=span_donate)

    class TrainRound:
        """Callable single-round step; `.train_rounds` runs a whole
        scanned span of rounds in one device program.

        `__call__` brackets the jitted ROUND program with the two
        state-motion programs: cohort-gather before, scatter-back
        after — three dispatches, but the gather/scatter compile once
        per config and the round program is one of exactly three
        treedef variants, so the steady state is three cache-hit
        dispatches with O(cohort) traffic between them.

        Caller contract under donation (Config.donate_round_state, the
        default): `__call__` donates the gathered CohortState into the
        round program and the full ClientState into scatter-back, and
        `.train_rounds` donates BOTH state operands — after a dispatch
        the caller must use the returned state, never the arrays it
        passed in (FedModel reassigns immediately; a timing loop that
        re-dispatches from one retained state object needs
        donate_round_state=False). The registry attributes below are
        graftaudit's trace surface: `round_step` is the un-jitted
        COHORT round body (what the round jit compiles — jax.make_jaxpr
        over it yields the audited ClosedJaxpr), `gather_fn` /
        `scatter_fn` are the raw state-motion bodies, `round_full` the
        composed scan step, and the *_donate_argnums record what the
        built jits actually donate, checked against
        ROUND_DEAD_ARGNUMS / SCATTER_DEAD_ARGNUMS / SPAN_DEAD_ARGNUMS."""

        def __call__(self, server, clients, batch, lr, key):
            # graftscope (ISSUE 13): HOST-side spans around the three
            # dispatches — asynchronous dispatch cost, not device
            # time (that's the device_execute bracket at the
            # dispatch/collect seam). The round/span tags inherit
            # from the caller's enclosing `dispatch` span; nothing
            # here touches the traced programs.
            with TRACE.span("gather"):
                cohort = _gather_jit(clients, batch.client_ids)
            with TRACE.span("round_dispatch"):
                server, new_cohort, metrics = _train_round_jit(
                    server, cohort, batch, lr, key)
            with TRACE.span("scatter"):
                clients = _scatter_jit(clients, batch.client_ids,
                                       new_cohort)
            return server, clients, metrics

    handle = TrainRound()
    handle.train_rounds = train_rounds
    handle.round_step = round_step
    # the gather program's declared cohort placement — the tiered
    # state store (federated/statestore) places its host-built
    # restore rows with exactly these shardings so the restore hits
    # the same compiled scatter program the post-round writeback uses
    handle.cohort_shardings = _cohort_sharding()
    handle.round_full = round_full
    handle.gather = _gather_jit
    handle.scatter = _scatter_jit
    handle.gather_fn = gather_cohort
    handle.scatter_fn = scatter_back
    handle.round_donate_argnums = round_donate
    handle.scatter_donate_argnums = scatter_donate
    handle.span_donate_argnums = span_donate
    handle.cfg = cfg
    return handle


def make_eval_fn(loss_fn: fclient.LossFn, unravel: Callable,
                 cfg: Config, mesh: Mesh):
    """Build the jitted eval function — separate from the train factory
    so a distinct val loss (GPT2's nll/acc/ppl metrics,
    gpt2_train.py:242-253) never builds a throwaway train round.

    Uses the loss-only flat fn: the eval jaxpr contains no backward
    ops (asserted by tests/test_client.py), so eval compiles and runs
    forward-only instead of relying on XLA to DCE an unused grad."""
    flat_loss = fclient.make_flat_loss_fn(
        loss_fn, unravel,
        compute_dtype=jnp.bfloat16 if cfg.do_bf16 else None)

    def shard_eval(ps_weights, data, mask):
        def one_shard(b, m):
            _, loss, metrics, count = fclient.forward_grad(
                flat_loss, ps_weights, b, m, cfg, compute_grad=False)
            return loss, metrics, count
        return jax.vmap(one_shard)(data, mask)

    shard_eval_mapped = shard_map(
        shard_eval, mesh=mesh,
        in_specs=(P(), P("clients"), P("clients")),
        out_specs=(P("clients"), P("clients"), P("clients")),
        # model axis (if present) stays auto — see make_train_fn
        axis_names=frozenset({"clients"}),
    )

    @jax.jit
    def eval_batch(ps_weights, data, mask):
        """data: [S, vb, ...], mask: [S, vb]; S divisible by the mesh.
        Returns per-shard (loss, metrics, count) — the val path of
        reference _call_val (fed_aggregator.py:337-364)."""
        return shard_eval_mapped(ps_weights, data, mask)

    return eval_batch

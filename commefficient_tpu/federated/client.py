"""Client-side computation: one simulated federated client's round.

Functional re-design of the reference worker runtime's per-client math
(reference: CommEfficient/fed_worker.py:140-335 — `process_batch`,
`local_step`, `forward_grad` — and the fedavg local-SGD branch at
:61-113). The reference runs this as a Python loop inside one process
per GPU; here it is a pure function over static-shape arrays, designed
to be `vmap`ed over the clients owned by a mesh shard and `shard_map`ed
over the `clients` axis.

Static-shape discipline (SURVEY.md §7.3 hard part #2): client batches
are padded to [B] with a validity mask; microbatching is a `lax.scan`
over a [n_mb, mb, ...] reshape; all means are masked means; the
transmitted quantity is scaled by the *valid* example count, matching
the reference's g *= batch_size (fed_worker.py:190) so the server's
divide-by-total-batch-size (fed_aggregator.py:332) is exact.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu import compress
from commefficient_tpu.config import Config
from commefficient_tpu.ops.flat import clip_to_l2, dp_noise, global_norm_clip

# loss_fn contract (the workload callback, analogous to the reference's
# compute_loss(model, batch, args) -> (loss, *metrics) at
# cv_train.py:67-83 / gpt2_train.py:77-99, extended with a validity
# mask): loss_fn(params_pytree, batch_tuple, mask) ->
#   (masked-mean loss, tuple of masked-mean metrics)
LossFn = Callable[[object, Tuple[jax.Array, ...], jax.Array],
                  Tuple[jax.Array, Tuple[jax.Array, ...]]]


class ClientResult(NamedTuple):
    transmit: jax.Array          # [D] vector or [r, c] sketch table
    error: jax.Array             # updated local error state (or dummy)
    velocity: jax.Array          # updated local velocity state (or dummy)
    loss: jax.Array              # masked-mean loss over this client's batch
    metrics: Tuple[jax.Array, ...]
    num_examples: jax.Array      # valid example count (f32)


def _cast_tree(tree, dtype):
    """Cast every inexact leaf to `dtype` (ints/bools untouched)."""
    return jax.tree.map(
        lambda l: l.astype(dtype)
        if jnp.issubdtype(l.dtype, jnp.inexact) else l, tree)


def make_flat_grad_fn(loss_fn: LossFn, unravel: Callable,
                      compute_dtype=None):
    """Lift a pytree loss into flat-vector space: the substrate every
    compression op works in (replaces get_grad/get_grad_vec,
    reference utils.py:254-273).

    compute_dtype=jnp.bfloat16 runs the client forward/backward on the
    MXU's fast path: master weights stay f32 (the [D] vector, all
    server/compression state), the model body computes in bf16, and
    the grad returns to f32 at the cast boundary. The bf16 rounding
    noise lands inside the same error-feedback loop that already
    absorbs compression error. Opt-in via --bf16 (a capability the
    reference's fp32-only CUDA path doesn't have)."""
    def flat_grad(weights_vec, batch, mask):
        def scalar_loss(vec):
            params = unravel(vec)
            b = batch
            if compute_dtype is not None:
                params = _cast_tree(params, compute_dtype)
                b = _cast_tree(b, compute_dtype)
            loss, metrics = loss_fn(params, b, mask)
            return loss.astype(jnp.float32), _cast_tree(
                metrics, jnp.float32)
        (loss, metrics), grad = jax.value_and_grad(
            scalar_loss, has_aux=True)(weights_vec)
        return loss, metrics, grad
    return flat_grad


def make_flat_loss_fn(loss_fn: LossFn, unravel: Callable,
                      compute_dtype=None):
    """Loss-only counterpart of make_flat_grad_fn for the eval path:
    no value_and_grad, so eval jaxprs carry no backward ops at all —
    eval cost and compile time are forward-only by construction, not by
    hoping XLA DCEs an unused gradient (this matters at GPT2 size)."""
    def flat_loss(weights_vec, batch, mask):
        params = unravel(weights_vec)
        if compute_dtype is not None:
            params = _cast_tree(params, compute_dtype)
            batch = _cast_tree(batch, compute_dtype)
        loss, metrics = loss_fn(params, batch, mask)
        return loss.astype(jnp.float32), _cast_tree(metrics, jnp.float32)
    return flat_loss


def _microbatch_shape(batch_size: int, microbatch_size: int) -> Tuple[int, int]:
    mb = batch_size if microbatch_size <= 0 else min(microbatch_size, batch_size)
    n_mb = -(-batch_size // mb)
    return n_mb, mb


def _reshape_microbatches(tree, mask, n_mb: int, mb: int):
    """Pad [B, ...] arrays to n_mb*mb and fold into [n_mb, mb, ...]."""
    B = mask.shape[0]
    pad = n_mb * mb - B

    def fold(x):
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
        return x.reshape((n_mb, mb) + x.shape[1:])

    mask = jnp.concatenate([mask, jnp.zeros((pad,), mask.dtype)]) if pad else mask
    return jax.tree.map(fold, tree), mask.reshape(n_mb, mb)


def forward_grad(flat_grad_fn, weights: jax.Array, batch, mask: jax.Array,
                 cfg: Config, key: Optional[jax.Array] = None,
                 compute_grad: bool = True,
                 grad_mask: Optional[jax.Array] = None):
    """Microbatched forward(/backward) over one client's padded batch
    (reference forward_grad, fed_worker.py:249-335).

    Returns (g, loss, metrics, count): g is the per-mode compressed
    mean-gradient ([D] vector, or [r, c] table for sketch); loss and
    metrics are masked means over the batch; count is the number of
    valid examples. When compute_grad=False (eval path,
    fed_worker.py:300-301) g is None and `flat_grad_fn` must be a
    loss-only callable returning (loss, metrics) — see
    make_flat_loss_fn — so the traced program has no backward pass.
    """
    B = mask.shape[0]
    n_mb, mb = _microbatch_shape(B, cfg.microbatch_size)
    mbatch, mmask = _reshape_microbatches(batch, mask, n_mb, mb)

    def body(carry, xs):
        accum_grad, accum_loss, accum_metrics = carry
        b, m = xs
        count = m.sum()
        if compute_grad:
            loss, metrics, grad = flat_grad_fn(weights, b, m)
            accum_grad = accum_grad + grad * count
        else:
            loss, metrics = flat_grad_fn(weights, b, m)
        accum_loss = accum_loss + loss * count
        accum_metrics = jax.tree.map(
            lambda a, v: a + v * count, accum_metrics, metrics)
        return (accum_grad, accum_loss, accum_metrics), None

    # metric structure probe (abstract eval: shapes only, no FLOPs)
    probe = jax.eval_shape(
        flat_grad_fn, weights,
        jax.tree.map(lambda x: x[0], mbatch), mmask[0])
    metrics_shape = probe[1]
    # scan carries seeded from `mask` (not fresh constants) so that
    # under shard_map they inherit the data's varying-axes type
    zero = jnp.zeros_like(mask, shape=())
    metrics_proto = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype) + zero.astype(s.dtype),
        metrics_shape)
    init = (jnp.zeros_like(weights) + zero, zero, metrics_proto)
    (grad_sum, loss_sum, metric_sums), _ = jax.lax.scan(
        body, init, (mbatch, mmask))

    total = mask.sum()
    denom = jnp.maximum(total, 1.0)
    loss = loss_sum / denom
    metrics = jax.tree.map(lambda m: m / denom, metric_sums)

    if not compute_grad:
        return None, loss, metrics, total

    # weighted mean over valid examples: gradient scale is invariant to
    # microbatch_size. (Deliberate divergence: the reference sums
    # microbatch-mean grads, making scale depend on the microbatch
    # count, and compensates by scaling the clip threshold by
    # num_iters — fed_worker.py:286-292.)
    grad = grad_sum / denom

    # frozen-coordinate masking FIRST: frozen coordinates contribute
    # nothing — no gradient, no weight decay, no share of any clipping
    # norm, no compression budget. The reference gets all of this for
    # free because requires_grad=False params never enter the flat
    # vector; here they stay in the vector, so every term below must
    # exclude them explicitly.
    if grad_mask is not None:
        grad = grad * grad_mask

    # gradient clipping for non-sketch modes (reference
    # fed_worker.py:290-292; unscaled here per the note above)
    if cfg.max_grad_norm is not None and cfg.mode != "sketch":
        grad = global_norm_clip(grad, cfg.max_grad_norm)

    # weight decay folded into the gradient, divided by num_workers so
    # the summed transmission applies it once (reference utils.py:254-259)
    if cfg.weight_decay != 0:
        wd_term = (cfg.weight_decay / cfg.num_workers) * weights
        if grad_mask is not None:
            wd_term = wd_term * grad_mask
        grad = grad + wd_term

    # differential privacy at the worker (reference fed_worker.py:304-309)
    if cfg.do_dp:
        grad = clip_to_l2(grad, cfg.l2_norm_clip)
        if cfg.dp_mode == "worker":
            grad = grad + dp_noise(key, grad.shape, cfg.noise_multiplier,
                                   scale=float(np.sqrt(cfg.num_workers)))
        if grad_mask is not None:
            grad = grad * grad_mask  # DP noise lands only on live coords

    # per-mode compression (reference fed_worker.py:311-335), delegated
    # to the mode's Compressor plugin (ISSUE 19): the sketch-like
    # plugins encode the [r, c] table here; dense plugins pass the
    # gradient through untouched (sparsification happens later —
    # server for true_topk, the residual seam for local_topk/powersgd)
    g = compress.get_compressor(cfg.mode).encode(cfg, grad, key)

    return g, loss, metrics, total


def fused_shard_grads(flat_loss_fn, weights, batch, mask,
                      cfg: Config,
                      grad_mask: Optional[jax.Array] = None,
                      survivors: Optional[jax.Array] = None):
    """One backward pass for a whole shard of clients
    (Config.fused_client_backward's gate guarantees this equals the
    sum of per-client local_step transmits):

        sum_c transmit_c = sum_c count_c * mean_grad_c
                         = d/dw [ sum_c count_c * mean_loss_c ]

    plus the weight-decay term, which every client adds as
    (wd/num_workers) * w before the count scaling, so the shard sum
    contributes (wd/num_workers) * w * total_count (reference
    utils.py:254-259 semantics preserved).

    survivors: optional [W_shard] f32 {0,1} dropout mask. Each
    client's term of the fused objective (and its weight-decay
    contribution) is scaled by its survivor bit, so a dropped client
    contributes exactly nothing to the shard gradient — the same
    linearity that lets the fusion exist at all. Returned counts are
    survivor-weighted; losses/metrics stay per-client diagnostics.

    batch/mask are the shard's [W_shard, B, ...] arrays. Returns
    (grad_sum [D], losses [W_shard], metrics, counts [W_shard]) where
    losses/metrics are per-client masked means — the same reporting
    contract as the vmapped path.
    """
    def objective(vec):
        def one(d, m):
            loss, metrics = flat_loss_fn(vec, d, m)
            return loss, metrics, m.sum()
        losses, metrics, counts = jax.vmap(one)(batch, mask)
        if survivors is not None:
            counts = counts * survivors
        total = (losses * counts).sum()
        return total, (losses, metrics, counts)

    (_, (losses, metrics, counts)), grad_sum = jax.value_and_grad(
        objective, has_aux=True)(weights)

    if grad_mask is not None:
        grad_sum = grad_sum * grad_mask
    if cfg.weight_decay != 0:
        wd_term = (cfg.weight_decay / cfg.num_workers) * weights \
            * counts.sum()
        if grad_mask is not None:
            wd_term = wd_term * grad_mask
        grad_sum = grad_sum + wd_term
    return grad_sum, losses, metrics, counts


def local_step(flat_grad_fn, weights, batch, mask, error, velocity,
               cfg: Config, key=None,
               grad_mask: Optional[jax.Array] = None) -> ClientResult:
    """One client's single local step + compression bookkeeping
    (reference local_step, fed_worker.py:184-230)."""
    g, loss, metrics, count = forward_grad(
        flat_grad_fn, weights, batch, mask, cfg, key, grad_mask=grad_mask)

    # transmit sums over examples; server divides by the global batch
    # size (reference fed_worker.py:190)
    g = g * count

    if cfg.local_momentum > 0:
        velocity = g + cfg.local_momentum * velocity

    if cfg.error_type == "local":
        error = error + (velocity if cfg.local_momentum > 0 else g)
        to_transmit = error
    else:
        to_transmit = velocity if cfg.local_momentum > 0 else g

    # residual seam (ISSUE 19): the plugin turns the accumulated
    # quantity into the final wire payload plus new error/velocity
    # carries — local_topk's sparsify-and-mask, powersgd's low-rank
    # factorization, dp_sketch's sensitivity clip; identity elsewhere
    to_transmit, error, velocity = compress.get_compressor(
        cfg.mode).residual(cfg, to_transmit, error, velocity, key)

    return ClientResult(to_transmit, error, velocity, loss, metrics, count)


def fedavg_step(flat_grad_fn, weights, batch, mask, cfg: Config,
                lr, key=None,
                grad_mask: Optional[jax.Array] = None,
                work: Optional[jax.Array] = None) -> ClientResult:
    """FedAvg: full local SGD over the client's dataset, transmitting
    the dataset-size-weighted weight delta (reference worker_loop
    fedavg branch, fed_worker.py:61-113).

    `batch` holds the client's entire local dataset padded to a static
    size; it is split into fedavg_batch_size local batches and scanned
    num_fedavg_epochs times with per-step lr decay fedavg_lr_decay**step.

    `lr` may be a scalar or a per-parameter [D] vector (finetune
    freezing / Fixup param-group LRs applied to the LOCAL steps, since
    fedavg's server update runs at lr=1); `grad_mask` zeroes frozen
    coordinates' local gradients so they neither move nor accrue
    weight decay.

    `work`: optional traced scalar work fraction in (0, 1] — a
    straggler's COMPLETED-STEPS budget (Config.straggler_*). The
    client applies only its first ceil(work * steps) local SGD steps
    (the round deadline lands mid-local-training); later steps still
    trace (static shapes) but their updates are gated off. The
    transmitted delta is weighted by examples actually processed —
    dataset size scaled by completed/total steps — the FedNova-style
    normalization that keeps heterogeneous work from biasing the
    average. Loss/metrics are means over completed steps only. None
    traces the original work-free program.
    """
    B = mask.shape[0]
    inner = B if cfg.fedavg_batch_size == -1 else min(cfg.fedavg_batch_size, B)
    n_batches = -(-B // inner)
    lbatch, lmask = _reshape_microbatches(batch, mask, n_batches, inner)

    # one scan over epochs * n_batches steps
    steps = cfg.num_fedavg_epochs * n_batches
    step_batch = jax.tree.map(
        lambda x: jnp.tile(x, (cfg.num_fedavg_epochs,) + (1,) * (x.ndim - 1)),
        lbatch)
    step_mask = jnp.tile(lmask, (cfg.num_fedavg_epochs, 1))
    if work is not None:
        # ceil keeps a surviving straggler on >= 1 step; work=1.0 is
        # exactly `steps` (below-cutoff fractions never reach here —
        # the host degraded them to dropout)
        live_steps = jnp.ceil(work * steps)

    def body(carry, xs):
        w, step = carry
        b, m = xs
        loss, metrics, grad = flat_grad_fn(w, b, m)
        # reference computes sum-grad then divides by batch size
        # (fed_worker.py:96-98); our flat_grad_fn already returns the
        # masked-mean gradient, but weight decay must still be added
        if cfg.weight_decay != 0:
            grad = grad + (cfg.weight_decay / cfg.num_workers) * w
        if grad_mask is not None:
            grad = grad * grad_mask
        decay = cfg.fedavg_lr_decay ** step
        if work is None:
            w = w - grad * lr * decay
            return (w, step + 1.0), (loss, metrics)
        live = (step < live_steps).astype(w.dtype)
        w = w - grad * lr * decay * live
        return (w, step + 1.0), (loss, metrics, live)

    zero = jnp.zeros_like(mask, shape=())
    (w_final, _), outs = jax.lax.scan(
        body, (weights + zero, zero), (step_batch, step_mask))

    if work is None:
        losses, metrics_seq = outs
        # metrics averaged over local steps (reference fed_worker.py:102-103)
        loss = losses.mean()
        metrics = jax.tree.map(lambda m: m.mean(), metrics_seq)
        count = mask.sum()
    else:
        losses, metrics_seq, lives = outs
        done = lives.sum()
        denom = jnp.maximum(done, 1.0)
        loss = (losses * lives).sum() / denom
        metrics = jax.tree.map(lambda m: (m * lives).sum() / denom,
                               metrics_seq)
        # examples actually processed: dataset size scaled by the
        # completed-step fraction (FedNova-style delta weighting)
        count = mask.sum() * (done / steps)
    delta = (weights - w_final) * count  # dataset-size weighting (:104-108)
    dummy = jnp.zeros_like(mask, shape=())
    return ClientResult(delta, dummy, dummy, loss, metrics, count)

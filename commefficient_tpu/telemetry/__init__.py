"""commefficient_tpu.telemetry — the observability subsystem (ISSUE 4).

Three parts, one session object tying them together:

  * `metrics` — a fixed-shape NAMED f32 metric vector computed inside
    the jitted round (round loss, update/error norms, survivor count,
    processed examples, realized top-k, sketch estimate-residual
    proxy), carried through scanned spans and exported to the host
    only at span boundaries via explicit `device_get` — the
    transfer-guard and three-programs contracts hold with telemetry
    permanently on, and `ServerState` bits are provably unchanged;
  * `journal` — an append-only JSONL event log in the run dir
    recording round/span metrics, wall-clock spans, checkpoint saves,
    XLA compile events, retry attempts, and injected faults; bench
    harnesses append their digests in the same schema;
  * `clients` — per-client EMA throughput + participation, persisted
    in the checkpoint resume-bit-exact: the measurement substrate for
    the ROADMAP's deadline-estimation and straggler-aware-sampling
    openings.

`TelemetrySession` is the host-side conductor FedModel dispatches into
(`FedModel.attach_telemetry`): it buffers device metric vectors with a
ONE-ROUND lag on the per-round path (materializing a round that has
already completed costs no sync — the same discipline the drivers'
metric emission uses, PERF.md), consumes whole spans at their natural
boundary on the scanned path, feeds the throughput tracker, journals
everything, and drives `jax.profiler` capture of operator-selected
spans (`--profile_spans A:B`).
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import Callable, Optional, Tuple

import numpy as np

import jax

from commefficient_tpu.analysis import runtime as _runtime
from commefficient_tpu.telemetry import metrics as tmetrics
from commefficient_tpu.telemetry.clients import ClientThroughputTracker
from commefficient_tpu.telemetry.journal import RunJournal, append_event
from commefficient_tpu.telemetry.trace import TRACE

__all__ = [
    "ClientThroughputTracker", "NumericTripError", "RunJournal",
    "TRACE", "TelemetrySession", "append_event",
    "attach_run_telemetry", "parse_profile_spans", "tmetrics",
]

# the telemetry metrics the finite-frontier watch trips on (ISSUE
# 16): non-finite update or error-feedback l2 means corruption
# reached the server state — the persistent-poison condition the
# auto-rollback recovers from. Both are EXISTING metrics; the watch
# adds no device work.
WATCHED_METRICS = ("update_l2", "error_l2")


class NumericTripError(RuntimeError):
    """A watched telemetry metric went non-finite: value corruption
    reached ServerState (error feedback makes it persistent —
    PAPER.md). Raised by TelemetrySession at the round's one-lag
    emission, AFTER the `numeric_trip` journal event is durable. The
    drivers catch this, halt the span, roll back to the newest
    finite checkpoint (utils/checkpoint.load_resilient with
    require_finite) and resume with screening force-enabled
    (FedModel.force_screen_rounds); Config.max_numeric_rollbacks
    bounds the retries before failing loud."""

    def __init__(self, round_idx: int, metrics=()):
        super().__init__(
            f"non-finite {'/'.join(metrics) or 'telemetry'} at round "
            f"{round_idx}: value corruption reached the server state")
        self.round_idx = int(round_idx)
        self.metrics = tuple(metrics)


def parse_profile_spans(spec: str) -> Optional[Tuple[int, int]]:
    """Parse `--profile_spans A:B` into a half-open span-index range
    [A, B), or None for the empty spec. Raises ValueError on malformed
    input (caught at config validation, not mid-run)."""
    if not spec:
        return None
    lo, sep, hi = spec.partition(":")
    try:
        if not sep:
            raise ValueError
        a, b = int(lo), int(hi)
    except ValueError:
        raise ValueError(
            f"--profile_spans expects 'A:B' (half-open span indices, "
            f"e.g. '2:4'), got {spec!r}") from None
    if a < 0 or b <= a:
        raise ValueError(
            f"--profile_spans {spec!r}: need 0 <= A < B")
    return a, b


def attach_run_telemetry(model, cfg, log_dir: str, coord: bool,
                         driver: str,
                         materialize: Callable = jax.device_get):
    """Build + attach a run's TelemetrySession (both drivers share
    this wiring): journal on the coordinator only (cfg.journal_path or
    <run dir>/journal.jsonl), profiler capture per cfg.profile_spans,
    the model's own throughput tracker, and the caller's device->host
    materializer (multihost.gather_host in the drivers). Journals
    `run_start` and returns the session — the caller owns close() —
    or None under --no_telemetry."""
    if not cfg.telemetry:
        return None
    journal = None
    if coord:
        jpath = cfg.journal_path or os.path.join(
            log_dir or ".", "journal.jsonl")
        # --pipeline: appends ride a bounded-queue writer thread (one
        # fsync per queued batch, drained on close/crash) so journal
        # durability leaves the round loop's critical path
        journal = RunJournal(jpath, run_id=log_dir or driver,
                             async_writer=bool(cfg.pipeline),
                             drain_timeout=float(getattr(
                                 cfg, "writer_drain_timeout_s", 0.0)))
    tele = TelemetrySession(
        journal=journal, tracker=model.throughput,
        profile_spans=cfg.profile_spans,
        profile_dir=os.path.join(log_dir or ".", "profile_spans"),
        materialize=materialize,
        # graftscope (ISSUE 13): --trace enables the process-global
        # stage tracer for this run (session-owned; disabled at
        # close); the controller tag keys cross-controller stitching
        trace=bool(getattr(cfg, "trace", False)),
        controller=jax.process_index())
    model.attach_telemetry(tele)
    tele.journal_event(
        "run_start", driver=driver, mode=cfg.mode,
        trace=bool(getattr(cfg, "trace", False)),
        dataset=cfg.dataset_name, num_workers=cfg.num_workers,
        num_clients=model.num_clients, grad_size=model.cfg.grad_size,
        # compression-kernel provenance (ISSUE 6): a journal reader
        # attributing up_bytes or round timings needs to know which
        # backend ran and what dtype rode the wire
        kernel_backend=cfg.kernel_backend,
        sketch_table_dtype=cfg.sketch_table_dtype,
        # residency provenance (ISSUE 11): a reader of state_tier
        # events needs the tier and working-set cap in the run record
        state_tier=cfg.state_tier,
        state_working_set=int(cfg.state_working_set),
        scan_rounds=bool(cfg.scan_rounds),
        transfer_guard=bool(cfg.debug_transfer_guard),
        resumed_round=int(np.asarray(
            materialize(model.server.round_idx))))
    return tele


class TelemetrySession:
    """Host-side telemetry conductor for one run.

    journal:       RunJournal or None (non-coordinator processes pass
                   None — tracker updates still run, since every
                   process gathers identical metrics)
    tracker:       ClientThroughputTracker or None; FedModel.
                   attach_telemetry fills in the model's own tracker
                   when unset
    profile_spans: `--profile_spans` spec ("" = no capture)
    profile_dir:   where jax.profiler traces land
    materialize:   device->host function for buffered metric arrays;
                   pass multihost.gather_host in multi-controller runs
                   (the default jax.device_get only handles
                   process-addressable arrays)
    """

    def __init__(self, journal: Optional[RunJournal] = None,
                 tracker: Optional[ClientThroughputTracker] = None,
                 profile_spans: str = "",
                 profile_dir: str = "profile_spans",
                 materialize: Callable = jax.device_get,
                 clock: Callable[[], float] = time.monotonic,
                 trace: bool = False, controller: int = 0):
        self.journal = journal
        self.tracker = tracker
        # graftscope (ISSUE 13, --trace): enable the process-global
        # tracer for this run; the session owns it — drained at every
        # round/span boundary into batched `trace` journal events and
        # DISABLED again at close, so tracing never leaks into a
        # later in-process run
        self._owns_trace = bool(trace)
        if trace:
            TRACE.enable(controller=controller)
        self._materialize = materialize
        self._clock = clock
        self._spans = parse_profile_spans(profile_spans)
        self._profile_dir = profile_dir
        self._profiling = False
        self._steady = False
        # per-round path: (round_idx, ids, vec, counts, t) buffer — the
        # previous round materializes when the next one arrives (its
        # device values are complete by then; device_get costs no sync)
        self._pending = None
        self._closed = False
        self._journal_warned = False
        # cumulative communication byte totals (accounting.py feeds
        # per-round sums through on_round/on_span; run_end carries the
        # cumulative pair so a journal is self-contained on cost)
        self._cum_down_bytes = 0.0
        self._cum_up_bytes = 0.0
        self._comm_seen = False
        _runtime.add_compile_listener(self._on_compile)

    # ---------------- journal passthrough --------------------------------
    def _safe_write(self, write: Callable[[], object]) -> None:
        """Observability must never kill training: a journal append
        that fails (disk full, unwritable path mid-run) warns once and
        the run continues — the same contract bench.journal_digest
        keeps for measurements. Notably the retry hook journals from
        INSIDE utils/retry.with_retries; an exception there would turn
        a recoverable transient into a fatal span failure."""
        try:
            write()
        except (OSError, TypeError, ValueError) as e:
            # TypeError included: a field json can't serialize must
            # degrade to a lost record, not a crashed run
            if not self._journal_warned:
                print(f"telemetry: journal write failed ({e}); "
                      f"training continues, further failures silent")
                self._journal_warned = True

    def journal_event(self, kind: str, /, **fields) -> None:
        if self.journal is not None:
            self._safe_write(lambda: self.journal.event(kind, **fields))

    def _flush_trace(self) -> None:
        """Drain the graftscope rings into ONE batched `trace` journal
        event (span-boundary flush cadence: one append+fsync per
        flush, not per span). Without a journal (non-coordinator
        processes) the drain still runs so the rings stay bounded —
        the spans are simply discarded, like every other
        coordinator-only record."""
        if not TRACE.enabled:
            return
        spans, dropped = TRACE.drain()
        if not spans and not dropped:
            return
        if self.journal is None:
            return
        fields = {"controller": TRACE.controller, "spans": spans}
        if dropped:
            fields["dropped"] = int(dropped)
        self._safe_write(
            lambda: self.journal.event("trace", **fields))

    # ---------------- compile events (analysis/runtime listener) ---------
    def mark_steady_state(self) -> None:
        """After this, every backend compile is journaled as a
        `compile_warning` — steady-state recompiles are retrace bugs
        (new treedef/shape/weak-type leak), the regression class
        assert_program_count(3) pins in tests and this surfaces in
        production journals. The drivers call it once the first full
        epoch (train spans + eval) has compiled everything a
        steady-state run legitimately needs."""
        self._steady = True

    @contextlib.contextmanager
    def expect_compiles(self, why: str = ""):
        """Temporarily allow compiles without warnings (e.g. a final
        eval program that legitimately first-compiles long after the
        training loop reached steady state)."""
        prev, self._steady = self._steady, False
        try:
            yield
        finally:
            self._steady = prev

    def _on_compile(self, event_name: str, duration: float) -> None:
        if self.journal is None:
            return
        fields = {"event_name": event_name}
        if duration is not None:
            fields["seconds"] = round(float(duration), 4)
        if self._steady:
            self.journal_event(
                "compile_warning", unexpected=True,
                why="backend compile after steady state: an accidental "
                    "retrace (see analysis/runtime.py)", **fields)
        else:
            self.journal_event("compile", **fields)

    # ---------------- per-round path (FedModel.__call__) -----------------
    def on_round(self, round_idx: int, client_ids, telemetry_vec,
                 num_examples, comm=None, scheduled=None) -> None:
        """Buffer one round's device metrics; materialize + journal the
        PREVIOUS round (one-round lag, so no per-round host sync).
        comm: optional (download_bytes, upload_bytes) round totals from
        the accountant — journaled on the round event and accumulated
        into the run_end cumulative pair. scheduled: optional [W]
        mask; zero slots are idle scheduler pads, excluded from the
        throughput tracker (telemetry/clients.update_round)."""
        now = self._clock()
        prev, self._pending = self._pending, (
            int(round_idx), np.asarray(client_ids), telemetry_vec,
            num_examples, now, comm, scheduled)
        if prev is not None:
            self._emit_round(prev, now - prev[4])

    def _record_comm(self, fields: dict, comm) -> None:
        if comm is None:
            return
        down, up = float(comm[0]), float(comm[1])
        self._cum_down_bytes += down
        self._cum_up_bytes += up
        self._comm_seen = True
        fields["down_bytes"] = down
        fields["up_bytes"] = up

    def _emit_round(self, rec, seconds: Optional[float]) -> None:
        round_idx, ids, vec, counts, _, comm, scheduled = rec
        counts_h = np.asarray(self._materialize(counts))
        if (self.tracker is not None and seconds is not None
                and seconds > 0):
            self.tracker.update_round(ids, counts_h, seconds,
                                      scheduled=scheduled)
        named = tmetrics.named(
            None if vec is None else np.asarray(
                self._materialize(vec), np.float32))
        if self.journal is not None:
            fields = {"round": round_idx}
            if named:
                fields["metrics"] = named
            if seconds is not None:
                fields["seconds"] = round(seconds, 6)
            self._record_comm(fields, comm)
            self.journal_event("round", **fields)
        elif comm is not None:
            self._record_comm({}, comm)
        # per-round boundary = the unscanned path's span boundary:
        # flush the stage spans this round produced as one batch
        self._flush_trace()
        self._check_trip(round_idx, named)

    def _check_trip(self, round_idx: int, named) -> None:
        """The finite-frontier watch (ISSUE 16): a non-finite watched
        metric journals a durable `numeric_trip` event and raises
        NumericTripError for the driver's rollback handler. Armed
        whenever telemetry metrics flow (no extra device work; every
        process trips identically since all gather the same metrics);
        disarmed during close() so a trailing flush cannot raise out
        of the shutdown path."""
        if not named or self._closed:
            return
        bad = [k for k in WATCHED_METRICS
               if k in named and not np.isfinite(named[k])]
        if not bad:
            return
        self.journal_event("numeric_trip", round=int(round_idx),
                           metrics=bad)
        if self.journal is not None:
            self._safe_write(self.journal.flush)
        raise NumericTripError(round_idx, bad)

    def discard_pending(self) -> None:
        """Drop the one-round-lag buffer WITHOUT journaling it — the
        rollback path: the buffered round belongs to the halted
        stream (and likely carries the same non-finite metrics that
        tripped), so emitting it after the rollback would double-
        count the trip against Config.max_numeric_rollbacks."""
        self._pending = None

    def flush(self) -> None:
        """Drain the one-round-lag buffer (end of epoch/run; before a
        deliberate crash boundary). The drained round has no interval
        measurement, so it journals without `seconds` and skips the
        tracker. Also barriers the journal's async writer queue (a
        no-op for the default synchronous journal), so a crash-
        boundary caller knows its records are on disk before it
        raises."""
        prev, self._pending = self._pending, None
        if prev is not None:
            self._emit_round(prev, None)
        self._flush_trace()
        if self.journal is not None:
            self._safe_write(self.journal.flush)

    def journal_flush(self) -> None:
        """Barrier ONLY the journal's async writer queue, leaving the
        one-round-lag metric buffer alone (draining it here would
        journal the pending round without an interval measurement and
        skip its tracker feeding). The write-ahead plan seal (ISSUE
        12, FedModel._flush_write_ahead) needs exactly this: sealed
        `schedule` records durable before dispatch, telemetry
        semantics untouched. A no-op for the default synchronous
        journal, whose events are durable when event() returns."""
        if self.journal is not None:
            self._safe_write(self.journal.flush)

    # ---------------- span path (FedModel.run_rounds) --------------------
    def on_span(self, first_round: int, ids_rows: np.ndarray,
                telemetry_rows: Optional[np.ndarray],
                counts_rows: np.ndarray,
                dispatch_s: float, block_s: float,
                comm_rows=None, scheduled_rows=None) -> None:
        """Consume one completed scanned span: host-materialized
        [N, W] ids/counts and [N, M] metric rows (the caller did the
        explicit span-boundary device_get). Journals one `span` event
        plus one `round` event per round, and feeds the tracker with
        the span-amortized per-round wall time. comm_rows: optional
        per-round (download_bytes, upload_bytes) totals (None entries
        for unaccounted rounds — FedModel.run_rounds(account=False)).
        scheduled_rows: optional per-round [W] masks whose zero slots
        are idle scheduler pads, excluded from the tracker."""
        # a pending per-round buffer holds an EARLIER round (mixed
        # per-round + scanned usage): drain it first so the journal's
        # round events stay strictly ordered
        self.flush()
        n = int(np.asarray(ids_rows).shape[0])
        per_round_s = (dispatch_s + block_s) / max(n, 1)
        if self.tracker is not None:
            for i in range(n):
                self.tracker.update_round(
                    ids_rows[i], counts_rows[i], per_round_s,
                    scheduled=(None if scheduled_rows is None
                               else scheduled_rows[i]))
        if self.journal is not None:
            batch = [("span", {"first_round": int(first_round),
                               "rounds": n,
                               "dispatch_s": round(dispatch_s, 6),
                               "block_s": round(block_s, 6)})]
            for i in range(n):
                fields = {"round": int(first_round) + i,
                          "seconds": round(per_round_s, 6)}
                if telemetry_rows is not None:
                    named = tmetrics.named(
                        np.asarray(telemetry_rows[i], np.float32))
                    if named:
                        fields["metrics"] = named
                if comm_rows is not None:
                    self._record_comm(fields, comm_rows[i])
                batch.append(("round", fields))
            # one append + fsync for the whole span's records
            self._safe_write(lambda: self.journal.events(batch))
        elif comm_rows is not None:
            for comm in comm_rows:
                self._record_comm({}, comm)
        # span-boundary graftscope flush: the span's stage spans (and
        # any writer-thread spans committed since the last boundary)
        # land as one batched trace event — one additional fsync
        self._flush_trace()
        # finite-frontier watch over the span's rows, in round order:
        # the FIRST tripped round raises (its journal records above
        # are already durable), matching the per-round path's boundary
        if telemetry_rows is not None:
            for i in range(n):
                self._check_trip(
                    int(first_round) + i,
                    tmetrics.named(np.asarray(telemetry_rows[i],
                                              np.float32)))

    # ---------------- profiler capture (--profile_spans) -----------------
    def span_profile_begin(self, span_idx: int) -> None:
        """Start a jax.profiler trace when `span_idx` enters the
        requested [A, B) window (called by scanloop before each span's
        dispatch). One contiguous capture covers the whole window."""
        if (self._spans is None or self._profiling
                or not (self._spans[0] <= span_idx < self._spans[1])):
            return
        os.makedirs(self._profile_dir, exist_ok=True)
        jax.profiler.start_trace(self._profile_dir)
        self._profiling = True
        self.journal_event("profile_start", span=span_idx,
                           dir=self._profile_dir)

    def span_profile_end(self, span_idx: int) -> None:
        """Stop the capture once the window's last span completed (the
        caller's run_rounds already forced device completion, so the
        trace covers the span's real device work)."""
        if not self._profiling or span_idx < self._spans[1] - 1:
            return
        jax.profiler.stop_trace()
        self._profiling = False
        self.journal_event("profile_stop", span=span_idx,
                           dir=self._profile_dir)

    # ---------------- lifecycle ------------------------------------------
    def close(self, **fields) -> None:
        """Drain buffers, stop a live profiler capture, detach the
        compile listener, and journal `run_end` with `fields`."""
        if self._closed:
            return
        self._closed = True
        self.flush()
        if self._profiling:
            jax.profiler.stop_trace()
            self._profiling = False
            self.journal_event("profile_stop", span=-1,
                               dir=self._profile_dir)
        _runtime.remove_compile_listener(self._on_compile)
        if self.journal is not None:
            if self._comm_seen:
                # cumulative accountant totals: the journal is
                # self-contained on communication cost (validated
                # against the per-round sums by validate_journal)
                fields.setdefault("down_bytes_total",
                                  self._cum_down_bytes)
                fields.setdefault("up_bytes_total", self._cum_up_bytes)
            self.journal_event("run_end", **fields)
            self.journal.close()
        if self._owns_trace:
            # the session enabled the global tracer; a leaked enable
            # would trace (and buffer) every later in-process run
            TRACE.disable()

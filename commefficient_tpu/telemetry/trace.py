"""graftscope: cross-thread round-lifecycle tracing (ISSUE 13).

The engine overlaps five concurrent actors per round — host staging,
device execution, the journal/checkpoint/spill writer threads (ISSUE
10/11), and the broadcast control plane (ISSUE 12) — but the journal
records only round-granularity events, so "where did this round's
120 ms go?" and "is the pipeline actually overlapping?" were
unanswerable without ad-hoc printf. This module is the measurement
substrate: monotonic-clock spans around every stage of the round
lifecycle, tagged with the correlation keys that stitch cross-thread
(and cross-controller) records into one timeline.

Design constraints, in order:

  * ALWAYS AVAILABLE, DEFAULT OFF. The global `TRACE` object exists
    unconditionally so instrumentation sites (`with TRACE.span(...)`)
    cost one attribute check + one call when disabled — no journal
    writes, no ring appends, no allocation beyond the shared no-op
    context manager. `--trace` (Config.trace) enables it.
  * ZERO TRACED-PROGRAM CHANGES. Every span brackets HOST code — a
    dispatch call, a queue wait, an fsync — never anything inside a
    jitted program. The three-round-programs contract, the
    graftaudit/graftmesh baselines, and transfer-guard cleanliness
    are untouched whether tracing is on or off (tests/test_trace.py
    pins ServerState bit-identity on vs off).
  * MONOTONIC CLOCK. Span timestamps come from time.monotonic() — a
    wall-clock (time.time) difference is not a duration (NTP steps;
    graftlint GL011). The journal's per-record `ts`/`mono` pair maps
    monotonic trace time back onto wall time for export.
  * BEST-EFFORT, BOUNDED. Spans buffer in per-thread rings (bounded;
    overflow drops-and-counts, never blocks) and flush as batched
    `trace` journal events at span boundaries — one fsync per flush,
    torn-tail rules intact, I/O failures warn-once like all
    telemetry (TelemetrySession._safe_write).

Span records are small dicts:

    {"name": <stage>, "t0": <monotonic s>, "dur": <s>,
     "thread": <thread name>, ...tags}

with the correlation tags:

    round   the producing round index (round_idx)
    span    the scanned-span index (the same counter --profile_spans
            selects on, so a jax.profiler capture of spans [A, B)
            correlates with the device_execute trace spans tagged
            span=A..B-1)
    seq     per-writer submission sequence number: a producer-side
            `*_enqueue` instant and the writer-thread `*_qwait` /
            `*_write` spans of one queued item share a `seq`, which is
            how a writer thread's work stitches back to the round that
            produced it
    q       queue depth observed at enqueue (writer back-pressure
            gauge; summarize() surfaces the max per writer)

The stage taxonomy (README "Tracing" has the full table): plan,
plan_install, stage, gather, round_dispatch, scatter, dispatch,
device_execute, collect, tier_spill, tier_restore, checkpoint,
journal_write, plus the per-writer {journal,checkpoint,state-spill}
_enqueue/_qwait/_write families.

Nested spans inherit their enclosing span's `round`/`span` tags
(thread-local stack), so e.g. a checkpoint writer enqueue recorded
inside the `checkpoint` span carries the checkpoint's round without
every call site re-plumbing indices.

`scripts/trace_export.py` converts a journal's trace events into
Chrome trace-event JSON loadable in Perfetto (one process row per
controller, one thread row per thread); `journal.summarize()` computes
the stage-level analytics block (per-stage p50/p95, inter-round
cadence histogram, writer queue-depth gauges, and the pipeline
overlap-efficiency metric device-busy/wall).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["TRACE", "Tracer", "device_busy_wall", "overlap_efficiency",
           "stage_stats"]

# tags inherited by nested spans / instants from the innermost open
# span on the same thread (correlation keys, not payload)
_INHERITED_TAGS = ("round", "span")


class _NullSpan:
    """Shared no-op context manager: the disabled-tracer fast path
    allocates nothing per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One open span: context manager that commits its record on exit.
    Pushed on the owning thread's open-span stack so nested spans and
    instants inherit its correlation tags."""

    __slots__ = ("_tracer", "rec", "_stack")

    def __init__(self, tracer: "Tracer", rec: dict, stack: list):
        self._tracer = tracer
        self.rec = rec
        self._stack = stack

    def __enter__(self):
        self.rec["t0"] = self._tracer._clock()
        self._stack.append(self.rec)
        return self

    def __exit__(self, *exc):
        t1 = self._tracer._clock()
        stack = self._stack
        if stack and stack[-1] is self.rec:
            stack.pop()
        rec = self.rec
        rec["dur"] = round(t1 - rec["t0"], 6)
        rec["t0"] = round(rec["t0"], 6)
        self._tracer._commit(rec)
        return False


class Tracer:
    """Per-thread ring buffers of monotonic-clock stage spans.

    Thread-safe by one small lock held only for ring append/drain —
    spans are committed a handful of times per ROUND, not per op, so
    contention is negligible and the lock keeps drain() exact (no
    torn hand-off with a writer thread mid-append).
    """

    def __init__(self, enabled: bool = False, controller: int = 0,
                 ring_size: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        self.enabled = bool(enabled)
        self.controller = int(controller)
        self.ring_size = int(ring_size)
        self._clock = clock
        self._lock = threading.Lock()
        # thread ident -> list of committed span records (the ring)
        self._rings: Dict[int, List[dict]] = {}
        self._dropped = 0
        # per-thread stack of OPEN span records (tag inheritance);
        # thread-local so no lock is needed on the span enter/exit path
        self._open = threading.local()

    # ---------------- recording ------------------------------------------
    def _thread_stack(self) -> list:
        stack = getattr(self._open, "stack", None)
        if stack is None:
            stack = self._open.stack = []
        return stack

    def _make_rec(self, name: str, tags: dict) -> Tuple[dict, list]:
        rec = {"name": str(name),
               "thread": threading.current_thread().name}
        stack = self._thread_stack()
        if stack:
            parent = stack[-1]
            for key in _INHERITED_TAGS:
                if key in parent and key not in tags:
                    rec[key] = parent[key]
        for k, v in tags.items():
            if v is not None:
                rec[k] = v
        return rec, stack

    def current_tags(self) -> dict:
        """The innermost open span's correlation tags on THIS thread
        (round/span), or {}. Writer submit paths capture these so the
        writer-thread spans of a queued item carry the producing
        round even though they run on another thread."""
        if not self.enabled:
            return {}
        stack = self._thread_stack()
        if not stack:
            return {}
        parent = stack[-1]
        return {k: parent[k] for k in _INHERITED_TAGS if k in parent}

    def span(self, name: str, **tags):
        """Context manager bracketing one stage; commits a span record
        with the enclosed wall (monotonic) duration on exit. The
        disabled path returns a shared no-op."""
        if not self.enabled:
            return _NULL_SPAN
        rec, stack = self._make_rec(name, tags)
        return _Span(self, rec, stack)

    def instant(self, name: str, **tags) -> None:
        """Zero-duration marker (e.g. a writer-queue enqueue, carrying
        its `seq`/`q` tags and the enclosing span's round)."""
        if not self.enabled:
            return
        rec, _ = self._make_rec(name, tags)
        rec["t0"] = round(self._clock(), 6)
        rec["dur"] = 0.0
        self._commit(rec)

    def record(self, name: str, t0: float, t1: float, **tags) -> None:
        """Commit a span with EXPLICIT monotonic endpoints — the
        dispatch/collect seam uses this to bracket device execution
        ([t_dispatched, t_blocked], measured where those instants
        naturally exist rather than where the record is written)."""
        if not self.enabled:
            return
        rec, _ = self._make_rec(name, tags)
        rec["t0"] = round(float(t0), 6)
        rec["dur"] = round(max(float(t1) - float(t0), 0.0), 6)
        self._commit(rec)

    def _commit(self, rec: dict) -> None:
        if not self.enabled:
            # a span that straddled disable (session close) drops
            return
        ident = threading.get_ident()
        with self._lock:
            ring = self._rings.get(ident)
            if ring is None:
                ring = self._rings[ident] = []
            if len(ring) >= self.ring_size:
                self._dropped += 1
                return
            ring.append(rec)

    # ---------------- draining / lifecycle --------------------------------
    def drain(self) -> Tuple[List[dict], int]:
        """Swap out every thread's ring; returns (spans sorted by t0,
        drops since the last drain). The flush path (TelemetrySession)
        batches the result into ONE `trace` journal event."""
        with self._lock:
            spans: List[dict] = []
            for ident in list(self._rings):
                ring = self._rings[ident]
                if ring:
                    spans.extend(ring)
                    self._rings[ident] = []
            dropped, self._dropped = self._dropped, 0
        spans.sort(key=lambda r: r.get("t0", 0.0))
        return spans, dropped

    def enable(self, controller: Optional[int] = None) -> None:
        if controller is not None:
            self.controller = int(controller)
        self.enabled = True

    def disable(self) -> None:
        """Turn tracing off and discard anything buffered (the session
        drains before disabling on a clean close)."""
        self.enabled = False
        with self._lock:
            self._rings.clear()
            self._dropped = 0


# The process-global tracer every instrumentation site records into.
# Default OFF: `attach_run_telemetry` enables it under Config.trace and
# the owning TelemetrySession disables it again at close, so tracing
# never leaks across in-process runs (tests) or into untraced ones.
TRACE = Tracer(enabled=False)


# ---------------- stage analytics (summarize()'s trace block) -----------

def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list — tiny,
    dependency-free (summarize() must not require numpy arrays of
    every stage)."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q / 100.0 * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def stage_stats(spans: List[dict]) -> dict:
    """Per-stage duration stats over trace span records: count,
    p50/p95 seconds, total seconds — the journal_summary block a perf
    investigation reads first."""
    by_stage: Dict[str, List[float]] = {}
    for rec in spans:
        name = rec.get("name")
        dur = rec.get("dur")
        if not isinstance(name, str) or not isinstance(dur, (int, float)):
            continue
        by_stage.setdefault(name, []).append(float(dur))
    out = {}
    for name in sorted(by_stage):
        durs = sorted(by_stage[name])
        out[name] = {
            "n": len(durs),
            "p50_s": round(_percentile(durs, 50), 6),
            "p95_s": round(_percentile(durs, 95), 6),
            "total_s": round(sum(durs), 6),
        }
    return out


def device_busy_wall(spans: List[dict]
                     ) -> Optional[Tuple[float, float]]:
    """(device-busy seconds, wall seconds) over ONE trace segment —
    spans whose monotonic t0 share a process lifetime (consumers must
    split at run_start before calling; mono bases differ across
    processes). Busy is the UNION of the `device_execute` spans'
    intervals (under --pipeline consecutive spans overlap — summing
    would overcount); wall is the extent of the whole segment. None
    when no device_execute spans (or no wall extent) exist."""
    dev = sorted((float(r["t0"]), float(r["t0"]) + float(r["dur"]))
                 for r in spans
                 if r.get("name") == "device_execute"
                 and isinstance(r.get("t0"), (int, float))
                 and isinstance(r.get("dur"), (int, float)))
    times = [float(r["t0"]) for r in spans
             if isinstance(r.get("t0"), (int, float))]
    ends = [float(r["t0"]) + float(r.get("dur", 0.0)) for r in spans
            if isinstance(r.get("t0"), (int, float))]
    if not dev or not times:
        return None
    wall = max(ends) - min(times)
    if wall <= 0:
        return None
    busy = 0.0
    cur_lo, cur_hi = dev[0]
    for lo, hi in dev[1:]:
        if lo > cur_hi:
            busy += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    busy += cur_hi - cur_lo
    return busy, wall


def overlap_efficiency(spans: List[dict]) -> Optional[float]:
    """Pipeline overlap efficiency: device-busy time / wall time over
    one trace segment. 1.0 means the device never waited on host
    staging or persistence; the sync baseline measured ~0.79x cadence
    at BENCH_r10 — this turns that one-off claim into a
    continuously-measured number. For multi-segment journals
    (resume/takeover), summarize() sums device_busy_wall per segment
    instead of calling this across segments."""
    bw = device_busy_wall(spans)
    if bw is None:
        return None
    busy, wall = bw
    return round(min(busy / wall, 1.0), 4)

"""Per-client throughput tracking: EMA examples/sec + participation.

The ROADMAP's two named scheduling openings — deadline estimation from
MEASURED per-client throughput (instead of the scripted/static work
fractions of utils/faults) and straggler-aware client sampling
(deprioritize chronically slow clients) — both need one substrate: a
per-client record of how fast each client actually processes examples,
fed from real round timings and surviving checkpoint/resume. This
module is that substrate.

Feeding: TelemetrySession hands the tracker one
(client_ids, examples_processed, round_seconds) triple per round, from
span-boundary metrics (scanned path: span wall time amortized over its
rounds) or inter-dispatch intervals (per-round path — a steady-state
approximation, since dispatch is async the interval converges to the
true round time once the device is the bottleneck). Dropped clients
arrive with zero examples: their participation is counted but their
EMA is untouched (a dead round says nothing about their speed).

Storage is SPARSE (ISSUE 9): only clients that have ever been sampled
own a row, so tracker memory and checkpoint bytes are
O(clients-ever-seen), never O(population) — at a million-client
population with sparse participation the dense arrays this replaced
were ~25 MB of host state per tracker and the same again in every
checkpoint, for rows that were all zero. Unseen clients read as
rate 0 / zero counts, exactly what the dense zeros encoded.

Persistence: `state_dict`/`load_state_dict` round-trip the sparse row
arrays bit-exactly (`ids` + per-row records; legacy dense captures
load transparently); utils/checkpoint embeds them under `thr_*` keys
(next to the fingerprint, so a resume into a different client
population fails loudly) and FedModel.load_state restores them —
crash->resume preserves every EMA bit-exactly
(tests/test_telemetry.py).

Determinism caveat: the RATES are wall-clock derived, so two runs of
the same seed produce different rates — the tracker informs
SCHEDULING, never the model update, keeping the round engine's
pure-(state, seed, round) contract intact.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# state_dict keys, fixed order (checkpoint serialization contract):
# `ids` maps each row to its global client id; the per-row arrays are
# aligned with it. Legacy captures lack `ids` and carry dense
# [num_clients] arrays instead — load_state_dict converts.
STATE_KEYS = ("ids", "rate", "participations", "completions",
              "busy_seconds")


class ClientThroughputTracker:
    """EMA examples/sec and participation accounting per SEEN client.

    Row semantics (rows exist only for clients ever sampled):

    rate[row]           EMA of the client's examples/sec over its
                        COMPLETED rounds (0.0 until the first
                        completion — callers must treat 0 as
                        "unmeasured", see estimate_round_seconds)
    participations[row] rounds the client was sampled into
    completions[row]    rounds the client actually processed examples in
    busy_seconds[row]   cumulative wall seconds of completed rounds

    `version` increments whenever any EMA value changes — the cheap
    staleness signal the alias sampler's rebuild check keys on
    (scheduler/policy.AliasTable)."""

    def __init__(self, num_clients: int, ema_decay: float = 0.9):
        if not 0.0 < ema_decay < 1.0:
            raise ValueError(
                f"ema_decay={ema_decay} must be in (0, 1)")
        self.num_clients = int(num_clients)
        self.ema_decay = float(ema_decay)
        self._slot: dict = {}                      # global id -> row
        # row storage: capacity-backed arrays with a live-row count
        # (`_n`), doubled on overflow — growing by concatenate per
        # first-seen client would make cumulative copy work QUADRATIC
        # in clients-ever-seen, on the host hot path of exactly the
        # million-client populations this module exists for
        self._n = 0
        self._ids = np.zeros((0,), np.int64)       # row -> global id
        self._rate = np.zeros((0,), np.float32)
        self._participations = np.zeros((0,), np.int64)
        self._completions = np.zeros((0,), np.int64)
        self._busy = np.zeros((0,), np.float64)
        # O(1) aggregates for the scheduler's survival estimate —
        # summing the row arrays per round would be O(seen), fine, but
        # these make the hot read constant-time
        self.total_participations = 0
        self.total_completions = 0
        self.version = 0

    # -- row bookkeeping --------------------------------------------------
    def _grow(self, need: int) -> None:
        """Ensure capacity for `need` live rows (geometric doubling —
        O(1) amortized append, O(seen) peak memory)."""
        cap = len(self._ids)
        if need <= cap:
            return
        new_cap = max(need, 2 * cap, 64)

        def grown(arr, dtype):
            out = np.zeros(new_cap, dtype)
            out[:self._n] = arr[:self._n]
            return out

        self._ids = grown(self._ids, np.int64)
        self._rate = grown(self._rate, np.float32)
        self._participations = grown(self._participations, np.int64)
        self._completions = grown(self._completions, np.int64)
        self._busy = grown(self._busy, np.float64)

    def _rows_for(self, ids: np.ndarray) -> np.ndarray:
        """Row indices for `ids`, allocating rows for first-seen
        clients (new rows zero-initialized — identical reads to the
        dense zeros they replace). Deduplicated: a repeated first-seen
        id must map to ONE row, or the extra row would sit orphaned in
        `_ids` forever. Ids outside [0, num_clients) raise — the dense
        arrays this storage replaced bounds-checked implicitly via
        fancy indexing, and a silently-allocated bogus row would
        corrupt state far from the caller's bug."""
        fresh, fresh_seen = [], set()
        for c in ids:
            c = int(c)
            if not 0 <= c < self.num_clients:
                raise ValueError(
                    f"client id {c} out of range for a "
                    f"{self.num_clients}-client population")
            if c not in self._slot and c not in fresh_seen:
                fresh.append(c)
                fresh_seen.add(c)
        if fresh:
            self._grow(self._n + len(fresh))
            for c in fresh:
                self._slot[c] = self._n
                self._ids[self._n] = c
                self._n += 1
        return np.array([self._slot[int(c)] for c in ids], np.int64)

    @property
    def seen_ids(self) -> np.ndarray:
        """Global ids of every client that owns a row (a copy)."""
        return self._ids[:self._n].copy()

    def update_round(self, client_ids, num_examples, round_seconds,
                     survivors: Optional[np.ndarray] = None,
                     scheduled: Optional[np.ndarray] = None) -> None:
        """Fold one round's measurements in.

        client_ids:    [W] global ids sampled into the round (assumed
                       distinct — the sampler draws without
                       replacement; duplicate ids would collapse to one
                       fancy-index write)
        num_examples:  [W] examples each slot actually processed (the
                       round engine already zeroes dropped clients and
                       truncates stragglers)
        round_seconds: wall-clock seconds this round took; <= 0 or None
                       skips the update (no timing signal)
        survivors:     optional [W] mask; zeroes num_examples for
                       callers whose counts don't already encode drops
        scheduled:     optional [W] mask; slots at 0 were IDLE pads
                       (scheduler over-provisioning below the compiled
                       width) and are EXCLUDED entirely — unlike a
                       dropped client, an idle pad was never asked to
                       work, so counting it as a participation would
                       depress the completion ratio the scheduler's
                       survival estimate reads (a self-reinforcing
                       over-provisioning error)
        """
        if round_seconds is None or not round_seconds > 0:
            return
        ids = np.asarray(client_ids, np.int64).reshape(-1)
        ex = np.asarray(num_examples, np.float64).reshape(-1)
        if scheduled is not None:
            keep = np.asarray(scheduled).reshape(-1) > 0
            ids, ex = ids[keep], ex[keep]
        if survivors is not None:
            surv = np.asarray(survivors).reshape(-1)
            if scheduled is not None:
                surv = surv[keep]
            ex = ex * (surv > 0)
        rows = self._rows_for(ids)
        # np.add.at, not fancy-index +=: callers are documented to pass
        # distinct ids, but if a duplicate ever slips through the
        # unbuffered add keeps the row counters consistent with the
        # O(1) totals — a fancy-index += would collapse the duplicate
        # and silently desync state_dict totals across a resume
        np.add.at(self._participations, rows, 1)
        self.total_participations += len(rows)
        done = ex > 0
        done_rows = rows[done]
        np.add.at(self._completions, done_rows, 1)
        self.total_completions += int(done.sum())
        np.add.at(self._busy, done_rows, float(round_seconds))
        if not done.any():
            return
        sample = (ex[done] / float(round_seconds)).astype(np.float32)
        prev = self._rate[done_rows]
        d = np.float32(self.ema_decay)
        # first completion seeds the EMA with the sample itself (an
        # EMA warmed from 0 would need ~1/(1-decay) rounds to stop
        # underestimating every client)
        first = self._completions[done_rows] <= 1
        self._rate[done_rows] = np.where(
            first, sample, d * prev + (np.float32(1.0) - d) * sample)
        self.version += 1

    # -- consumers (deadline estimation / straggler-aware sampling) -------
    def examples_per_sec(self, client_ids=None) -> np.ndarray:
        """EMA rates for `client_ids` (0.0 marks unmeasured/unseen
        clients). With client_ids=None materializes the DENSE
        [num_clients] vector — an O(population) convenience for tests
        and small populations; production consumers (alias sampler,
        deadline policy) always pass explicit ids or use
        `measured()`."""
        if client_ids is None:
            out = np.zeros(self.num_clients, np.float32)
            out[self._ids[:self._n]] = self._rate[:self._n]
            return out
        return self._lookup(self._rate, client_ids,
                            np.float32(0.0)).astype(np.float32)

    def participation_counts(self, client_ids) -> np.ndarray:
        return self._lookup(self._participations, client_ids, 0)

    def completion_counts(self, client_ids) -> np.ndarray:
        return self._lookup(self._completions, client_ids, 0)

    def _lookup(self, arr, client_ids, default):
        ids = np.asarray(client_ids, np.int64).reshape(-1)
        return np.array([arr[self._slot[int(c)]]
                         if int(c) in self._slot else default
                         for c in ids], arr.dtype)

    def measured(self) -> Tuple[np.ndarray, np.ndarray]:
        """(ids, rates) of every client with a nonzero EMA — the alias
        sampler's table basis; O(clients-ever-seen)."""
        m = self._rate[:self._n] > 0
        return (self._ids[:self._n][m].copy(),
                self._rate[:self._n][m].copy())

    def estimate_round_seconds(self, client_ids, num_examples,
                               cold_start_seconds: Optional[float]
                               = None) -> np.ndarray:
        """Expected seconds for each client to process its batch at its
        measured EMA rate — the deadline-estimation primitive.

        Cold-start contract (never NaN, never a zero-division):

          * zero examples estimate 0.0 seconds regardless of
            measurement state (no work takes no time);
          * an UNMEASURED client (no completed round yet) estimates
            +inf by default, so callers fall back to a prior instead
            of treating it as infinitely fast (the DeadlinePolicy's
            fallback is "never truncate the unmeasured");
          * with `cold_start_seconds` set, unmeasured clients instead
            get a CONSERVATIVE finite estimate: their batch at the
            SLOWEST measured rate in the population (a new client is
            assumed no faster than the slowest known one), or
            `cold_start_seconds` itself when nothing at all has been
            measured yet.
        """
        ex = np.asarray(num_examples, np.float64)
        r = self.examples_per_sec(client_ids).astype(np.float64)
        with np.errstate(divide="ignore"):
            out = np.where(r > 0, ex / np.maximum(r, 1e-30), np.inf)
        out = np.where(ex <= 0, 0.0, out)
        unmeasured = (r <= 0) & (ex > 0)
        if unmeasured.any() and cold_start_seconds is not None:
            rows = self._rate[:self._n]
            live = rows[rows > 0]
            if live.size:
                out[unmeasured] = ex[unmeasured] / float(live.min())
            else:
                out[unmeasured] = float(cold_start_seconds)
        return out

    # -- test / bench hook ------------------------------------------------
    def force(self, client_ids, rate=None, participations=None,
              completions=None, busy_seconds=None) -> None:
        """Directly set per-client records (rows allocated as needed).
        Replaces the dense-array writes tests used to do
        (`tracker.rate[:] = ...`); bumps `version` like a real
        measurement so alias-table rebuild logic sees the change."""
        rows = self._rows_for(
            np.asarray(client_ids, np.int64).reshape(-1))
        if rate is not None:
            self._rate[rows] = np.asarray(rate, np.float32)
            self.version += 1
        if participations is not None:
            new = np.asarray(participations, np.int64)
            self.total_participations += int(
                new.sum() - self._participations[rows].sum())
            self._participations[rows] = new
        if completions is not None:
            new = np.asarray(completions, np.int64)
            self.total_completions += int(
                new.sum() - self._completions[rows].sum())
            self._completions[rows] = new
        if busy_seconds is not None:
            self._busy[rows] = np.asarray(busy_seconds, np.float64)

    # -- checkpoint round-trip (bit-exact) --------------------------------
    def state_dict(self) -> dict:
        n = self._n
        return {
            "ids": self._ids[:n].copy(),
            "rate": self._rate[:n].copy(),
            "participations": self._participations[:n].copy(),
            "completions": self._completions[:n].copy(),
            "busy_seconds": self._busy[:n].copy(),
        }

    def load_state_dict(self, state: dict) -> None:
        rate = np.asarray(state["rate"], np.float32)
        if "ids" in state:
            ids = np.asarray(state["ids"], np.int64)
            if ids.size and ids.max() >= self.num_clients:
                raise ValueError(
                    f"throughput state tracks client id {ids.max()}; "
                    f"this run has {self.num_clients} clients — the "
                    f"checkpoint fingerprint should have rejected this "
                    f"resume")
        else:
            # legacy dense capture: every client had a row; keep only
            # the rows that carry information (any nonzero record) —
            # the dense zeros are exactly what absent rows read as
            if rate.shape[0] != self.num_clients:
                raise ValueError(
                    f"throughput state tracks {rate.shape[0]} clients; "
                    f"this run has {self.num_clients} — the checkpoint "
                    f"fingerprint should have rejected this resume")
            part = np.asarray(state["participations"], np.int64)
            comp = np.asarray(state["completions"], np.int64)
            busy = np.asarray(state["busy_seconds"], np.float64)
            seen = (rate > 0) | (part > 0) | (comp > 0) | (busy > 0)
            ids = np.where(seen)[0].astype(np.int64)
            state = {"rate": rate[seen], "participations": part[seen],
                     "completions": comp[seen], "busy_seconds": busy[seen]}
            rate = state["rate"]
        self._n = len(ids)
        self._ids = ids.copy()
        self._slot = {int(c): i for i, c in enumerate(ids)}
        self._rate = rate.copy()
        self._participations = np.asarray(
            state["participations"], np.int64).copy()
        self._completions = np.asarray(
            state["completions"], np.int64).copy()
        self._busy = np.asarray(
            state["busy_seconds"], np.float64).copy()
        self.total_participations = int(self._participations.sum())
        self.total_completions = int(self._completions.sum())
        self.version += 1

"""Per-client throughput tracking: EMA examples/sec + participation.

The ROADMAP's two named scheduling openings — deadline estimation from
MEASURED per-client throughput (instead of the scripted/static work
fractions of utils/faults) and straggler-aware client sampling
(deprioritize chronically slow clients) — both need one substrate: a
per-client record of how fast each client actually processes examples,
fed from real round timings and surviving checkpoint/resume. This
module is that substrate.

Feeding: TelemetrySession hands the tracker one
(client_ids, examples_processed, round_seconds) triple per round, from
span-boundary metrics (scanned path: span wall time amortized over its
rounds) or inter-dispatch intervals (per-round path — a steady-state
approximation, since dispatch is async the interval converges to the
true round time once the device is the bottleneck). Dropped clients
arrive with zero examples: their participation is counted but their
EMA is untouched (a dead round says nothing about their speed).

Persistence: `state_dict`/`load_state_dict` round-trip plain numpy
arrays bit-exactly; utils/checkpoint embeds them under `thr_*` keys
(next to the fingerprint, so a resume into a different client
population fails loudly) and FedModel.load_state restores them —
crash->resume preserves every EMA bit-exactly
(tests/test_telemetry.py).

Determinism caveat: the RATES are wall-clock derived, so two runs of
the same seed produce different rates — the tracker informs
SCHEDULING, never the model update, keeping the round engine's
pure-(state, seed, round) contract intact.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

# state_dict keys, fixed order (checkpoint serialization contract)
STATE_KEYS = ("rate", "participations", "completions", "busy_seconds")


class ClientThroughputTracker:
    """EMA examples/sec and participation accounting per client.

    rate[c]           EMA of client c's examples/sec over its COMPLETED
                      rounds (0.0 until the first completion — callers
                      must treat 0 as "unmeasured", see
                      estimate_round_seconds)
    participations[c] rounds client c was sampled into
    completions[c]    rounds client c actually processed examples in
    busy_seconds[c]   cumulative wall seconds of rounds c completed
    """

    def __init__(self, num_clients: int, ema_decay: float = 0.9):
        if not 0.0 < ema_decay < 1.0:
            raise ValueError(
                f"ema_decay={ema_decay} must be in (0, 1)")
        self.num_clients = int(num_clients)
        self.ema_decay = float(ema_decay)
        self.rate = np.zeros(self.num_clients, np.float32)
        self.participations = np.zeros(self.num_clients, np.int64)
        self.completions = np.zeros(self.num_clients, np.int64)
        self.busy_seconds = np.zeros(self.num_clients, np.float64)

    def update_round(self, client_ids, num_examples, round_seconds,
                     survivors: Optional[np.ndarray] = None,
                     scheduled: Optional[np.ndarray] = None) -> None:
        """Fold one round's measurements in.

        client_ids:    [W] global ids sampled into the round (assumed
                       distinct — the sampler draws without
                       replacement; duplicate ids would collapse to one
                       fancy-index write)
        num_examples:  [W] examples each slot actually processed (the
                       round engine already zeroes dropped clients and
                       truncates stragglers)
        round_seconds: wall-clock seconds this round took; <= 0 or None
                       skips the update (no timing signal)
        survivors:     optional [W] mask; zeroes num_examples for
                       callers whose counts don't already encode drops
        scheduled:     optional [W] mask; slots at 0 were IDLE pads
                       (scheduler over-provisioning below the compiled
                       width) and are EXCLUDED entirely — unlike a
                       dropped client, an idle pad was never asked to
                       work, so counting it as a participation would
                       depress the completion ratio the scheduler's
                       survival estimate reads (a self-reinforcing
                       over-provisioning error)
        """
        if round_seconds is None or not round_seconds > 0:
            return
        ids = np.asarray(client_ids, np.int64).reshape(-1)
        ex = np.asarray(num_examples, np.float64).reshape(-1)
        if scheduled is not None:
            keep = np.asarray(scheduled).reshape(-1) > 0
            ids, ex = ids[keep], ex[keep]
        if survivors is not None:
            surv = np.asarray(survivors).reshape(-1)
            if scheduled is not None:
                surv = surv[keep]
            ex = ex * (surv > 0)
        self.participations[ids] += 1
        done = ex > 0
        done_ids = ids[done]
        self.completions[done_ids] += 1
        self.busy_seconds[done_ids] += float(round_seconds)
        if not done.any():
            return
        sample = (ex[done] / float(round_seconds)).astype(np.float32)
        prev = self.rate[done_ids]
        d = np.float32(self.ema_decay)
        # first completion seeds the EMA with the sample itself (an
        # EMA warmed from 0 would need ~1/(1-decay) rounds to stop
        # underestimating every client)
        first = self.completions[done_ids] <= 1
        self.rate[done_ids] = np.where(
            first, sample, d * prev + (np.float32(1.0) - d) * sample)

    # -- consumers (deadline estimation / straggler-aware sampling) -------
    def examples_per_sec(self, client_ids=None) -> np.ndarray:
        """Current EMA rates (a copy); 0.0 marks unmeasured clients."""
        if client_ids is None:
            return self.rate.copy()
        return self.rate[np.asarray(client_ids, np.int64)].copy()

    def estimate_round_seconds(self, client_ids, num_examples,
                               cold_start_seconds: Optional[float]
                               = None) -> np.ndarray:
        """Expected seconds for each client to process its batch at its
        measured EMA rate — the deadline-estimation primitive.

        Cold-start contract (never NaN, never a zero-division):

          * zero examples estimate 0.0 seconds regardless of
            measurement state (no work takes no time);
          * an UNMEASURED client (no completed round yet) estimates
            +inf by default, so callers fall back to a prior instead
            of treating it as infinitely fast (the DeadlinePolicy's
            fallback is "never truncate the unmeasured");
          * with `cold_start_seconds` set, unmeasured clients instead
            get a CONSERVATIVE finite estimate: their batch at the
            SLOWEST measured rate in the population (a new client is
            assumed no faster than the slowest known one), or
            `cold_start_seconds` itself when nothing at all has been
            measured yet.
        """
        ids = np.asarray(client_ids, np.int64)
        ex = np.asarray(num_examples, np.float64)
        r = self.rate[ids].astype(np.float64)
        with np.errstate(divide="ignore"):
            out = np.where(r > 0, ex / np.maximum(r, 1e-30), np.inf)
        out = np.where(ex <= 0, 0.0, out)
        unmeasured = (r <= 0) & (ex > 0)
        if unmeasured.any() and cold_start_seconds is not None:
            measured = self.rate[self.rate > 0]
            if measured.size:
                out[unmeasured] = ex[unmeasured] / float(measured.min())
            else:
                out[unmeasured] = float(cold_start_seconds)
        return out

    # -- checkpoint round-trip (bit-exact) --------------------------------
    def state_dict(self) -> dict:
        return {
            "rate": self.rate.copy(),
            "participations": self.participations.copy(),
            "completions": self.completions.copy(),
            "busy_seconds": self.busy_seconds.copy(),
        }

    def load_state_dict(self, state: dict) -> None:
        rate = np.asarray(state["rate"], np.float32)
        if rate.shape[0] != self.num_clients:
            raise ValueError(
                f"throughput state tracks {rate.shape[0]} clients; "
                f"this run has {self.num_clients} — the checkpoint "
                f"fingerprint should have rejected this resume")
        self.rate = rate.copy()
        self.participations = np.asarray(
            state["participations"], np.int64).copy()
        self.completions = np.asarray(
            state["completions"], np.int64).copy()
        self.busy_seconds = np.asarray(
            state["busy_seconds"], np.float64).copy()

"""On-device round metrics: a fixed-shape, NAMED f32 vector computed
inside the jitted round.

The round engine's observability contract (ISSUE 4 tentpole): every
round produces one `[NUM_METRICS]` f32 vector — always the same shape
and dtype, so carrying it through `lax.scan` spans costs one stacked
`[N, NUM_METRICS]` output and never perturbs the treedef. Telemetry is
READ-ONLY observation: `round_vector` consumes values the round already
computed (losses, counts, the applied weight delta, the new
momentum/error state) and feeds nothing back, so a telemetry-on round
is bit-identical in `ServerState` to a telemetry-off round
(tests/test_telemetry.py proves it). Export to the host happens only at
span boundaries via explicit `jax.device_get` (telemetry.TelemetrySession),
so the transfer-guard contract holds with telemetry permanently on.

Metric semantics (indices are `METRIC_NAMES` order):

  train_loss        survivor-example-weighted mean client loss — dropped
                    clients and padding examples carry zero weight
  update_l2         l2 norm of the weight delta the round actually
                    applied (zero on a zero-survivor no-op round)
  error_l2          l2 norm of the NEW server error accumulator
                    (table-space for sketch mode, dense for true_topk;
                    zero when error_type == none)
  velocity_l2       l2 norm of the new server (virtual) momentum state
  survivors         number of sampled clients that completed the round
  examples          examples actually processed (straggler budgets and
                    dropout already applied — the FedNova denominator)
  realized_k        nonzero count of the applied weight delta: the
                    REALIZED top-k support, next to the analytic k the
                    accountant bills (ops/flat.py tie-widening and
                    sketch decode collisions make the two diverge)
  estimate_residual the sketch/top-k estimate-error proxy: the fraction
                    of accumulated update mass the compressed channel
                    FAILED to transmit this round,
                    error_l2 / (error_l2 + update_l2). Rising values
                    mean the compression budget (k, sketch geometry) is
                    falling behind the gradient — the knob PowerSGD-
                    style error feedback otherwise hides. 0 when the
                    mode has no error accumulator.
"""
from __future__ import annotations

import jax.numpy as jnp

METRIC_NAMES = (
    "train_loss",
    "update_l2",
    "error_l2",
    "velocity_l2",
    "survivors",
    "examples",
    "realized_k",
    "estimate_residual",
)
NUM_METRICS = len(METRIC_NAMES)
METRIC_INDEX = {name: i for i, name in enumerate(METRIC_NAMES)}

_EPS = 1e-12


def empty_vector() -> jnp.ndarray:
    """The telemetry-off placeholder: a zero-size leaf, so the
    RoundMetrics treedef stays stable per config and `lax.scan` stacks
    it for free."""
    return jnp.zeros((0,), jnp.float32)


def round_vector(losses, counts, delta, verror, vvelocity,
                 survivors) -> jnp.ndarray:
    """[NUM_METRICS] f32 from values the round already computed.

    losses:    [W] per-client mean losses
    counts:    [W] examples actually processed per client (dropped
               clients already zeroed)
    delta:     [D] applied weight update (new_ps - old_ps)
    verror:    new server error accumulator (any shape; may be size 0)
    vvelocity: new server momentum state (any shape; may be size 0)
    survivors: scalar survivor count (traced or static)

    Pure jnp — trace-safe under jit/scan/shard_map, no host touches.
    """
    counts = counts.astype(jnp.float32)
    total = counts.sum()
    train_loss = (losses * counts).sum() / jnp.maximum(total, 1.0)
    update_l2 = jnp.sqrt(jnp.sum(delta * delta))
    error_l2 = jnp.sqrt(jnp.sum(verror.astype(jnp.float32) ** 2))
    velocity_l2 = jnp.sqrt(jnp.sum(vvelocity.astype(jnp.float32) ** 2))
    realized_k = jnp.sum(delta != 0).astype(jnp.float32)
    estimate_residual = error_l2 / (error_l2 + update_l2 + _EPS)
    return jnp.stack([
        train_loss,
        update_l2,
        error_l2,
        velocity_l2,
        jnp.asarray(survivors, jnp.float32),
        total,
        realized_k,
        estimate_residual,
    ])


def named(vec) -> dict:
    """Host-side convenience: {metric name: float} from one materialized
    [NUM_METRICS] vector (or a no-op {} for a zero-size placeholder)."""
    if vec is None or getattr(vec, "size", 0) == 0:
        return {}
    return {name: float(vec[i]) for i, name in enumerate(METRIC_NAMES)}

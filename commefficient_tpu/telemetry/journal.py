"""Structured run journal: an append-only JSONL event log in the run
dir.

Every record is one JSON object per line with three mandatory fields —
`v` (schema version), `event` (record kind), `ts` (wall-clock epoch
seconds) — plus a monotonic-clock `mono` twin of `ts` and
kind-specific payload. `ts` is for humans and cross-machine
correlation; `mono` is what DURATIONS are derived from (inter-record
deltas of `ts` are not durations — an NTP step mid-run corrupts them;
graftlint GL011 holds that line in code, `mono` holds it in the
record format). `mono` values share a base only within one process
lifetime: consumers must reset delta tracking at each `run_start`. One schema serves every
producer: training runs (round/span metrics, checkpoint saves, XLA
compile events, retry attempts, injected faults), bench harnesses
(bench.py / benchmarks/profile_round.py append their digests as
`bench_digest` / `profile_digest` events), and future tooling, so a
perf investigation reads ONE record format instead of correlating
stdout tables with BENCH_*.json by hand.

Durability: appends route through utils/atomic_io.atomic_append_line
(flush + fsync per record); a preemption can tear at most the final
line, which `read_journal`/`validate_journal` detect and report
without losing committed records. Only the coordinator of a
multi-controller run writes (drivers construct the journal behind
`mh.is_coordinator()`).

Known event kinds written by the framework (all optional-fielded;
consumers must tolerate kinds they don't know):

  run_start / run_end     driver lifecycle, config snapshot / ok flag;
                          run_end also carries cumulative
                          down_bytes_total / up_bytes_total when the
                          accountant fed the session
  round                   one federated round: `round` index, optional
                          `metrics` dict named per telemetry.metrics.
                          METRIC_NAMES, optional `seconds`, optional
                          down_bytes / up_bytes accountant totals
  schedule                one round's scheduler decision
                          (commefficient_tpu/scheduler): sampler,
                          n_sampled, optional deadline_s /
                          est_round_s / expected_round_s /
                          truncated_slots
  state_tier              tiered client-state residency deltas
                          (ISSUE 11, federated/statestore): working-
                          set hits/misses, spill/restore counts and
                          bytes since the last record, plus resident
                          row count and working_set size; carries
                          `round` (per-round path) or `first_round` +
                          `rounds` (span path)
  span                    one scanned span: first_round, rounds,
                          dispatch_s (host staging + dispatch),
                          block_s (device completion wait)
  epoch                   driver epoch summary row
  checkpoint              one rotated save: path, seconds
  compile / compile_warning   XLA backend compile (via the
                          analysis/runtime listener); the _warning
                          variant marks a compile AFTER steady state —
                          an unexpected retrace
  retry                   one utils/retry backoff attempt
  injected_fault          a utils/faults InjectedFault about to raise
  profile_start / profile_stop   jax.profiler capture of operator-
                          selected spans (--profile_spans)
  trace                   one batched flush of graftscope stage spans
                          (ISSUE 13, telemetry/trace.py): `spans` is a
                          list of {name, t0 (monotonic s), dur,
                          thread, ...correlation tags}, `controller`
                          the recording controller, `dropped` the
                          ring-overflow count — the record
                          scripts/trace_export.py turns into a
                          Perfetto-loadable Chrome trace and
                          summarize() turns into per-stage p50/p95 +
                          overlap efficiency
  screened                value-fault screening (ISSUE 16,
                          federated/round `update_screen`): one round
                          admitted fewer clients than survived —
                          `round`, `n_screened` (clients excluded by
                          the in-round admission mask), `kind`
                          ("finite" or "norm")
  aggregator              Byzantine-robust aggregation (ISSUE 17,
                          federated/round `Config.aggregator`): one
                          round's robust-reduction stats — `round`,
                          `aggregator` (coord_median / trimmed_mean /
                          norm_clip), `n_trimmed` (mean clients
                          trimmed per sketch cell), `n_clipped`
                          (clients norm-clipped), `residual_l2`
                          (robust-vs-mean aggregate distance; -1.0
                          when non-finite), `n_contrib`
  screen_adapt            adaptive screening (ISSUE 17, scheduler
                          AdaptiveScreenController): the norm-screen
                          multiplier moved — `round`, `old_mult`,
                          `new_mult`, `rate` (observed screened
                          fraction), `target`
  control                 controller bank (ISSUE 20, control/): one
                          plan-riding controller adjusted its value —
                          `round`, `controller` (a name registered in
                          analysis.domains.CONTROL_FIELDS), `signal`
                          (the observed metric), `old`, `new`,
                          `clamped` (the bound bit). The trajectory a
                          crash-resume/takeover replay must reproduce
                          bit-exactly from the plan stream
  numeric_trip            the finite-frontier watch tripped: a
                          watched telemetry metric (update_l2 /
                          error_l2) went non-finite — `round`,
                          `metrics` (the offending metric names).
                          Opens a new validation SEGMENT like
                          run_start: the driver rolls back to the
                          newest finite checkpoint and legitimately
                          replays rounds after this record
  state_quarantine        a checksummed state-tier chunk failed
                          verification at restore time
                          (federated/statestore) and the row was
                          re-initialized from its init base —
                          `client`, `field`
  bench_digest / profile_digest  bench harness result records
  audit_digest            graftaudit's static cost report
                          (analysis/audit): sha256 `digest`,
                          per-program `programs` {flops, hbm_bytes},
                          the traced `geometry`, and the finding count
  mesh_audit_digest       graftmesh's per-link collective report
                          (analysis/shardaudit): sha256 `digest`,
                          per-program `programs` {ici_bytes,
                          dcn_bytes, dcn_collectives}, the `meshes`
                          link models, geometry, finding count
  sync_audit_digest       graftsync's concurrency-audit report
                          (analysis/syncaudit): 64-hex sha256
                          `digest` (bit-identical across runs),
                          per-rule `rules` counts, the `registry`
                          sizes (shared-state guards / ordering
                          edges), and the finding count
  num_audit_digest        graftnum's numerics-audit report
                          (analysis/numaudit): 64-hex sha256
                          `digest` (bit-identical across runs),
                          per-rule NU `rules` counts, per-program
                          `ulp` worst-case reassociation bounds, and
                          the finding count
  privacy                 differential privacy (ISSUE 19, dp_sketch
                          mode): one committed round's cumulative
                          Rényi-DP budget — `round`, `epsilon`
                          (cumulative; never decreases within a
                          segment), `sigma` (noise multiplier),
                          `clip` (per-client l2 bound), `delta`
  compressor              one committed round's compressor billing
                          (ISSUE 19, compress/ plugins): `round`,
                          `mode`, `wire_bytes` (the plugin's static
                          per-client wire geometry), `up_bytes` (the
                          round's accounted upload total) —
                          summarize() folds these into the per-mode
                          bytes-on-wire table
"""
from __future__ import annotations

import json
import math
import os
import queue
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from commefficient_tpu.analysis.domains import CONTROL_FIELDS
from commefficient_tpu.telemetry.trace import (
    TRACE, device_busy_wall, stage_stats,
)
from commefficient_tpu.utils.atomic_io import atomic_append_lines

SCHEMA_VERSION = 1

# fields every record must carry to be schema-valid
REQUIRED_FIELDS = ("v", "event", "ts")


def _jsonable(obj):
    """json.dumps default hook: numpy scalars/arrays -> python."""
    if isinstance(obj, np.bool_):
        return bool(obj)
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError(f"not JSON-serializable: {type(obj).__name__}")


# strict-JSON sentinels for non-finite floats (see _finite)
NONFINITE = {"nan": "NaN", "inf": "Infinity", "-inf": "-Infinity"}


def _finite(obj):
    """Replace non-finite floats with their string sentinels ("NaN",
    "Infinity", "-Infinity"), recursively. Python's json module would
    happily emit bare `NaN` tokens (allow_nan defaults True) — lines
    no strict JSONL consumer (jq, Go/Rust/JS parsers) accepts; a
    diverging run's train_loss is exactly when the journal matters
    most, so the value is preserved as a recoverable string instead of
    dropped or left spec-invalid."""
    if isinstance(obj, float) and not np.isfinite(obj):
        return NONFINITE[repr(obj)]
    if isinstance(obj, np.floating) and not np.isfinite(obj):
        return NONFINITE[repr(float(obj))]
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


# inverse of NONFINITE: the exact sentinel strings _finite writes,
# mapped back to the float values they stood for
NONFINITE_INVERSE = {"NaN": math.nan, "Infinity": math.inf,
                     "-Infinity": -math.inf}


def _unfinite(obj):
    """Inverse of `_finite`, applied by `read_journal` (ISSUE 16
    satellite): the exact sentinel strings "NaN" / "Infinity" /
    "-Infinity" round-trip back to floats, recursively, so consumers
    (summarize, the rollback drill's resume-equivalence check,
    np.isfinite over metrics) see numbers, not strings. Only the
    three exact sentinels convert — every other string passes
    through untouched. Dict KEYS are never rewritten."""
    if isinstance(obj, str):
        return NONFINITE_INVERSE.get(obj, obj)
    if isinstance(obj, dict):
        return {k: _unfinite(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unfinite(v) for v in obj]
    return obj


class RunJournal:
    """Append-only JSONL writer for one run.

    Construction creates the parent directory but writes nothing; the
    first `event()` call creates the file. In the default synchronous
    mode the object is stateless beyond its path — safe to
    reconstruct (e.g. `append_event`) and to leave unclosed; every
    record is durable as soon as `event` returns.

    async_writer=True (ISSUE 10, Config.pipeline) moves the
    flush+fsync onto a bounded-queue writer thread: `event`/`events`
    SERIALIZE the record on the caller's thread (so later mutation of
    passed values cannot corrupt it) and enqueue the finished lines;
    one daemon thread drains the queue strictly FIFO through the same
    `atomic_append_lines` path, so record content, ordering, batching
    (a span's records stay ONE queued fsync) and the torn-tail seal
    are byte-identical to the synchronous mode — only durability
    timing changes. The queue is bounded (a dead disk back-pressures
    rather than ballooning memory); `flush()` blocks until everything
    queued is durable and `close()` flushes then stops the thread —
    the crash-drill path (drivers close the session in `finally`)
    drains exactly like a clean shutdown. Writer-side I/O failures
    keep the best-effort contract: warn once, keep training."""

    _SENTINEL = object()

    def __init__(self, path: str, run_id: str = "",
                 clock: Callable[[], float] = time.time,
                 mono_clock: Callable[[], float] = time.monotonic,
                 async_writer: bool = False, max_queue: int = 256,
                 drain_timeout: float = 0.0):
        self.path = path
        self.run_id = run_id
        self._clock = clock
        self._mono = mono_clock
        # graftscope correlation (ISSUE 13): per-journal submission
        # sequence — an async append's producer-side enqueue instant
        # and its writer-thread qwait/write spans share a `seq`
        self._seq = 0
        # writer-thread watchdog (ISSUE 12 satellite): flush()/close()
        # deadline in seconds; 0 = wait forever (the old behavior)
        self._drain_timeout = float(drain_timeout)
        # a torn tail can only predate this writer's first append —
        # seal-check once, then skip the per-record read
        self._tail_checked = False
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._q: Optional["queue.Queue"] = None
        self._thread = None
        self._warned = False
        if async_writer:
            self._q = queue.Queue(maxsize=max(max_queue, 1))
            self._thread = threading.Thread(
                target=self._drain_loop, args=(self._q,),
                name="journal-writer", daemon=True)
            self._thread.start()

    def _record(self, kind: str, fields: dict) -> dict:
        # dual timestamps (ISSUE 13 satellite): `ts` stays the human/
        # cross-machine wall clock, `mono` is the monotonic twin every
        # duration derivation (cadence stats, bench gap histograms)
        # must use — wall-clock deltas are NTP-step-hazardous
        rec = {"v": SCHEMA_VERSION, "event": str(kind),
               "ts": round(float(self._clock()), 6),
               "mono": round(float(self._mono()), 6)}
        if self.run_id:
            rec["run_id"] = self.run_id
        rec.update(fields)
        return rec

    def _append(self, lines, check_tail: bool) -> None:
        atomic_append_lines(self.path, lines, check_tail=check_tail)

    def _drain_loop(self, q: "queue.Queue") -> None:
        # the queue rides in as an argument: close() detaches self._q
        # before the final join, and the loop must keep draining the
        # ORIGINAL queue through that handoff
        while True:
            item = q.get()
            try:
                if item is self._SENTINEL:
                    return
                lines, check_tail, enq_mono, seq, tags = item
                if enq_mono is not None:
                    # queue-wait span: enqueue -> dequeue, the
                    # back-pressure interval graftscope charges to
                    # this writer (same `seq` as the producer-side
                    # journal_enqueue instant)
                    TRACE.record("journal_qwait", enq_mono,
                                 time.monotonic(), seq=seq, **tags)
                try:
                    if enq_mono is not None:
                        with TRACE.span("journal_write", seq=seq,
                                        **tags):
                            self._append(lines, check_tail)
                    else:
                        self._append(lines, check_tail)
                except (OSError, ValueError) as e:
                    # best-effort like the sync path's _safe_write
                    # wrapper: observability must never kill training
                    if not self._warned:
                        print(f"journal writer: append failed ({e}); "
                              f"further failures silent")
                        self._warned = True
            finally:
                q.task_done()

    def _emit(self, lines, trace_tags: Optional[dict] = None) -> None:
        """Write or enqueue serialized lines. `trace_tags`: graftscope
        correlation tags ({} = trace with no tags, None = do NOT trace
        this append — the flush of `trace` events themselves, which
        would otherwise self-generate one span per flush forever)."""
        check_tail = not self._tail_checked
        self._tail_checked = True
        traced = trace_tags is not None and TRACE.enabled
        if self._q is None:
            if traced:
                with TRACE.span("journal_write", **trace_tags):
                    self._append(lines, check_tail)
            else:
                self._append(lines, check_tail)
            return
        if traced:
            seq, self._seq = self._seq, self._seq + 1
            TRACE.instant("journal_enqueue", seq=seq,
                          q=self._q.qsize(), **trace_tags)
            self._q.put((list(lines), check_tail,
                         time.monotonic(), seq, dict(trace_tags)))
        else:
            self._q.put((list(lines), check_tail, None, 0, {}))

    @staticmethod
    def _tags_of(recs) -> Optional[dict]:
        """Correlation tags for one append: the first record's round
        index (round or first_round), or untagged. `trace` records
        return None — their own appends are never traced (see
        _emit)."""
        if any(r.get("event") == "trace" for r in recs):
            return None
        for r in recs:
            for key in ("round", "first_round"):
                v = r.get(key)
                if isinstance(v, int):
                    return {"round": v}
        return {}

    def event(self, kind: str, /, **fields) -> dict:
        """Append one record; returns the dict that was written.
        `kind` is positional-only: the `screened` event (ISSUE 16)
        carries a FIELD named `kind`, which must stay usable as a
        keyword."""
        rec = self._record(kind, fields)
        self._emit((json.dumps(_finite(rec), default=_jsonable),),
                   trace_tags=self._tags_of((rec,)))
        return rec

    def events(self, batch) -> List[dict]:
        """Append many records — `batch` is (kind, fields) pairs — with
        ONE flush+fsync for the lot. The span-boundary path uses this:
        a span's N round records are produced at the same instant, so
        per-record fsyncs would buy no durability, only a host stall
        proportional to span length. Under the async writer the whole
        batch rides the queue as ONE item — still one fsync."""
        recs = [self._record(kind, fields) for kind, fields in batch]
        self._emit([json.dumps(_finite(r), default=_jsonable)
                    for r in recs],
                   trace_tags=self._tags_of(recs))
        return recs

    def flush(self) -> None:
        """Block until every queued record is durable (async mode); a
        no-op in synchronous mode, where `event` already fsynced. The
        crash-boundary writers (FedModel._journal_fault) call this so
        an injected_fault record is on disk before the raise. With a
        `drain_timeout`, a hung writer raises TimeoutError naming the
        journal (utils/watchdog) instead of hanging the caller."""
        if self._q is not None:
            from commefficient_tpu.utils.watchdog import drain_queue
            drain_queue(self._q, self._drain_timeout, "journal")

    def close(self) -> None:
        """Drain and stop the writer thread (async mode); in sync mode
        there is no buffered state — kept so callers can treat the
        journal like a file handle. Idempotent. Honors the
        drain_timeout watchdog like flush()."""
        if self._q is not None:
            from commefficient_tpu.utils.watchdog import drain_queue
            q, self._q = self._q, None
            drain_queue(q, self._drain_timeout, "journal")
            q.put(self._SENTINEL)
            self._thread.join()
            self._thread = None


def append_event(path: str, kind: str, /, **fields) -> dict:
    """One-shot append for producers without a long-lived journal
    (bench harness digests)."""
    return RunJournal(path).event(kind, **fields)


# ---------------- reading + invariant validation -------------------------

def read_journal(path: str,
                 counters: Optional[dict] = None
                 ) -> Tuple[List[dict], List[str]]:
    """Parse a journal file. Returns (records, problems): records are
    the successfully parsed lines in order; problems are human-readable
    descriptions of malformed lines that invalidate the journal.

    Corruption tolerance (ISSUE 12 satellite): a torn FINAL line (the
    shape a preemption mid-append produces) is reported as a problem
    but does not invalidate the committed records before it — the
    original contract. Corrupt INTERIOR lines — possible since the
    PR-10 async batch writer can die mid-batch, and a sealed torn tail
    becomes interior once a resumed run appends past it — are SKIPPED
    AND COUNTED rather than treated as validation failures: every
    parseable record still reads, and the count is surfaced through
    `counters` (key "corrupt_interior", plus "corrupt_lines" detailing
    line numbers) so `summarize()` can report it. Pass a dict as
    `counters` to receive the counts; the (records, problems) return
    shape is unchanged for the many existing callers."""
    records: List[dict] = []
    problems: List[str] = []
    skipped: List[int] = []
    with open(path) as f:
        lines = f.read().splitlines()

    def _skip_or_problem(i: int, desc: str) -> None:
        if i == len(lines):
            # the final line: the one torn shape a clean-history
            # journal can have — report it, the committed prefix
            # stands
            problems.append(f"line {i}: {desc} (torn tail?)")
        else:
            skipped.append(i)

    for i, line in enumerate(lines, 1):
        if not line.strip():
            _skip_or_problem(i, "blank line")
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            _skip_or_problem(i, "not valid JSON")
            continue
        if not isinstance(rec, dict):
            _skip_or_problem(i, "not a JSON object")
            continue
        records.append(_unfinite(rec))
    if counters is not None:
        counters["corrupt_interior"] = len(skipped)
        counters["corrupt_lines"] = list(skipped)
    return records, problems


def validate_journal(path: str,
                     counters: Optional[dict] = None
                     ) -> Tuple[List[dict], List[str]]:
    """Journal invariants as a checkable function (shared by
    scripts/journal_summary.py and tests/test_telemetry.py):

      * every line parses as a JSON object carrying v/event/ts;
      * `v` matches SCHEMA_VERSION;
      * `round` events carry an integer `round` and never repeat a
        round index WITHIN one run segment (a duplicate means two code
        paths journaled the same round);
      * `round` indices are strictly increasing within a segment;
      * `metrics` payloads (when present) are {str: number} dicts;
      * `down_bytes`/`up_bytes` (when present) are non-negative
        numbers, and a segment's `run_end` cumulative
        down_bytes_total/up_bytes_total covers at least the sum of its
        journaled per-round totals (accounting.py's per-round and
        cumulative views must agree);
      * `schedule` events carry an integer `round` and a `sampler`
        name; their optional deadline_s/est_round_s payloads are
        non-negative numbers;
      * `state_tier` events (tiered client state, ISSUE 11) carry
        non-negative integer hits/misses/spills/restores and
        non-negative spill_bytes/restore_bytes/resident/working_set —
        the residency record the BENCH_r11 working-set table reads;
      * `trace` events (graftscope, telemetry/trace.py) carry a list
        `spans` of objects each with a string `name`, string
        `thread`, numeric non-negative `t0` (monotonic seconds) and
        `dur`; optional `dropped` must be a non-negative integer —
        the record trace_export.py and the stage analytics read, so
        its shape must not rot;
      * `mono` (when present) is a non-negative number — the
        monotonic twin of `ts` durations are derived from;
      * `audit_digest` events (graftaudit cost reports) carry a
        non-empty string `digest` and a `programs` object mapping each
        audited program to non-negative numeric flops/hbm_bytes — the
        record a cost-regression investigation greps for, so its shape
        must not rot;
      * `mesh_audit_digest` events (graftmesh per-link reports) carry
        the same digest/programs shape with non-negative numeric
        ici_bytes/dcn_bytes/dcn_collectives per program — the record
        the million-client refactor's before/after comm table reads;
      * `sync_audit_digest` events (graftsync concurrency reports,
        analysis/syncaudit) carry a 64-hex string `digest`, a `rules`
        object mapping each SY rule to a non-negative integer count,
        and a non-negative integer `findings` — the record tier1's
        sync step journals, so its shape must not rot;
      * `num_audit_digest` events (graftnum numerics reports,
        analysis/numaudit) carry the same 64-hex `digest` / `rules`
        counts / optional `findings` shape plus a `ulp` object
        mapping each audited program to a non-negative integer
        worst-case reassociation bound — the record tier1's NUM step
        journals, so its shape must not rot;
      * `screened` events (ISSUE 16 value-fault admission) carry an
        integer `round`, a non-negative integer `n_screened`, and a
        non-empty string `kind`;
      * `aggregator` events (ISSUE 17 robust aggregation) carry an
        integer `round`, a non-empty string `aggregator`, numeric
        `n_trimmed`/`residual_l2`, and non-negative integers
        `n_clipped`/`n_contrib`;
      * `screen_adapt` events (ISSUE 17 adaptive screening) carry an
        integer `round` and numeric `old_mult`/`new_mult`/`rate`/
        `target`, with both multipliers positive;
      * `privacy` events (ISSUE 19 differential privacy) carry an
        integer `round`, a non-negative numeric `epsilon` that never
        DECREASES within a run segment (the RDP budget only
        accumulates), positive `sigma`/`clip`, and `delta` in (0, 1);
      * `compressor` events (ISSUE 19 compressor plugins) carry an
        integer `round`, a non-empty string `mode`, and non-negative
        numeric `wire_bytes`/`up_bytes`;
      * `numeric_trip` events carry an integer `round` and a list of
        metric-name strings `metrics`; a trip also opens a new run
        SEGMENT (see below) — the driver rolls back and replays;
      * `state_quarantine` events carry a non-negative integer
        `client` and a non-empty string `field`.

    A `run_start` event opens a new run SEGMENT and resets the round
    tracking: a preempted run resumed with the same --journal_path
    legitimately replays rounds journaled after its last checkpoint
    (its run_start carries `resumed_round`), so cross-segment repeats
    are healthy history, not violations.

    Corrupt INTERIOR lines are skipped-and-counted, not violations
    (read_journal; the PR-10 async batch writer can die mid-batch) —
    pass a `counters` dict to receive the count for summarize().

    Returns (records, problems); an empty problems list means the
    journal is valid."""
    records, problems = read_journal(path, counters=counters)
    seen_rounds = set()
    last_round = None
    seg_down = seg_up = 0.0
    last_epsilon = None

    def _comm_field(rec, n, field):
        """Validate one byte-total field; returns its value or None."""
        v = rec.get(field)
        if v is None:
            return None
        if not isinstance(v, (int, float)) or v < 0:
            problems.append(
                f"record {n}: `{field}` must be a non-negative "
                f"number (got {v!r})")
            return None
        return float(v)

    for n, rec in enumerate(records, 1):
        if rec.get("event") == "run_start":
            seen_rounds = set()
            last_round = None
            seg_down = seg_up = 0.0
            last_epsilon = None
        if rec.get("event") == "numeric_trip":
            # finite-frontier rollback (ISSUE 16): the driver walks
            # back to the newest finite checkpoint and REPLAYS rounds
            # after this record — round repeats across a trip are
            # healthy history, exactly like a resume's run_start.
            # Byte accumulation is NOT reset: the accountant keeps
            # counting across the rollback, so run_end totals still
            # cover every journaled per-round sum including replays.
            # The epsilon tracker IS reset: epsilon is a pure function
            # of the committed-round count, so replayed rounds
            # legitimately re-journal the lower values of the window.
            seen_rounds = set()
            last_round = None
            last_epsilon = None
        for field in REQUIRED_FIELDS:
            if field not in rec:
                problems.append(f"record {n}: missing `{field}`")
        v = rec.get("v")
        if v is not None and v != SCHEMA_VERSION:
            problems.append(
                f"record {n}: schema version {v!r} != {SCHEMA_VERSION}")
        if not isinstance(rec.get("ts", 0.0), (int, float)):
            problems.append(f"record {n}: non-numeric `ts`")
        mono = rec.get("mono")
        if mono is not None and not (isinstance(mono, (int, float))
                                     and mono >= 0):
            problems.append(
                f"record {n}: `mono` must be a non-negative number "
                f"(got {mono!r})")
        if rec.get("event") == "trace":
            spans = rec.get("spans")
            if not isinstance(spans, list):
                problems.append(
                    f"record {n}: trace event `spans` is not a list")
            else:
                for j, sp in enumerate(spans):
                    if not isinstance(sp, dict):
                        problems.append(
                            f"record {n}: trace span {j} is not an "
                            "object")
                        continue
                    for field in ("name", "thread"):
                        if not isinstance(sp.get(field), str):
                            problems.append(
                                f"record {n}: trace span {j} "
                                f"`{field}` must be a string (got "
                                f"{sp.get(field)!r})")
                    for field in ("t0", "dur"):
                        v2 = sp.get(field)
                        if not (isinstance(v2, (int, float))
                                and v2 >= 0):
                            problems.append(
                                f"record {n}: trace span {j} "
                                f"`{field}` must be a non-negative "
                                f"number (got {v2!r})")
            d2 = rec.get("dropped")
            if d2 is not None and not (isinstance(d2, int)
                                       and d2 >= 0):
                problems.append(
                    f"record {n}: trace `dropped` must be a "
                    f"non-negative integer (got {d2!r})")
        if rec.get("event") == "schedule":
            if not isinstance(rec.get("round"), int):
                problems.append(
                    f"record {n}: schedule event without an integer "
                    f"`round` (got {rec.get('round')!r})")
            if not isinstance(rec.get("sampler"), str):
                problems.append(
                    f"record {n}: schedule event without a `sampler` "
                    "name")
            for field in ("deadline_s", "est_round_s",
                          "expected_round_s"):
                _comm_field(rec, n, field)
        if rec.get("event") == "state_tier":
            for field in ("hits", "misses", "spills", "restores"):
                v2 = rec.get(field)
                if not (isinstance(v2, int) and v2 >= 0):
                    problems.append(
                        f"record {n}: state_tier `{field}` must be a "
                        f"non-negative integer (got {v2!r})")
            for field in ("spill_bytes", "restore_bytes",
                          "resident", "working_set"):
                _comm_field(rec, n, field)
        if rec.get("event") == "screened":
            # value-fault admission (ISSUE 16): the record the drill
            # matrix and the tier1 poisoned smoke read, so its shape
            # must not rot
            if not isinstance(rec.get("round"), int):
                problems.append(
                    f"record {n}: screened event without an integer "
                    f"`round` (got {rec.get('round')!r})")
            ns = rec.get("n_screened")
            if not (isinstance(ns, int) and ns >= 0):
                problems.append(
                    f"record {n}: screened `n_screened` must be a "
                    f"non-negative integer (got {ns!r})")
            k2 = rec.get("kind")
            if not (isinstance(k2, str) and k2):
                problems.append(
                    f"record {n}: screened event without a non-empty "
                    f"string `kind` (got {k2!r})")
        if rec.get("event") == "aggregator":
            # robust aggregation (ISSUE 17): the record the drill
            # matrix and the tier1 adversarial smoke read
            if not isinstance(rec.get("round"), int):
                problems.append(
                    f"record {n}: aggregator event without an integer "
                    f"`round` (got {rec.get('round')!r})")
            a2 = rec.get("aggregator")
            if not (isinstance(a2, str) and a2):
                problems.append(
                    f"record {n}: aggregator event without a "
                    f"non-empty string `aggregator` (got {a2!r})")
            for field in ("n_trimmed", "residual_l2"):
                v2 = rec.get(field)
                if not isinstance(v2, (int, float)):
                    problems.append(
                        f"record {n}: aggregator `{field}` must be "
                        f"numeric (got {v2!r})")
            for field in ("n_clipped", "n_contrib"):
                v2 = rec.get(field)
                if not (isinstance(v2, int) and v2 >= 0):
                    problems.append(
                        f"record {n}: aggregator `{field}` must be a "
                        f"non-negative integer (got {v2!r})")
        if rec.get("event") == "screen_adapt":
            # adaptive screening (ISSUE 17): the threshold trajectory
            # the resume-bit-exactness drill replays
            if not isinstance(rec.get("round"), int):
                problems.append(
                    f"record {n}: screen_adapt event without an "
                    f"integer `round` (got {rec.get('round')!r})")
            for field in ("rate", "target"):
                v2 = rec.get(field)
                if not isinstance(v2, (int, float)):
                    problems.append(
                        f"record {n}: screen_adapt `{field}` must be "
                        f"numeric (got {v2!r})")
            for field in ("old_mult", "new_mult"):
                v2 = rec.get(field)
                if not (isinstance(v2, (int, float)) and v2 > 0):
                    problems.append(
                        f"record {n}: screen_adapt `{field}` must be "
                        f"a positive number (got {v2!r})")
        if rec.get("event") == "control":
            # controller bank (ISSUE 20): the plan-riding adjustment
            # trajectory the replay-exactness drills compare, so the
            # shape — and the controller name's registration in
            # analysis.domains.CONTROL_FIELDS — must not rot
            if not isinstance(rec.get("round"), int):
                problems.append(
                    f"record {n}: control event without an integer "
                    f"`round` (got {rec.get('round')!r})")
            c2 = rec.get("controller")
            if not (isinstance(c2, str) and c2 in CONTROL_FIELDS):
                problems.append(
                    f"record {n}: control `controller` must be a "
                    f"name registered in analysis.domains."
                    f"CONTROL_FIELDS (got {c2!r})")
            for field in ("signal", "old", "new"):
                v2 = rec.get(field)
                if not isinstance(v2, (int, float)):
                    problems.append(
                        f"record {n}: control `{field}` must be "
                        f"numeric (got {v2!r})")
            if not isinstance(rec.get("clamped"), bool):
                problems.append(
                    f"record {n}: control `clamped` must be a bool "
                    f"(got {rec.get('clamped')!r})")
        if rec.get("event") == "privacy":
            # differential privacy (ISSUE 19): the budget record the
            # tier1 dp smoke's monotone-epsilon gate reads, so its
            # shape — and the monotonicity itself — must not rot
            if not isinstance(rec.get("round"), int):
                problems.append(
                    f"record {n}: privacy event without an integer "
                    f"`round` (got {rec.get('round')!r})")
            eps = rec.get("epsilon")
            if not (isinstance(eps, (int, float)) and eps >= 0):
                problems.append(
                    f"record {n}: privacy `epsilon` must be a "
                    f"non-negative number (got {eps!r})")
            else:
                if last_epsilon is not None and eps < last_epsilon:
                    problems.append(
                        f"record {n}: privacy `epsilon` decreased "
                        f"({last_epsilon!r} -> {eps!r}) — the RDP "
                        f"budget only accumulates within a segment")
                last_epsilon = float(eps)
            for field in ("sigma", "clip"):
                v2 = rec.get(field)
                if not (isinstance(v2, (int, float)) and v2 > 0):
                    problems.append(
                        f"record {n}: privacy `{field}` must be a "
                        f"positive number (got {v2!r})")
            d3 = rec.get("delta")
            if not (isinstance(d3, (int, float)) and 0 < d3 < 1):
                problems.append(
                    f"record {n}: privacy `delta` must be in (0, 1) "
                    f"(got {d3!r})")
        if rec.get("event") == "compressor":
            # compressor plugin billing (ISSUE 19): the per-mode
            # bytes-on-wire record summarize() accumulates
            if not isinstance(rec.get("round"), int):
                problems.append(
                    f"record {n}: compressor event without an integer "
                    f"`round` (got {rec.get('round')!r})")
            m2 = rec.get("mode")
            if not (isinstance(m2, str) and m2):
                problems.append(
                    f"record {n}: compressor event without a "
                    f"non-empty string `mode` (got {m2!r})")
            for field in ("wire_bytes", "up_bytes"):
                v2 = rec.get(field)
                if not (isinstance(v2, (int, float)) and v2 >= 0):
                    problems.append(
                        f"record {n}: compressor `{field}` must be a "
                        f"non-negative number (got {v2!r})")
        if rec.get("event") == "numeric_trip":
            if not isinstance(rec.get("round"), int):
                problems.append(
                    f"record {n}: numeric_trip event without an "
                    f"integer `round` (got {rec.get('round')!r})")
            m2 = rec.get("metrics")
            if not (isinstance(m2, list)
                    and all(isinstance(x, str) for x in m2)):
                problems.append(
                    f"record {n}: numeric_trip `metrics` must be a "
                    f"list of metric-name strings (got {m2!r})")
        if rec.get("event") == "state_quarantine":
            c2 = rec.get("client")
            if not (isinstance(c2, int) and c2 >= 0):
                problems.append(
                    f"record {n}: state_quarantine `client` must be "
                    f"a non-negative integer (got {c2!r})")
            f2 = rec.get("field")
            if not (isinstance(f2, str) and f2):
                problems.append(
                    f"record {n}: state_quarantine event without a "
                    f"non-empty string `field` (got {f2!r})")
        # the two analysis-tier digest records share a shape: sha256
        # digest + per-program cost object, with tier-specific fields
        digest_fields = {
            "audit_digest": ("flops", "hbm_bytes"),
            "mesh_audit_digest": ("ici_bytes", "dcn_bytes",
                                  "dcn_collectives"),
        }
        ev = rec.get("event")
        if ev in digest_fields:
            d = rec.get("digest")
            if not (isinstance(d, str) and d):
                problems.append(
                    f"record {n}: {ev} without a non-empty "
                    f"string `digest` (got {d!r})")
            progs = rec.get("programs")
            if not isinstance(progs, dict):
                problems.append(
                    f"record {n}: {ev} `programs` is not an "
                    "object")
            else:
                for prog, cost in sorted(progs.items()):
                    if not isinstance(cost, dict):
                        problems.append(
                            f"record {n}: {ev} program "
                            f"{prog!r} cost is not an object")
                        continue
                    for field in digest_fields[ev]:
                        v2 = cost.get(field)
                        if not (isinstance(v2, (int, float))
                                and v2 >= 0):
                            problems.append(
                                f"record {n}: {ev} program "
                                f"{prog!r} `{field}` must be a "
                                f"non-negative number (got {v2!r})")
        if rec.get("event") in ("sync_audit_digest",
                                "num_audit_digest"):
            # graftsync/graftnum: the digest is pinned to 64-hex —
            # the bit-identical-across-runs claim is checked on
            # exactly this value, so a truncated or non-canonical
            # digest is a schema rot, not a style choice
            ev2 = rec.get("event")
            d = rec.get("digest")
            if not (isinstance(d, str) and len(d) == 64
                    and all(c in "0123456789abcdef" for c in d)):
                problems.append(
                    f"record {n}: {ev2} `digest` must be "
                    f"a 64-char lowercase hex string (got {d!r})")
            rls = rec.get("rules")
            if not isinstance(rls, dict):
                problems.append(
                    f"record {n}: {ev2} `rules` is not "
                    "an object")
            else:
                for rule, cnt in sorted(rls.items()):
                    if not (isinstance(cnt, int) and cnt >= 0):
                        problems.append(
                            f"record {n}: {ev2} rule "
                            f"{rule!r} count must be a non-negative "
                            f"integer (got {cnt!r})")
            fnd = rec.get("findings")
            if fnd is not None and not (isinstance(fnd, int)
                                        and fnd >= 0):
                problems.append(
                    f"record {n}: {ev2} `findings` must "
                    f"be a non-negative integer (got {fnd!r})")
        if rec.get("event") == "num_audit_digest":
            # graftnum additionally journals the per-program
            # worst-case reassociation ulp bounds the baseline diffs
            ulp = rec.get("ulp")
            if not isinstance(ulp, dict):
                problems.append(
                    f"record {n}: num_audit_digest `ulp` is not an "
                    "object")
            else:
                for prog, bound in sorted(ulp.items()):
                    if not (isinstance(bound, int) and bound >= 0):
                        problems.append(
                            f"record {n}: num_audit_digest program "
                            f"{prog!r} ulp bound must be a "
                            f"non-negative integer (got {bound!r})")
        if rec.get("event") == "run_end":
            total_down = _comm_field(rec, n, "down_bytes_total")
            total_up = _comm_field(rec, n, "up_bytes_total")
            # cumulative totals must cover the segment's journaled
            # per-round sums (0.5-byte slack for float accumulation)
            if total_down is not None and total_down < seg_down - 0.5:
                problems.append(
                    f"record {n}: down_bytes_total {total_down} < "
                    f"sum of per-round down_bytes {seg_down}")
            if total_up is not None and total_up < seg_up - 0.5:
                problems.append(
                    f"record {n}: up_bytes_total {total_up} < "
                    f"sum of per-round up_bytes {seg_up}")
        if rec.get("event") == "round":
            d = _comm_field(rec, n, "down_bytes")
            u = _comm_field(rec, n, "up_bytes")
            seg_down += d or 0.0
            seg_up += u or 0.0
            r = rec.get("round")
            if not isinstance(r, int):
                problems.append(f"record {n}: round event without an "
                                f"integer `round` (got {r!r})")
                continue
            if r in seen_rounds:
                problems.append(f"record {n}: duplicate round {r}")
            elif last_round is not None and r <= last_round:
                problems.append(
                    f"record {n}: round {r} out of order "
                    f"(after round {last_round})")
            seen_rounds.add(r)
            last_round = r if last_round is None else max(last_round, r)
            m = rec.get("metrics")
            if m is not None:
                if not isinstance(m, dict):
                    problems.append(
                        f"record {n}: `metrics` is not an object")
                else:
                    # the non-finite sentinels (_finite) are legal —
                    # a diverging run's NaN loss is valid telemetry
                    ok_strings = set(NONFINITE.values())
                    bad = [k for k, val in m.items()
                           if not (isinstance(val, (int, float))
                                   or val in ok_strings)]
                    if bad:
                        problems.append(
                            f"record {n}: non-numeric metrics {bad}")
    return records, problems


# inter-round cadence histogram buckets (seconds): log-ish edges with
# human labels — coarse on purpose (the p50/p95 carry the precision;
# the histogram shows the SHAPE: bimodal cadence = a periodic stall)
_CADENCE_EDGES = (
    (0.001, "<1ms"), (0.003, "1-3ms"), (0.01, "3-10ms"),
    (0.03, "10-30ms"), (0.1, "30-100ms"), (0.3, "0.1-0.3s"),
    (1.0, "0.3-1s"), (3.0, "1-3s"), (10.0, "3-10s"),
)


def _cadence_bucket(dt: float) -> str:
    for edge, label in _CADENCE_EDGES:
        if dt < edge:
            return label
    return ">=10s"


def summarize(records: List[dict], corrupt_lines: int = 0) -> dict:
    """Small host-side digest of a journal: event-kind counts, round
    coverage, total journaled wall time in spans/checkpoints.
    `corrupt_lines`: the skipped-interior-line count from
    read_journal/validate_journal's `counters` — surfaced in the
    summary (ISSUE 12 satellite) so a journal that survived a
    mid-batch writer crash says so instead of silently looking
    clean.

    Stage-level analytics (ISSUE 13, graftscope): with `trace` events
    present the summary grows per-stage p50/p95 (`trace_stages`), the
    writer queue-depth gauges (`writer_queue_max`, from the enqueue
    spans' `q` tags), and the pipeline overlap-efficiency metric
    (`overlap_efficiency` = device-busy / wall over the
    device_execute spans). Independently, round events carrying the
    `mono` timestamp yield the inter-round `cadence` block
    (p50/p95 + histogram) — deltas are taken on the MONOTONIC clock,
    reset at every run_start (each process has its own mono base,
    and a wall-clock delta is not a duration)."""
    kinds: dict = {}
    rounds = []
    span_s = ckpt_s = 0.0
    down_b = up_b = 0.0
    deadlines = 0
    tier_hits = tier_misses = tier_spills = 0
    tier_spill_b = 0.0
    screened_total = 0
    trimmed_total = 0.0
    clipped_total = 0
    epsilon_spent = None
    privacy_sigma = privacy_delta = None
    wire_by_mode: dict = {}
    control_by_ctl: dict = {}
    # trace spans SEGMENTED at run_start: monotonic t0 values share a
    # base only within one process lifetime, so the wall-extent math
    # (overlap efficiency) must never mix segments from a resumed run
    # or a coordinator takeover
    trace_segments: List[List[dict]] = [[]]
    trace_dropped = 0
    cadence: List[float] = []
    prev_mono = None
    # the four analysis tiers' journaled report digests (last record
    # of each wins — a re-run within one journal supersedes)
    tier_digests: dict = {}
    num_findings = None
    for rec in records:
        kind = rec.get("event", "?")
        kinds[kind] = kinds.get(kind, 0) + 1
        if kind in ("audit_digest", "mesh_audit_digest",
                    "sync_audit_digest", "num_audit_digest"):
            d = rec.get("digest")
            if isinstance(d, str) and d:
                tier_digests[kind] = d
            if kind == "num_audit_digest":
                f2 = rec.get("findings")
                if isinstance(f2, int):
                    num_findings = f2
        if kind == "run_start":
            # new segment: a resumed process has its own monotonic
            # base, so cross-segment deltas are meaningless
            prev_mono = None
            if trace_segments[-1]:
                trace_segments.append([])
        if kind == "trace":
            spans = rec.get("spans")
            if isinstance(spans, list):
                trace_segments[-1].extend(
                    sp for sp in spans if isinstance(sp, dict))
            d = rec.get("dropped")
            if isinstance(d, int) and d > 0:
                trace_dropped += d
        if kind == "screened":
            screened_total += int(rec.get("n_screened", 0) or 0)
        if kind == "aggregator":
            trimmed_total += float(rec.get("n_trimmed", 0) or 0)
            clipped_total += int(rec.get("n_clipped", 0) or 0)
        if kind == "privacy":
            # cumulative by construction — the LAST record is the
            # budget spent (a rollback's replay re-journals the lower
            # window values, and the last one still wins)
            eps = rec.get("epsilon")
            if isinstance(eps, (int, float)):
                epsilon_spent = float(eps)
            if isinstance(rec.get("sigma"), (int, float)):
                privacy_sigma = float(rec["sigma"])
            if isinstance(rec.get("delta"), (int, float)):
                privacy_delta = float(rec["delta"])
        if kind == "compressor":
            m2 = rec.get("mode")
            ub = rec.get("up_bytes")
            if isinstance(m2, str) and isinstance(ub, (int, float)):
                acc = wire_by_mode.setdefault(
                    m2, {"rounds": 0, "up_bytes": 0.0,
                         "wire_bytes": 0.0})
                acc["rounds"] += 1
                acc["up_bytes"] += float(ub)
                if isinstance(rec.get("wire_bytes"), (int, float)):
                    acc["wire_bytes"] = float(rec["wire_bytes"])
        if kind == "control":
            c2 = rec.get("controller")
            if isinstance(c2, str) and c2:
                acc = control_by_ctl.setdefault(
                    c2, {"adjustments": 0, "clamped": 0,
                         "final": None})
                acc["adjustments"] += 1
                if rec.get("clamped") is True:
                    acc["clamped"] += 1
                if isinstance(rec.get("new"), (int, float)):
                    # records are appended in commit order, so the
                    # last `new` IS the controller's final value
                    acc["final"] = float(rec["new"])
        if kind == "state_tier":
            tier_hits += int(rec.get("hits", 0) or 0)
            tier_misses += int(rec.get("misses", 0) or 0)
            tier_spills += int(rec.get("spills", 0) or 0)
            tier_spill_b += float(rec.get("spill_bytes", 0) or 0)
        if kind == "round" and isinstance(rec.get("round"), int):
            rounds.append(rec["round"])
            mono = rec.get("mono")
            if isinstance(mono, (int, float)):
                if prev_mono is not None and mono > prev_mono:
                    cadence.append(float(mono) - prev_mono)
                prev_mono = float(mono)
            if isinstance(rec.get("down_bytes"), (int, float)):
                down_b += float(rec["down_bytes"])
            if isinstance(rec.get("up_bytes"), (int, float)):
                up_b += float(rec["up_bytes"])
        elif kind == "span":
            span_s += float(rec.get("dispatch_s", 0.0))
            span_s += float(rec.get("block_s", 0.0))
        elif kind == "checkpoint":
            ckpt_s += float(rec.get("seconds", 0.0))
        elif kind == "schedule" and rec.get("deadline_s") is not None:
            deadlines += 1
    out = {
        "records": len(records),
        "events": dict(sorted(kinds.items())),
        "rounds": len(rounds),
        "first_round": min(rounds) if rounds else None,
        "last_round": max(rounds) if rounds else None,
        "span_seconds": round(span_s, 3),
        "checkpoint_seconds": round(ckpt_s, 3),
        "down_mib": round(down_b / (1024 ** 2), 3),
        "up_mib": round(up_b / (1024 ** 2), 3),
        "deadline_rounds": deadlines,
    }
    if (kinds.get("screened") or kinds.get("numeric_trip")
            or kinds.get("state_quarantine")):
        # numeric-robustness counters (ISSUE 16): how many client
        # updates the in-round admission excluded, how many times the
        # finite-frontier watch tripped (each trip = one rollback),
        # and how many state-tier rows were quarantined at restore
        out["screened_total"] = screened_total
        out["numeric_trips"] = kinds.get("numeric_trip", 0)
        out["state_quarantines"] = kinds.get("state_quarantine", 0)
    if kinds.get("aggregator") or kinds.get("screen_adapt"):
        # Byzantine-robustness counters (ISSUE 17): cumulative
        # trimmed/clipped clients across the robust-aggregated rounds
        # and how many times adaptive screening moved the threshold
        out["trimmed_total"] = round(trimmed_total, 3)
        out["clipped_total"] = clipped_total
        out["screen_adaptations"] = kinds.get("screen_adapt", 0)
    if epsilon_spent is not None:
        # differential privacy (ISSUE 19): cumulative budget spent —
        # the one number a DP run is answerable for
        out["epsilon_spent"] = round(epsilon_spent, 6)
        if privacy_sigma is not None:
            out["privacy_sigma"] = privacy_sigma
        if privacy_delta is not None:
            out["privacy_delta"] = privacy_delta
    if wire_by_mode:
        # compressor plugins (ISSUE 19): per-mode bytes-on-wire —
        # round count, per-client wire geometry, cumulative upload
        out["compressor_modes"] = {
            m: {"rounds": acc["rounds"],
                "wire_bytes": round(acc["wire_bytes"], 3),
                "up_mib": round(acc["up_bytes"] / (1024 ** 2), 3)}
            for m, acc in sorted(wire_by_mode.items())}
    if control_by_ctl:
        # controller bank (ISSUE 20): per-controller adjustment count,
        # clamp count, and final value — the one-line answer to "what
        # did the self-tuning loop actually do this run"
        out["controllers"] = {
            c: {"adjustments": acc["adjustments"],
                "clamped": acc["clamped"],
                "final": (None if acc["final"] is None
                          else round(acc["final"], 6))}
            for c, acc in sorted(control_by_ctl.items())}
    if tier_hits or tier_misses:
        # tiered client state (ISSUE 11): working-set hit rate +
        # spill traffic — the run's residency summary line
        out["state_hit_rate"] = round(
            tier_hits / max(tier_hits + tier_misses, 1), 4)
        out["state_spills"] = tier_spills
        out["state_spill_mib"] = round(tier_spill_b / (1024 ** 2), 3)
    if cadence:
        hist: dict = {}
        for dt in cadence:
            label = _cadence_bucket(dt)
            hist[label] = hist.get(label, 0) + 1
        srt = sorted(cadence)
        out["cadence"] = {
            "rounds": len(cadence),
            "p50_s": round(srt[min(len(srt) // 2, len(srt) - 1)], 6),
            "p95_s": round(
                srt[min(int(0.95 * len(srt)), len(srt) - 1)], 6),
            "hist": hist,
        }
    trace_spans = [sp for seg in trace_segments for sp in seg]
    if trace_spans:
        # graftscope (ISSUE 13): the stage-level analytics block.
        # Stage durations pool across segments (each dur is already a
        # within-process interval); busy/wall sums PER segment.
        out["trace_spans"] = len(trace_spans)
        out["trace_stages"] = stage_stats(trace_spans)
        busy = wall = 0.0
        for seg in trace_segments:
            bw = device_busy_wall(seg)
            if bw is not None:
                busy += bw[0]
                wall += bw[1]
        if wall > 0:
            out["overlap_efficiency"] = round(min(busy / wall, 1.0), 4)
        qmax: dict = {}
        for sp in trace_spans:
            q = sp.get("q")
            name = sp.get("name", "")
            if isinstance(q, int) and isinstance(name, str) \
                    and name.endswith("_enqueue"):
                writer = name[:-len("_enqueue")]
                qmax[writer] = max(qmax.get(writer, 0), q)
        if qmax:
            out["writer_queue_max"] = dict(sorted(qmax.items()))
        if trace_dropped:
            out["trace_dropped"] = trace_dropped
    if tier_digests:
        # the analysis tiers' report digests side by side (graftaudit
        # / graftmesh / graftsync / graftnum), so "which exact audit
        # reports does this run vouch for" is one summary read
        out["analysis_digests"] = dict(sorted(tier_digests.items()))
        if num_findings is not None:
            out["num_audit_findings"] = num_findings
    if corrupt_lines:
        out["corrupt_lines"] = int(corrupt_lines)
    return out

"""Configuration: the framework's single flat config namespace.

Flag-name-parity with the reference CLI (reference:
CommEfficient/utils.py:102-230 `parse_args`), so reference launch
commands work unmodified, but held in a typed dataclass instead of a
bare argparse namespace so it can be closed over as static jit config.

Static/hashable by design: a `Config` is frozen and usable as a jit
static argument; anything traced (learning rate, rng keys) is passed
separately.
"""
from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Tuple

# one Compressor plugin per mode (commefficient_tpu/compress): the
# five reference modes plus the ISSUE-19 plugins — powersgd (rank-r
# power-iteration factors) and dp_sketch (sketch transport under the
# Gaussian mechanism with Rényi budget accounting)
MODES = ("sketch", "true_topk", "local_topk", "fedavg", "uncompressed",
         "powersgd", "dp_sketch")
ERROR_TYPES = ("none", "local", "virtual")
DP_MODES = ("worker", "server")
SCREEN_MODES = ("off", "finite", "norm")
POISON_KINDS = ("nan", "inf", "scale")
# cross-client reduction of the jitted round (ISSUE 17,
# federated/round.py): mean is the reference FetchSGD sum/total;
# the robust tier computes per-cell order statistics over the
# [num_workers, ...] client update tables inside the round
AGGREGATORS = ("mean", "coord_median", "trimmed_mean", "norm_clip")
# scripted adversary kinds (utils/faults "byzantine" PRNG domain):
# sign_flip/scaled are per-client local corruptions; colluding and
# little_is_enough are COORDINATED crafted updates built from the
# honest cohort's statistics — finite and norm-plausible, the class
# admission screening provably cannot catch
ATTACKS = ("sign_flip", "scaled", "colluding", "little_is_enough")

# dataset -> num_classes (reference: utils.py:37-44); PERSONA is a
# language-modeling dataset so has no class count.
FED_DATASETS = {
    "CIFAR10": 10,
    "CIFAR100": 100,
    "EMNIST": 62,
    "ImageNet": 1000,
    "PERSONA": -1,
}

# default client counts when --num_clients is unset
# (reference: fed_aggregator.py:66-73)
DEFAULT_NUM_CLIENTS = {
    "EMNIST": 3500,
    "PERSONA": 17568,
}


def num_classes_of_dataset(dataset_name: str) -> int:
    return FED_DATASETS[dataset_name]


@dataclass(frozen=True)
class Config:
    # meta (reference: utils.py:106-111)
    do_test: bool = False
    mode: str = "sketch"
    use_tensorboard: bool = False
    seed: int = 21

    # data/model (utils.py:114-139)
    model: str = "ResNet9"
    do_finetune: bool = False
    do_checkpoint: bool = False
    checkpoint_path: str = "./checkpoint"
    checkpoint_every: int = 0  # epochs between mid-run checkpoints; 0 = end only
    resume: bool = False
    finetune_path: str = "./finetune"
    finetuned_from: Optional[str] = None
    num_results_train: int = 2
    num_results_val: int = 2
    dataset_name: str = "CIFAR10"
    dataset_dir: str = "./dataset"
    do_batchnorm: bool = False
    nan_threshold: float = 999.0
    # dump a jax.profiler trace of the first training epoch into
    # <logdir>/profile (viewable in TensorBoard/Perfetto) — the TPU
    # equivalent of the reference's dormant cProfile scaffolding
    # (fed_aggregator.py:46-52; SURVEY.md §5 tracing row)
    do_profile: bool = False

    # observability (commefficient_tpu/telemetry, ISSUE 4). telemetry
    # is ON by default: the jitted round computes a fixed-shape named
    # f32 metric vector (telemetry/metrics.METRIC_NAMES — round loss,
    # update/error norms, survivor count, processed examples, realized
    # top-k, sketch estimate-residual proxy) that is exported to the
    # host only at span boundaries via explicit device_get. Disabling
    # it (--no_telemetry) traces the metric-free round program;
    # ServerState bits are identical either way (tests/test_telemetry).
    telemetry: bool = True
    # journal file path ("" = <run dir>/journal.jsonl): the structured
    # JSONL run record (telemetry/journal.py) — round/span metrics,
    # checkpoint saves, XLA compile events, retries, injected faults
    journal_path: str = ""
    # capture a jax.profiler trace of scanned-span indices [A, B)
    # ("" = off; requires --scan_rounds). Unlike --profile (whole first
    # epoch), this targets operator-selected steady-state spans
    profile_spans: str = ""
    # arm analysis/runtime.forbid_transfers around the drivers'
    # steady-state dispatch (every span/round after the first): any
    # implicit host<->device transfer — a hidden per-round sync, the
    # silent TPU performance cliff — raises instead of slowly burning
    # the tunnel (ROADMAP PR-3 opening)
    debug_transfer_guard: bool = False
    # graftscope round-lifecycle tracing (ISSUE 13,
    # telemetry/trace.py). OFF by default: the tracer exists but
    # records nothing and adds zero journal writes (the only schema
    # change that lands regardless of this flag is the `mono`
    # timestamp every journal record carries). ON: monotonic-clock
    # spans bracket every HOST stage of the round lifecycle — plan
    # composition/broadcast, operand staging, dispatch, the
    # device-execute window at the dispatch/collect seam, tiered-state
    # restore/spill, collection/accounting, checkpoint saves, and each
    # writer thread's queue-wait + fsync — tagged with (round, span,
    # controller, thread) correlation keys, buffered in per-thread
    # rings, and flushed as batched `trace` journal events at span
    # boundaries. Zero traced-program changes either way (spans wrap
    # dispatch calls, never jitted code); scripts/trace_export.py
    # converts the journal to Perfetto-loadable Chrome trace JSON and
    # journal_summary.py reports per-stage p50/p95 + overlap
    # efficiency.
    trace: bool = False

    # compression (utils.py:142-147)
    k: int = 50000
    num_cols: int = 500000
    num_rows: int = 5
    num_blocks: int = 20
    do_topk_down: bool = False
    # download top-k budget, decoupled from the upload/server k
    # (0 = use k, the reference's single shared knob). The server's
    # update is k-sparse per round while a sparsely-participating
    # client accumulates MANY rounds of changes between downloads, so
    # the download budget that keeps staleness bounded is a multiple
    # of k — the tradeoff benchmarks/convergence.py sweeps.
    down_k: int = 0
    # kernel backend for the compression hot path (ISSUE 6,
    # commefficient_tpu/ops/kernels): "xla" — the default, bit-
    # identical to the pre-kernel program (the dispatch gates are
    # untaken, not re-proven) — or "pallas", which routes count-sketch
    # encode / estimate-all / the large-d threshold decode through
    # fused Pallas TPU kernels (interpret-mode on CPU, so tests
    # execute the same kernel bodies). Static config: either choice
    # traces the same THREE round programs, stays transfer-guard
    # clean, and resumes bit-exactly (tests/test_kernels.py).
    kernel_backend: str = "xla"
    # wire dtype of the transmitted [r, c] sketch table (sketch mode
    # only): "f32" (default — the transport code path is the identity,
    # bit-identical to a build without the flag), "bf16", or "int8"
    # (symmetric per-row scales). Quantization rounds the shard's
    # client-sum table before the psum; the server's virtual error
    # feedback absorbs the rounding noise the same way it absorbs
    # sketch compression noise (ops/kernels/quant.py), telemetry's
    # estimate_residual metric gauges whether accuracy pays for it,
    # and the accountant bills upload bytes at the WIRE element size
    # (Config.upload_bytes).
    sketch_table_dtype: str = "f32"

    # optimization (utils.py:150-162)
    local_momentum: float = 0.9
    virtual_momentum: float = 0.0
    weight_decay: float = 5e-4
    num_epochs: float = 24.0
    num_fedavg_epochs: int = 1
    fedavg_batch_size: int = -1
    fedavg_lr_decay: float = 1.0
    error_type: str = "none"
    lr_scale: Optional[float] = None
    pivot_epoch: float = 5.0

    # fault tolerance (an extension beyond the reference, which assumes
    # every sampled client finishes every round and every run finishes
    # uninterrupted — neither holds in FetchSGD's target setting or on
    # preemptible TPU pods). client_dropout is the per-round Bernoulli
    # probability that a sampled client FAILS to complete the round:
    # its upload is excluded from aggregation (survivor-count
    # reweighting), its persistent error/velocity/stale-weight rows
    # stay bit-untouched, and accounting charges it nothing. The draw
    # is deterministic in (seed, round), so crash->resume replays it
    # exactly. 0.0 keeps the engine on the mask-free program — the
    # machinery costs nothing when disabled. Tests inject explicit
    # per-round schedules instead (utils/faults.FaultSchedule).
    client_dropout: float = 0.0
    # buffer donation for the jitted round dispatch (ISSUE 7: the
    # graftaudit donation audit's first applied finding). When on —
    # the default — the dead-after-dispatch round inputs are donated
    # to XLA so their HBM is reused for the matching outputs in place:
    # the scanned span donates ServerState AND the per-client state
    # rows (run_rounds only ever assigns state from the span's
    # RESULT), the per-round path donates the client rows only
    # (FedModel._call_train reads the previous ps_weights AFTER
    # dispatch for the lagged accounting bitset, so ServerState must
    # survive — the justified exception graftaudit documents). At the
    # EMNIST/PERSONA populations the error-feedback block is the
    # dominant allocation (3500 x 6.6M f32 ≈ 92 GB across a pod), so
    # un-donated dispatch transiently doubles it. Semantics are
    # bit-identical either way (aliasing only; tests/test_audit.py
    # proves resume bit-exactness) — but donated inputs are INVALID
    # after the call: generic callers that re-dispatch from a retained
    # state object (benchmark timing loops) must disable this, and a
    # donated span dispatch that fails mid-execute can no longer be
    # transparently retried (utils/retry), which is what
    # --no_donate_round_state is for on flaky preemptible pods.
    donate_round_state: bool = True
    # straggler (slow-client) modeling beyond binary dropout: each
    # sampled client is a straggler with probability straggler_rate;
    # a straggler draws a WORK FRACTION uniform in
    # [straggler_min_work, 1) — deterministic in (seed, round), same
    # replay contract as client_dropout (utils/faults.
    # straggler_work_fractions). The fraction becomes a per-client
    # completed-examples budget (completed local SGD steps for
    # fedavg) inside the jitted round, and aggregation weights by
    # examples actually processed (FedNova-style) so partial uploads
    # don't bias the average. A fraction below straggler_cutoff
    # degrades to the dropout path: state rows bit-untouched,
    # accounting charges nothing. 0.0 keeps the engine on the
    # work-free program — the machinery costs nothing when disabled.
    straggler_rate: float = 0.0
    straggler_min_work: float = 0.1
    straggler_cutoff: float = 0.0
    # numeric-integrity layer (ISSUE 16, federated/round.py screened
    # programs). update_screen is the in-round update ADMISSION policy:
    # "off" — the default, bit-identical to a build without the
    # feature (default configs trace the original three round
    # programs) — "finite" screens any client whose local update
    # carries a NaN/Inf, "norm" additionally screens norm outliers
    # (update l2 > screen_norm_mult x the cohort's median l2 over
    # surviving, measurable clients; rounds with no measurable
    # survivor admit everyone, so the screen is zero-survivor-safe).
    # A screened client takes EXACTLY the dropped-client path — state
    # rows bit-untouched, survivor-count reweighting, survivor-only
    # accounting — so screening composes with dropout, stragglers,
    # deadlines, and async admission for free.
    update_screen: str = "off"
    screen_norm_mult: float = 5.0
    # value-fault INJECTION (utils/faults.poison_mask): each sampled
    # client's update is corrupted with this per-round probability —
    # deterministic in (seed, round) on its own PRNG domain, same
    # replay contract as client_dropout. poison_kind picks the
    # corruption: nan / inf overwrite the transmitted update, scale
    # multiplies it by 2^40 (a finite explosion only the norm screen
    # catches). 0.0 keeps every default program untouched.
    poison_rate: float = 0.0
    poison_kind: str = "nan"
    # Byzantine-robust aggregation tier (ISSUE 17, federated/round.py
    # robust programs). aggregator replaces the cross-client mean with
    # a robust reduction computed INSIDE the jitted round, composed
    # with the admission mask (screened/dropped clients are excluded
    # from the order statistics; zero-survivor safe): coord_median is
    # the per-cell coordinate median over admitted client tables,
    # trimmed_mean drops the trim_beta fraction from each end of every
    # cell's order statistics before the FedNova-weighted mean,
    # norm_clip rescales each client's update to at most the cohort
    # median l2 before the ordinary weighted mean (the cheap option).
    # "mean" — the default — keeps the traced round programs
    # bit-identical to a build without the feature.
    aggregator: str = "mean"
    trim_beta: float = 0.2
    # scripted adversary harness (utils/faults.byzantine_mask, its own
    # "byzantine" PRNG domain — deterministic in seed+round, same
    # replay contract as client_dropout/poison). Each sampled client
    # is an attacker with probability byzantine_rate; `attack` picks
    # the crafted update (ATTACKS above). 0.0 keeps every default
    # program untouched.
    byzantine_rate: float = 0.0
    attack: str = "sign_flip"
    # plan-driven adaptive screening (scheduler.AdaptiveScreenController):
    # with target_screened_rate >= 0 the norm-screen threshold
    # screen_norm_mult becomes a per-round TRACED operand adjusted
    # toward the target from the journaled per-round screened-rate —
    # each adjustment rides the journaled RoundPlan (coordinator-
    # broadcast under --plan_transport, replayed not recomputed on
    # takeover) so crash->resume reproduces the exact threshold
    # trajectory. Negative (the default) keeps the static threshold
    # and the PR-16 traced programs byte-identical.
    target_screened_rate: float = -1.0
    screen_adapt_step: float = 0.5
    screen_mult_min: float = 1.5
    screen_mult_max: float = 64.0
    # plan-riding controller bank (control/, ISSUE 20): three
    # self-tuning loops on the ISSUE-17 pattern — every adjustment is
    # bounded, f32-rounded, rides the journaled RoundPlan (`controls`
    # wire dict), and is installed (never recomputed) by followers and
    # replayed rounds. All off by default: make_bank returns None and
    # the loop is bit-identical to a pre-controller build.
    #
    # cohort speed-matching (control/speed.py): clients whose
    # examples/sec EMA falls below speed_ratio x cohort-median get a
    # work fraction < 1 min-composed onto plan.work, which the async
    # admission buffer defers into an --async_admit_rounds slot; the
    # ratio is nudged so the deferred fraction tracks
    # speed_match_target, clamped to [speed_ratio_min,
    # speed_ratio_max] (max < 1 — "slow" must mean strictly slower
    # than the median).
    speed_match: bool = False
    speed_match_target: float = 0.25
    speed_match_step: float = 0.25
    speed_ratio: float = 0.5
    speed_ratio_min: float = 0.25
    speed_ratio_max: float = 0.9
    # adaptive span cadence (control/span.py): comma-separated span
    # lengths ("1,2,4") the scanned staging loop may flush at; each
    # entry's program traces ONCE at warmup (the palette is the whole
    # shape vocabulary — steady state stays zero-recompile) and the
    # per-entry seconds-per-round EMA picks the steady-state length.
    # Must include 1 (the stream tail decomposes greedily over the
    # palette). Empty = static --scan_span, the default.
    scan_span_palette: str = ""
    # adaptive staleness decay (control/staleness.py): the
    # estimate_residual metric drives async_staleness_decay between
    # [staleness_decay_min, staleness_decay_max] — residual above
    # staleness_target discounts late admissions harder.
    adapt_staleness: bool = False
    staleness_target: float = 0.3
    staleness_step: float = 0.25
    staleness_decay_min: float = 0.2
    staleness_decay_max: float = 0.95
    # finite-frontier auto-rollback (the drivers' numeric_trip
    # handler): after a non-finite update/error-l2 trips telemetry and
    # the run rolls back to the newest finite checkpoint, screening is
    # FORCE-ENABLED for this many rounds so the replayed fault is
    # admitted out instead of re-tripping; bounded by
    # max_numeric_rollbacks trips per run, after which the driver
    # fails loud instead of thrashing.
    rollback_screen_rounds: int = 8
    max_numeric_rollbacks: int = 2
    # keep the newest k rotated mid-run checkpoints (utils/checkpoint.
    # save_rotating); older ones are pruned after each atomic save
    keep_checkpoints: int = 3
    # ALSO prune rotated checkpoints older than this wall-clock age in
    # hours (0 = age pruning off). Long preemptible-pod runs rotate
    # slowly near the end of an epoch; age pruning bounds disk growth
    # by time, not count. The manifest's `latest` entry is never
    # age-pruned, so resume always has a target.
    ckpt_max_age_hours: float = 0.0
    # scanned-path (--scan_rounds) checkpoint cadence in SPANS: with
    # checkpoint_every on, save a rotated checkpoint every k-th span
    # boundary (a span is the atomic commit unit — a preemption
    # mid-span loses back to the last boundary, so 1 bounds the loss
    # of a kill at any instant to one span). Each save is a full
    # server+client gather plus a disk write; short spans on a big
    # model can make every-boundary saving dominate, so raise this to
    # bound the save rate (preemption loss grows to k spans), or 0 to
    # keep only the epoch-cadence saves.
    ckpt_every_spans: int = 1

    # parallelization (utils.py:165-180). `port` kept for CLI parity but
    # unused: there is no process-group rendezvous in a single-program
    # SPMD runtime (reference needed it at fed_aggregator.py:161-164).
    port: int = 5315
    # run each epoch's rounds as one scanned device program (a TPU-only
    # capability; the reference's process/queue round-trip per round
    # cannot be batched this way). scan_span bounds the staged
    # [N, W, B, ...] device arrays by flushing every `scan_span` rounds
    # (0 = whole epoch in one program; set a span at ImageNet scale —
    # staging memory is span * num_workers * B * example_bytes).
    scan_rounds: bool = False
    scan_span: int = 0
    num_clients: Optional[int] = None
    num_workers: int = 1
    # tensor-parallel degree over the mesh's `model` axis (an extension
    # beyond the reference, whose only parallelism is one worker
    # process per GPU): >1 lays devices out as (clients, model) and
    # GSPMD-partitions each client's fwd/bwd per parallel/tp.py
    model_parallel: int = 1
    # lay the clients axis slice-major over DCN (emulated grouping off
    # real multi-slice hardware; parallel/mesh.py
    # make_multihost_client_mesh). 1 = flat single-slice mesh; real
    # slice topology is auto-detected either way
    num_slices: int = 1
    # multi-HOST runtime (the reference's PS + worker process topology,
    # fed_aggregator.py:143-164, as multi-controller SPMD): --multihost
    # calls jax.distributed.initialize before any backend use. On TPU
    # pods the coordinator/process grid is auto-detected; off-pod (CPU
    # grids, tests) pass all three of coordinator_address /
    # num_processes / process_id explicitly.
    multihost: bool = False
    coordinator_address: str = ""
    num_processes: int = 0
    process_id: int = -1
    # run client forward/backward in bfloat16 (f32 master weights and
    # f32 server/compression state; see client.make_flat_grad_fn) —
    # the MXU's fast path, an extension over the reference's fp32 CUDA
    do_bf16: bool = False
    # rematerialize transformer blocks on backward (GPT2 workload):
    # O(1)-block activation memory for ~1/3 extra FLOPs
    do_remat: bool = False
    # cap on the static per-client batch dim when local_batch_size=-1
    # (whole-client batches). Uncapped, fedavg at ImageNet scale stages
    # max(data_per_client) examples per client slot (~2.4 GB f32 at
    # 1300x224x224x3) — the cap bounds staging memory; clients with
    # more data participate in consecutive rounds on successive chunks
    # (a documented divergence: the reference instead serializes whole
    # clients one at a time per GPU, fed_worker.py:68-77).
    max_local_batch: int = -1
    device: str = "tpu"
    num_devices: int = 1
    share_ps_gpu: bool = False
    do_iid: bool = False
    train_dataloader_workers: int = 0
    val_dataloader_workers: int = 0

    # GPT2 (utils.py:183-207)
    model_checkpoint: str = "gpt2"
    num_candidates: int = 2
    max_history: int = 2
    local_batch_size: int = 8
    valid_batch_size: int = 8
    microbatch_size: int = -1
    lm_coef: float = 1.0
    mc_coef: float = 1.0
    max_grad_norm: Optional[float] = None
    personality_permutations: int = 1
    eval_before_start: bool = False

    # differential privacy (utils.py:210-214)
    do_dp: bool = False
    dp_mode: str = "worker"
    l2_norm_clip: float = 1.0
    noise_multiplier: float = 0.0

    # --- Compressor plugin knobs (ISSUE 19, commefficient_tpu/compress)
    # powersgd: rank of the per-client P/Q power-iteration factors —
    # the wire carries (m + n) * rank floats for the near-square
    # [m, n] factorization of the flat [grad_size] update
    powersgd_rank: int = 2
    # dp_sketch: the Gaussian mechanism on the sketch table. dp_clip
    # is the per-client Frobenius sensitivity bound on the count-
    # scaled table; dp_noise_mult the noise multiplier (noise std =
    # dp_noise_mult * dp_clip on the AGGREGATE, once per round);
    # dp_target_epsilon the fail-loud budget ceiling at dp_delta
    # (0 = track epsilon in the journal but never fail). Epsilon is
    # tracked by the Rényi accountant (compress/privacy.py) and
    # journaled per round as `privacy` events.
    dp_clip: float = 1.0
    dp_noise_mult: float = 0.0
    dp_target_epsilon: float = 0.0
    dp_delta: float = 1e-5

    # round scheduling (commefficient_tpu/scheduler, ISSUE 5): the
    # telemetry substrate's consumer. `sampler` picks the participant
    # policy — "uniform" is BIT-IDENTICAL to the pre-scheduler draw
    # (the default), "throughput" deprioritizes chronically slow
    # clients by their measured EMA examples/sec with an exploration
    # floor (`explore_floor`: every alive client keeps at least
    # floor/num_alive selection probability per slot so it keeps
    # getting measured). Throughput draws live on their own PRNG
    # domain, distinct from the dropout/straggler streams.
    sampler: str = "uniform"
    explore_floor: float = 0.1
    # deadline-driven rounds: 0 = off; otherwise each round's
    # wall-clock deadline is this quantile of the participants'
    # measured time estimates, and participants estimated past it get
    # work fractions deadline/estimate (floored at deadline_min_work)
    # on the EXISTING straggler work operand — deadline aggregation
    # stays inside the jitted round, three traced programs unchanged.
    # Unmeasured participants are never truncated (scheduler/deadline).
    deadline_quantile: float = 0.0
    deadline_min_work: float = 0.1
    # over-provisioning: sample ceil(target / expected-survival-rate)
    # participants (capped at num_workers) so EXPECTED survivors hit
    # this target; surplus compiled slots ride as survivor-mask zeros
    # (bit-exactly the dropped-client path). 0 = no target: fill every
    # slot, the pre-scheduler behavior.
    target_survivors: int = 0

    # pipelined round engine (ISSUE 10). OFF by default — the default
    # path is bit-identical to the pre-feature synchronous loop (the
    # pipelining machinery is never constructed). When on:
    #   * the scanned staging loop double-buffers dispatch
    #     (training/scanloop.py): span t+1's host staging — sampler
    #     draws, batch stacking, fault operands, explicit device
    #     placement — overlaps span t's device execution, and the
    #     span's accounting/journal/checkpoint commit one span late
    #     (FedModel.dispatch_rounds / collect_rounds);
    #   * journal appends and span-boundary checkpoint serialization
    #     move onto bounded-queue writer threads
    #     (telemetry/journal.RunJournal(async_writer=True),
    #     utils/checkpoint.AsyncCheckpointWriter) with flush-on-close
    #     and drain-at-crash — atomic-rename and torn-tail semantics
    #     unchanged;
    #   * the scanned span jit does NOT donate its state operands
    #     (round.py): the span-boundary checkpoint persists span t's
    #     state while span t+1 — which would otherwise consume those
    #     buffers in place — is already in flight, so double buffering
    #     transiently doubles state HBM (the price of the overlap).
    # Single-controller only for now (the writer threads and the
    # deferred commit would need cross-process barriers).
    pipeline: bool = False
    # buffered async aggregation (ISSUE 10): admit a straggler's late
    # contribution into round t+k instead of truncating it at round
    # t's deadline. A sampled client whose work fraction is below 1.0
    # (random straggler draw, FaultSchedule.slow, or a deadline
    # truncation) is DEFERRED: excluded from round t exactly like a
    # dropped client (no upload, state rows bit-untouched, accounting
    # charges nothing), then merged into round t+k's cohort operands
    # with its work fraction discounted by async_staleness_decay**k —
    # the FedNova-style processed-example reweighting the work operand
    # already implements turns that into a staleness-discounted
    # aggregation weight. Zero new traced programs: admission reuses
    # the existing dropout/straggler operand treedefs
    # (federated/async_agg.py). 0 = off (the synchronous straggler
    # path); k=0 via the buffer API is proven bit-identical to it.
    async_admit_rounds: int = 0
    # per-round staleness decay of a late-admitted contribution's
    # work fraction: weight = decay**rounds_late (1.0 = no discount)
    async_staleness_decay: float = 0.5

    # tiered cold client state (ISSUE 11). "device" — the default —
    # keeps the full [padded_population, D] client-state blocks
    # sharded in device HBM (bit-identical to the pre-feature
    # program: the tier machinery is never constructed). "host" caps
    # the device-resident rows at an LRU working set of
    # `state_working_set` recently-active clients; the long tail of
    # cold rows lives on the host (optionally disk-backed via
    # `state_spill_dir`), and the cohort-gather/scatter-back
    # state-motion pair moves rows between tiers: a sampled client
    # outside the working set is RESTORED into a device slot before
    # its round (through the same scatter program, as host-built
    # cohort rows) and the evicted victim's row is SPILLED to the
    # host tier off the critical path (the same gather program + an
    # async device->host copy on a bounded-queue writer thread — the
    # ISSUE-10 persistence pattern). The three round programs still
    # see only [num_workers, D] cohort operands (AU004 strict keeps
    # them honest), results are bit-identical to state_tier=device
    # (f32 rows round-trip the host exactly), and device HBM for
    # client state is O(working set) regardless of the population.
    # Single-controller only for now (the host tail is process-local;
    # per-process sharded tails are a ROADMAP opening).
    state_tier: str = "device"
    # device-HBM working-set size in client rows (state_tier=host):
    # the LRU keeps at most this many clients' state rows resident
    # (rounded up to the mesh's clients axis). Must be >= num_workers
    # (a round's whole cohort must fit), and on the scanned path
    # >= the distinct clients of one span (the span executes as one
    # device program, so its rows must all be resident at once —
    # FedModel raises an actionable error otherwise).
    state_working_set: int = 0
    # optional disk backing for the host tail (state_tier=host): cold
    # rows live in per-block f32 memmaps under this directory instead
    # of process RAM — sparse files, so untouched rows cost nothing.
    # Scratch state: rebuilt from the checkpoint's crows_* rows on
    # resume, never loaded across runs.
    state_spill_dir: str = ""

    # coordinator-broadcast control plane (ISSUE 12,
    # parallel/plantransport.py). "" — the default — attaches no
    # transport: non-default scheduling stays single-controller and
    # every code path is bit-identical to the pre-feature build.
    # "collective" attaches the production HostCollectiveTransport
    # (one fixed-size one-to-all host collective per round + a digest
    # allgather): the coordinator broadcasts each round's RoundPlan,
    # every process installs the RECEIVED plan, and Config.validate
    # then accepts throughput sampling / deadlines / async admission
    # in multihost runs. "emulated" replaces the run's scheduler with
    # an in-process N-controller harness (plan_controllers lockstep
    # controllers over an in-memory bus) — the CI surface for the
    # fault story, since this container cannot run multi-process jax.
    plan_transport: str = ""
    plan_controllers: int = 2
    # writer-thread watchdog (ISSUE 12 satellite): flush/drain timeout
    # in seconds for the three bounded-queue writers (journal,
    # checkpoint, state spill). 0 = wait forever (the old behavior);
    # positive turns a hung fsync into a TimeoutError NAMING the stuck
    # writer instead of a silent hang at crash-time drain.
    writer_drain_timeout_s: float = 0.0

    # set after model construction (reference mutates args.grad_size at
    # fed_aggregator.py:88; we return a new frozen Config instead)
    grad_size: int = 0

    # --- derived helpers -------------------------------------------------
    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)

    @property
    def compressor(self):
        """The registered Compressor plugin for this mode (ISSUE 19,
        commefficient_tpu/compress). Lazy import: compress imports
        this module for the MODES coverage assert, so the dependency
        must point compress -> config at module level and
        config -> compress only at property-call time."""
        from commefficient_tpu.compress import get_compressor
        return get_compressor(self.mode)

    @property
    def state_shape(self) -> Tuple[int, ...]:
        """Shape of the transmitted/accumulated quantity for this mode
        (reference: fed_aggregator.py:116-121,400-405; delegated to
        the mode's Compressor plugin)."""
        return self.compressor.state_shape(self)

    @property
    def upload_floats(self) -> int:
        """Floats uploaded per participating client per round
        (reference: fed_aggregator.py:291-299; delegated to the
        mode's Compressor plugin)."""
        return self.compressor.wire_floats(self)

    @property
    def upload_bytes(self) -> int:
        """Bytes uploaded per participating client per round AT THE
        WIRE DTYPE — the quantity the accountant bills and journals
        (ISSUE 6 accounting satellite; delegated to the mode's
        Compressor plugin). For sketch mode this is the [r, c] table
        at sketch_table_dtype's element size (plus int8's per-row f32
        scales); every other plugin transmits f32, so it is
        4 x upload_floats exactly as before."""
        return self.compressor.wire_bytes(self)

    @property
    def defer_sketch_encode(self) -> bool:
        """Sketch linearity optimization: when nothing nonlinear
        touches the per-client compressed quantity — no per-client DP
        clip/noise, no per-client table clip (and sketch mode never has
        per-client momentum/error state, see validate()) — the sum of
        per-client sketches equals the sketch of the summed gradient,
        so the round engine encodes ONCE per mesh shard after the local
        client sum instead of once per client (8 clients/shard -> 8x
        less encode work; measured in PERF.md)."""
        return (self.mode == "sketch" and not self.do_dp
                and self.max_grad_norm is None)

    @property
    def fused_client_backward(self) -> bool:
        """Backward-pass linearity optimization: when every per-client
        transmit is a LINEAR function of that client's gradient — no
        per-client DP/clipping, no per-client momentum/error state, no
        per-client weight staleness (topk_down), and no local_topk
        sparsification — the shard's summed transmit equals the
        gradient of the count-weighted summed loss, so the round
        engine runs ONE backward pass over all the shard's clients
        instead of a vmapped per-client backward. That removes the
        [W_shard, D] per-client gradient materialization (2 GB at
        GPT2-small x 4 clients) and lets XLA batch the weight-grad
        matmuls across clients; per-client losses/metrics still come
        from the (cheap) per-client forward values. Microbatching is
        gated out: the fused backward sees all clients' examples at
        once, which is exactly what microbatch_size exists to avoid."""
        return (self.mode in ("sketch", "uncompressed", "true_topk")
                and not self.do_dp and self.max_grad_norm is None
                and self.local_momentum == 0
                and self.error_type != "local"
                and not self.do_topk_down
                and self.microbatch_size <= 0)

    @property
    def robust_aggregation(self) -> bool:
        """True when the cross-client reduction is a robust order
        statistic (ISSUE 17). Robust rounds need PER-CLIENT update
        tables on device, so they always trace the screened program
        family (the per-client path) even with screening off.

        trimmed_mean with trim_beta == 0.0 trims nothing, so it is
        statically strength-reduced to the plain mean program: that
        keeps the inert setting bit-identical to ``--aggregator mean``
        even under defer_sketch_encode, where the mean path encodes
        the client SUM once while the robust path must encode every
        client before the order statistics (a ~1-ULP accumulation-
        order difference otherwise)."""
        if self.aggregator == "trimmed_mean" and self.trim_beta == 0.0:
            return False
        return self.aggregator != "mean"

    @property
    def adaptive_screen(self) -> bool:
        """True when the norm-screen threshold is the plan-carried
        traced operand the AdaptiveScreenController adjusts (ISSUE
        17); False keeps the static screen_norm_mult constant folded
        into the traced programs exactly as PR 16 shipped them."""
        return (self.target_screened_rate >= 0.0
                and self.update_screen == "norm")

    @property
    def span_palette(self) -> tuple:
        """Parsed --scan_span_palette: ascending unique span lengths,
        () when the adaptive span-cadence controller is off. Ascending
        order is the warmup trace order AND the argmin tie-break
        (np.argmin takes the first minimum → the shortest span wins a
        cadence tie), so the trajectory is deterministic in the flag
        string."""
        s = self.scan_span_palette.strip()
        if not s:
            return ()
        return tuple(sorted({int(tok) for tok in s.split(",")
                             if tok.strip()}))

    @property
    def control_loop(self) -> bool:
        """True when any bank-managed controller is enabled (the
        drivers then build plans every round so adjustments can ride
        them — control.make_bank returns non-None exactly when this
        does)."""
        return bool(self.speed_match or self.span_palette
                    or self.adapt_staleness)

    def resolved_num_clients(self, dataset_num_clients: Optional[int] = None) -> int:
        if self.num_clients is not None:
            return self.num_clients
        if dataset_num_clients is not None:
            return dataset_num_clients
        if self.dataset_name in DEFAULT_NUM_CLIENTS:
            return DEFAULT_NUM_CLIENTS[self.dataset_name]
        raise ValueError(
            f"num_clients must be given for dataset {self.dataset_name}"
        )

    def validate(self) -> "Config":
        """Config invariants; the scattered asserts of the reference
        (utils.py:225-228, fed_aggregator.py:484-486,573-576,
        fed_worker.py:62-63,221-228) centralized into one place."""
        if self.mode not in MODES:
            raise ValueError(f"unknown mode {self.mode}")
        if self.error_type not in ERROR_TYPES:
            raise ValueError(f"unknown error_type {self.error_type}")
        if self.dp_mode not in DP_MODES:
            raise ValueError(f"unknown dp_mode {self.dp_mode}")
        if self.mode == "fedavg":
            if self.local_batch_size != -1:
                raise ValueError("fedavg requires local_batch_size == -1")
            if self.local_momentum != 0:
                raise ValueError("fedavg requires local_momentum == 0")
            if self.error_type != "none":
                raise ValueError("fedavg requires error_type == none")
        if self.mode == "true_topk" and self.error_type != "virtual":
            raise ValueError("true_topk requires error_type == virtual")
        if self.mode == "local_topk" and self.error_type == "virtual":
            raise ValueError("local_topk cannot use virtual error")
        if self.mode == "sketch":
            if self.error_type == "local" and self.virtual_momentum != 0:
                raise ValueError("sketch+local error requires virtual_momentum=0")
            if self.error_type == "virtual" and self.local_momentum != 0:
                raise ValueError("sketch+virtual error requires local_momentum=0")
            if self.error_type == "local":
                raise ValueError(
                    "sketch mode cannot use per-client local error accumulation "
                    "(reference asserts this at fed_worker.py:221-222)"
                )
            if self.local_momentum != 0:
                raise ValueError(
                    "sketch mode cannot use local momentum "
                    "(reference asserts this at fed_worker.py:227-228)"
                )
        if self.mode == "uncompressed" and self.error_type == "local":
            raise ValueError(
                "uncompressed cannot use local error accumulation "
                "(reference asserts this at fed_worker.py:221-222)"
            )
        if not 0.0 <= self.client_dropout < 1.0:
            raise ValueError(
                f"client_dropout={self.client_dropout} must be in [0, 1) "
                "(1.0 would drop every client every round)")
        if not 0.0 <= self.straggler_rate <= 1.0:
            raise ValueError(
                f"straggler_rate={self.straggler_rate} must be in [0, 1]")
        if not 0.0 < self.straggler_min_work <= 1.0:
            raise ValueError(
                f"straggler_min_work={self.straggler_min_work} must be "
                "in (0, 1] (0 would draw clients that do no work at "
                "all — that's dropout, use client_dropout/cutoff)")
        if not 0.0 <= self.straggler_cutoff <= 1.0:
            raise ValueError(
                f"straggler_cutoff={self.straggler_cutoff} must be in "
                "[0, 1] (fractions below it degrade to dropout)")
        if self.update_screen not in SCREEN_MODES:
            raise ValueError(
                f"unknown update_screen {self.update_screen!r} "
                "(choices: off, finite, norm — federated/round.py "
                "screened programs)")
        if self.screen_norm_mult <= 1.0:
            raise ValueError(
                f"screen_norm_mult={self.screen_norm_mult} must be "
                "> 1 (an update AT the cohort median is by definition "
                "not an outlier; <= 1 would screen half the cohort "
                "every round)")
        if not 0.0 <= self.poison_rate < 1.0:
            raise ValueError(
                f"poison_rate={self.poison_rate} must be in [0, 1) "
                "(1.0 would corrupt every client every round — no "
                "finite update would ever survive the screen)")
        if self.poison_kind not in POISON_KINDS:
            raise ValueError(
                f"unknown poison_kind {self.poison_kind!r} "
                "(choices: nan, inf, scale — utils/faults)")
        if self.aggregator not in AGGREGATORS:
            raise ValueError(
                f"unknown aggregator {self.aggregator!r} (choices: "
                f"{', '.join(AGGREGATORS)} — federated/round.py "
                "robust programs)")
        if not 0.0 <= self.trim_beta < 0.5:
            raise ValueError(
                f"trim_beta={self.trim_beta} must be in [0, 0.5) "
                "(trimming half the cohort from EACH end leaves no "
                "client to average)")
        if not 0.0 <= self.byzantine_rate < 1.0:
            raise ValueError(
                f"byzantine_rate={self.byzantine_rate} must be in "
                "[0, 1) (1.0 leaves no honest client for the robust "
                "statistics to anchor on)")
        if self.attack not in ATTACKS:
            raise ValueError(
                f"unknown attack {self.attack!r} (choices: "
                f"{', '.join(ATTACKS)} — utils/faults adversary "
                "harness)")
        if self.byzantine_rate > 0 and self.poison_rate > 0:
            raise ValueError(
                "--byzantine_rate and --poison_rate are mutually "
                "exclusive: both ride the per-client fault operand, "
                "and a slot cannot be simultaneously an accidental "
                "value fault and a scripted adversary")
        if self.target_screened_rate >= 0:
            if self.update_screen != "norm":
                raise ValueError(
                    "--target_screened_rate adapts the NORM-screen "
                    "threshold and requires --update_screen norm "
                    "(finite screening has no threshold to adapt)")
            if self.target_screened_rate >= 1.0:
                raise ValueError(
                    f"target_screened_rate={self.target_screened_rate}"
                    " must be < 1 (screening the whole cohort every "
                    "round is a dead run)")
        if self.screen_adapt_step <= 0:
            raise ValueError(
                "screen_adapt_step must be > 0 (the multiplicative "
                "adjustment factor is 1 + step)")
        if not 1.0 < self.screen_mult_min <= self.screen_mult_max:
            raise ValueError(
                f"need 1 < screen_mult_min={self.screen_mult_min} <= "
                f"screen_mult_max={self.screen_mult_max} (same > 1 "
                "floor as screen_norm_mult)")
        if self.rollback_screen_rounds < 1:
            raise ValueError(
                "rollback_screen_rounds must be >= 1: a rollback that "
                "resumes with zero forced-screen rounds replays the "
                "same non-finite update and trips forever")
        if self.max_numeric_rollbacks < 0:
            raise ValueError(
                "max_numeric_rollbacks must be >= 0 (0 = a numeric "
                "trip fails loud immediately, no rollback)")
        if self.keep_checkpoints < 1:
            raise ValueError("keep_checkpoints must be >= 1")
        if self.ckpt_max_age_hours < 0:
            raise ValueError(
                "ckpt_max_age_hours must be >= 0 (0 = age pruning off)")
        if self.ckpt_every_spans < 0:
            raise ValueError(
                "ckpt_every_spans must be >= 0 (0 = no span-boundary "
                "saves, only the epoch cadence)")
        if self.profile_spans:
            # parse for side effect: a malformed spec fails at config
            # time with the flag named, not mid-run
            from commefficient_tpu.telemetry import parse_profile_spans
            parse_profile_spans(self.profile_spans)
            if not self.scan_rounds:
                # spans only exist on the scanned path — without it the
                # capture would silently never happen
                raise ValueError(
                    "--profile_spans requires --scan_rounds (span "
                    "indices select SCANNED spans; use --profile for "
                    "the per-round path's whole-first-epoch trace)")
            if not self.telemetry:
                # the capture is driven by the TelemetrySession that
                # --no_telemetry skips constructing
                raise ValueError(
                    "--profile_spans requires telemetry (drop "
                    "--no_telemetry: the session drives the capture)")
        if self.trace and not self.telemetry:
            # the tracer flushes through the telemetry session's
            # journal; without the session nothing would ever drain
            # the rings — fail loud like --profile_spans
            raise ValueError(
                "--trace requires telemetry (drop --no_telemetry: "
                "the session drains the trace rings into the journal)")
        if self.sampler not in ("uniform", "throughput"):
            raise ValueError(
                f"unknown sampler {self.sampler!r} (choices: uniform, "
                "throughput — commefficient_tpu/scheduler)")
        if not 0.0 <= self.explore_floor <= 1.0:
            raise ValueError(
                f"explore_floor={self.explore_floor} must be in [0, 1] "
                "(1.0 degenerates throughput sampling to uniform)")
        if not 0.0 <= self.deadline_quantile <= 1.0:
            raise ValueError(
                f"deadline_quantile={self.deadline_quantile} must be "
                "in [0, 1] (0 = no deadline)")
        if not 0.0 < self.deadline_min_work <= 1.0:
            raise ValueError(
                f"deadline_min_work={self.deadline_min_work} must be "
                "in (0, 1] — zero work is dropout, not a deadline "
                "truncation (use straggler_cutoff for degradation)")
        if self.target_survivors < 0:
            raise ValueError("target_survivors must be >= 0 (0 = fill "
                             "every participant slot)")
        if self.target_survivors > self.num_workers:
            raise ValueError(
                f"target_survivors={self.target_survivors} exceeds "
                f"num_workers={self.num_workers}: a round cannot "
                "produce more survivors than compiled participant "
                "slots")
        if not self.telemetry and (self.sampler != "uniform"
                                   or self.deadline_quantile > 0):
            # without the telemetry session nothing ever feeds the
            # throughput tracker, so these policies would silently
            # degenerate (uniform-with-floor sampling, a deadline that
            # never fires) — same fail-loud rule as --profile_spans.
            # --target_survivors is fine: its survival estimate falls
            # back to the 1 - client_dropout prior.
            raise ValueError(
                "--sampler throughput / --deadline_quantile require "
                "telemetry (drop --no_telemetry: the session feeds "
                "the throughput measurements these policies read)")
        if self.plan_transport not in ("", "collective", "emulated"):
            raise ValueError(
                f"unknown plan_transport {self.plan_transport!r} "
                "(choices: '' — none, collective — the production "
                "one-to-all host collective, emulated — the in-process "
                "N-controller harness; parallel/plantransport.py)")
        if self.plan_controllers < 1:
            raise ValueError("plan_controllers must be >= 1")
        if self.plan_transport == "emulated" and self.plan_controllers < 2:
            raise ValueError(
                "--plan_transport emulated needs --plan_controllers "
                ">= 2 (one coordinator plus at least one follower — "
                "a single controller has nobody to broadcast to and "
                "would silently test nothing)")
        if self.plan_transport and self.do_checkpoint \
                and not self.journal_path:
            raise ValueError(
                "--plan_transport with --checkpoint requires an "
                "explicit --journal_path: the write-ahead plan "
                "journal is the authoritative decision log a "
                "--resume takeover replays, and the default journal "
                "location (<run dir>/journal.jsonl) is a fresh "
                "timestamped directory each run — a resumed process "
                "could never find the crashed run's stream and would "
                "silently recompute (and diverge from) its durably "
                "committed plans")
        if self.plan_transport == "emulated" and self.multihost:
            raise ValueError(
                "--plan_transport emulated is the IN-PROCESS "
                "N-controller harness (one process pretending to be "
                "many) and cannot coexist with real multihost; use "
                "--plan_transport collective there")
        if (self.multihost and not self.plan_transport
                and (self.sampler != "uniform"
                     or self.deadline_quantile > 0
                     or self.target_survivors > 0)):
            raise ValueError(
                "scheduler policies (--sampler throughput / "
                "--deadline_quantile / --target_survivors) derive from "
                "process-local wall-clock throughput measurements and "
                "would diverge across controllers without a plan "
                "transport: attach --plan_transport collective (the "
                "coordinator broadcasts each round's RoundPlan and "
                "every process installs the received plan — "
                "parallel/plantransport.py)")
        if self.async_admit_rounds < 0:
            raise ValueError(
                "async_admit_rounds must be >= 0 (0 = synchronous "
                "stragglers, k = admit late contributions k rounds on)")
        if not 0.0 < self.async_staleness_decay <= 1.0:
            raise ValueError(
                f"async_staleness_decay={self.async_staleness_decay} "
                "must be in (0, 1] (1.0 = undiscounted late admission)")
        if self.multihost and self.pipeline:
            raise ValueError(
                "--pipeline is single-controller only for now: the "
                "persistence writer threads and the one-span-late "
                "commit would need cross-process barriers (a ROADMAP "
                "opening — the plan transport does not cover it)")
        if (self.multihost and self.async_admit_rounds > 0
                and not self.plan_transport):
            raise ValueError(
                "--async_admit_rounds needs a plan transport in "
                "multihost runs: the defer/admit merges are control "
                "decisions every controller must prove identical "
                "(each process defers/admits its OWN batch rows, but "
                "the slot/weight stream is digest-cross-checked) — "
                "attach --plan_transport collective "
                "(parallel/plantransport.py)")
        if self.speed_match:
            if self.async_admit_rounds <= 0:
                raise ValueError(
                    "--speed_match defers measured-slow clients into "
                    "async admission slots — it needs "
                    "--async_admit_rounds > 0 to have somewhere to "
                    "put them")
            if not 0.0 < self.speed_match_target < 1.0:
                raise ValueError(
                    f"speed_match_target={self.speed_match_target} "
                    "must be in (0, 1) (the deferred cohort fraction "
                    "the ratio is steered toward)")
            if self.speed_match_step <= 0:
                raise ValueError(
                    "speed_match_step must be > 0 (the multiplicative "
                    "adjustment per observed round)")
            if not (0.0 < self.speed_ratio_min
                    <= self.speed_ratio_max < 1.0):
                raise ValueError(
                    f"need 0 < speed_ratio_min={self.speed_ratio_min} "
                    f"<= speed_ratio_max={self.speed_ratio_max} < 1: "
                    "a ratio >= 1 would flag at-median clients as "
                    "slow and could defer half the cohort every round")
        if self.scan_span_palette.strip():
            pal = self.span_palette
            if any(p <= 0 for p in pal):
                raise ValueError(
                    f"scan_span_palette={self.scan_span_palette!r}: "
                    "span lengths must be positive")
            if 1 not in pal:
                raise ValueError(
                    f"scan_span_palette={self.scan_span_palette!r} "
                    "must include 1: the stream tail decomposes "
                    "greedily over the palette, and only a 1-span can "
                    "finish an arbitrary leftover without tracing a "
                    "new program shape")
            if not self.scan_rounds:
                raise ValueError(
                    "--scan_span_palette sizes the scanned staging "
                    "loop — enable --scan_rounds")
            if self.scan_span > 0:
                raise ValueError(
                    "--scan_span and --scan_span_palette are mutually "
                    "exclusive: the palette controller owns the span "
                    "length (static spans = --scan_span alone)")
        if self.adapt_staleness:
            if self.async_admit_rounds <= 0:
                raise ValueError(
                    "--adapt_staleness tunes the async admission "
                    "staleness discount — it needs "
                    "--async_admit_rounds > 0 for the discount to "
                    "apply to anything")
            if self.staleness_step <= 0:
                raise ValueError(
                    "staleness_step must be > 0 (the multiplicative "
                    "adjustment per observed round)")
            if not (0.0 < self.staleness_decay_min
                    <= self.staleness_decay_max <= 1.0):
                raise ValueError(
                    f"need 0 < staleness_decay_min="
                    f"{self.staleness_decay_min} <= staleness_decay_max="
                    f"{self.staleness_decay_max} <= 1 (1.0 = "
                    "undiscounted late admission)")
            if (self.pipeline and self.scan_rounds
                    and self.scan_span <= 0
                    and not self.scan_span_palette.strip()):
                raise ValueError(
                    "--adapt_staleness stamps a fixed-lag decay (the "
                    "lag bounds how far staging can run ahead of "
                    "commits), so pipelined --scan_rounds needs a "
                    "bounded span: set --scan_span or "
                    "--scan_span_palette (epoch-sized spans have no "
                    "static bound)")
        if self.writer_drain_timeout_s < 0:
            raise ValueError(
                "writer_drain_timeout_s must be >= 0 (0 = wait "
                "forever; positive = a hung journal/checkpoint/spill "
                "writer drain raises TimeoutError naming the writer)")
        if self.state_tier not in ("device", "host"):
            raise ValueError(
                f"unknown state_tier {self.state_tier!r} (choices: "
                "device — full population in device HBM, the default — "
                "or host — LRU working set on device, cold tail on "
                "host; federated/statestore.py)")
        if self.state_working_set < 0:
            raise ValueError("state_working_set must be >= 0")
        if self.state_tier != "device":
            if self.state_working_set <= 0:
                raise ValueError(
                    "--state_tier host requires --state_working_set N "
                    "(the device-HBM row budget; must be >= "
                    "num_workers)")
            if self.state_working_set < self.num_workers:
                raise ValueError(
                    f"state_working_set={self.state_working_set} < "
                    f"num_workers={self.num_workers}: one round's "
                    "whole cohort must fit in the device working set")
            if self.multihost:
                raise ValueError(
                    "--state_tier host is single-controller only for "
                    "now: the host tail is process-local state and "
                    "would need per-process sharded spill/restore "
                    "(the coordinator-broadcast ROADMAP opening)")
        if self.state_spill_dir and self.state_tier == "device":
            raise ValueError(
                "--state_spill_dir backs the HOST tail and requires "
                "--state_tier host (the device tier has no tail to "
                "spill)")
        if self.state_working_set > 0 and self.state_tier == "device":
            # fail loud rather than silently allocating the full
            # [padded_population, D] blocks in HBM — the exact OOM
            # the flag was set to prevent
            raise ValueError(
                "--state_working_set caps the device-resident rows of "
                "the HOST tier and requires --state_tier host (the "
                "device tier keeps every row in HBM, uncapped)")
        if self.kernel_backend not in ("xla", "pallas"):
            raise ValueError(
                f"unknown kernel_backend {self.kernel_backend!r} "
                "(choices: xla, pallas — commefficient_tpu/ops/kernels)")
        if self.sketch_table_dtype not in ("f32", "bf16", "int8"):
            raise ValueError(
                f"unknown sketch_table_dtype {self.sketch_table_dtype!r} "
                "(choices: f32, bf16, int8)")
        if self.sketch_table_dtype != "f32" and self.mode != "sketch":
            # fail loud rather than silently transmitting f32: the flag
            # names the SKETCH table, and no other mode has one
            raise ValueError(
                "--sketch_table_dtype quantizes the transmitted sketch "
                f"table and requires --mode sketch (got {self.mode!r})")
        if self.down_k < 0:
            raise ValueError("down_k must be >= 0 (0 = share the upload k)")
        if self.down_k > self.grad_size > 0:
            raise ValueError(
                f"down_k={self.down_k} exceeds grad_size={self.grad_size}")
        if self.dp_noise_mult != 0 and self.mode != "dp_sketch":
            # fail loud rather than silently training noise-free: the
            # flag names the dp_sketch Gaussian mechanism
            raise ValueError(
                "--dp_noise_mult calibrates the dp_sketch Gaussian "
                f"mechanism and requires --mode dp_sketch (got "
                f"{self.mode!r}; --dp/--noise_multiplier is the "
                "separate per-gradient DP path)")
        if self.dp_target_epsilon != 0 and self.mode != "dp_sketch":
            raise ValueError(
                "--dp_target_epsilon bounds the dp_sketch privacy "
                "budget and requires --mode dp_sketch (got "
                f"{self.mode!r})")
        # plugin-specific invariants (ISSUE 19): each Compressor
        # rejects the config combinations it does not compose with
        self.compressor.validate(self)
        return self


def _build_parser(default_lr: Optional[float] = None) -> argparse.ArgumentParser:
    """The reference CLI surface, flag for flag (utils.py:102-230)."""
    p = argparse.ArgumentParser()
    p.add_argument("--test", action="store_true", dest="do_test")
    p.add_argument("--mode", choices=list(MODES), default="sketch")
    p.add_argument("--tensorboard", dest="use_tensorboard", action="store_true")
    p.add_argument("--seed", type=int, default=21)

    p.add_argument("--model", default="ResNet9")
    p.add_argument("--finetune", action="store_true", dest="do_finetune")
    p.add_argument("--checkpoint", action="store_true", dest="do_checkpoint")
    p.add_argument("--checkpoint_path", type=str, default="./checkpoint")
    p.add_argument("--checkpoint_every", type=int, default=0)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--finetune_path", type=str, default="./finetune")
    p.add_argument("--finetuned_from", type=str, choices=list(FED_DATASETS))
    p.add_argument("--num_results_train", type=int, default=2)
    p.add_argument("--num_results_val", type=int, default=2)
    p.add_argument("--dataset_name", type=str, default="CIFAR10",
                   choices=list(FED_DATASETS))
    p.add_argument("--dataset_dir", type=str, default="./dataset")
    p.add_argument("--batchnorm", action="store_true", dest="do_batchnorm")
    p.add_argument("--nan_threshold", type=float, default=999)
    p.add_argument("--profile", action="store_true", dest="do_profile",
                   help="jax.profiler trace of the first epoch")
    p.add_argument("--no_telemetry", action="store_false",
                   dest="telemetry",
                   help="disable on-device round telemetry + the run "
                        "journal (telemetry is ON by default and "
                        "bit-neutral to training; see README "
                        "'Observability')")
    p.add_argument("--journal_path", type=str, default="",
                   help="structured JSONL run-journal path (default: "
                        "<run dir>/journal.jsonl; "
                        "telemetry/journal.py)")
    p.add_argument("--profile_spans", type=str, default="",
                   help="with --scan_rounds: jax.profiler-capture "
                        "scanned span indices [A, B), e.g. '2:4' "
                        "(trace lands in <run dir>/profile_spans and "
                        "the capture is journaled)")
    p.add_argument("--trace", action="store_true",
                   help="graftscope round-lifecycle tracing: "
                        "monotonic stage spans (plan/stage/dispatch/"
                        "device_execute/collect/tier motion/writer "
                        "queue-wait+fsync) buffered per thread and "
                        "flushed as batched `trace` journal events; "
                        "export with scripts/trace_export.py "
                        "(Perfetto), analyze with journal_summary.py "
                        "(per-stage p50/p95, overlap efficiency). "
                        "OFF by default — zero overhead, journal "
                        "unchanged (telemetry/trace.py)")
    p.add_argument("--debug_transfer_guard", action="store_true",
                   help="arm jax.transfer_guard('disallow') around "
                        "the steady-state training loop: any implicit "
                        "host<->device transfer (a hidden per-round "
                        "sync) raises instead of silently stalling "
                        "rounds (analysis/runtime.forbid_transfers)")

    p.add_argument("--k", type=int, default=50000)
    p.add_argument("--num_cols", type=int, default=500000)
    p.add_argument("--num_rows", type=int, default=5)
    p.add_argument("--num_blocks", type=int, default=20)
    p.add_argument("--topk_down", action="store_true", dest="do_topk_down")
    p.add_argument("--down_k", type=int, default=0,
                   help="download top-k budget (0 = share --k); see "
                        "Config.down_k")
    p.add_argument("--kernel_backend", choices=("xla", "pallas"),
                   default="xla",
                   help="compression hot-path kernels: xla (default, "
                        "bit-identical to the pre-kernel program) or "
                        "pallas (fused TPU kernels for sketch encode/"
                        "estimate/threshold decode; interpret-mode "
                        "off-TPU — commefficient_tpu/ops/kernels)")
    p.add_argument("--sketch_table_dtype",
                   choices=("f32", "bf16", "int8"), default="f32",
                   help="wire dtype of the transmitted sketch table "
                        "(sketch mode): bf16/int8 quantize the client-"
                        "sum table before aggregation — error feedback "
                        "absorbs the rounding noise, the accountant "
                        "bills bytes at this element size")

    p.add_argument("--local_momentum", type=float, default=0.9)
    p.add_argument("--virtual_momentum", type=float, default=0)
    p.add_argument("--weight_decay", type=float, default=5e-4)
    p.add_argument("--num_epochs", type=float, default=24)
    p.add_argument("--num_fedavg_epochs", type=int, default=1)
    p.add_argument("--fedavg_batch_size", type=int, default=-1)
    p.add_argument("--fedavg_lr_decay", type=float, default=1)
    p.add_argument("--error_type", choices=list(ERROR_TYPES), default="none")
    p.add_argument("--lr_scale", type=float, default=default_lr)
    p.add_argument("--pivot_epoch", type=float, default=5)

    p.add_argument("--client_dropout", type=float, default=0.0,
                   help="per-round probability a sampled client fails "
                        "to complete the round (survivor-reweighted "
                        "aggregation; Config.client_dropout)")
    p.add_argument("--no_donate_round_state", action="store_false",
                   dest="donate_round_state",
                   help="disable buffer donation of dead-after-"
                        "dispatch round state (donation is ON by "
                        "default: in-place HBM reuse of the server/"
                        "client state blocks, bit-identical results; "
                        "disable for callers that re-dispatch from a "
                        "retained state object or need failed span "
                        "dispatches to stay retryable — "
                        "Config.donate_round_state)")
    p.add_argument("--straggler_rate", type=float, default=0.0,
                   help="per-round probability a sampled client is a "
                        "straggler completing only a fraction of its "
                        "local work (Config.straggler_rate)")
    p.add_argument("--straggler_min_work", type=float, default=0.1,
                   help="lower bound of a straggler's uniform work-"
                        "fraction draw (Config.straggler_min_work)")
    p.add_argument("--straggler_cutoff", type=float, default=0.0,
                   help="work fractions below this degrade to client "
                        "dropout: no upload, state bit-untouched "
                        "(Config.straggler_cutoff)")
    p.add_argument("--update_screen", choices=list(SCREEN_MODES),
                   default="off",
                   help="in-round update admission (ISSUE 16, "
                        "federated/round.py): finite screens NaN/Inf "
                        "client updates, norm additionally screens "
                        "cohort-median norm outliers; a screened "
                        "client takes exactly the dropped-client path "
                        "(off = default, bit-identical programs)")
    p.add_argument("--screen_norm_mult", type=float, default=5.0,
                   help="norm-screen outlier threshold: screen a "
                        "client whose update l2 exceeds this multiple "
                        "of the cohort median l2 "
                        "(Config.screen_norm_mult)")
    p.add_argument("--poison_rate", type=float, default=0.0,
                   help="value-fault injection: per-round probability "
                        "a sampled client's update is corrupted "
                        "(deterministic in seed+round on its own PRNG "
                        "domain; utils/faults.poison_mask)")
    p.add_argument("--poison_kind", choices=list(POISON_KINDS),
                   default="nan",
                   help="corruption applied to a poisoned client's "
                        "update: nan/inf overwrite it, scale "
                        "multiplies by 2^40 (finite explosion — only "
                        "the norm screen catches it)")
    p.add_argument("--aggregator", choices=list(AGGREGATORS),
                   default="mean",
                   help="cross-client reduction inside the jitted "
                        "round (ISSUE 17): mean (default, reference "
                        "FetchSGD sum), coord_median / trimmed_mean "
                        "(per-cell order statistics over admitted "
                        "client tables), norm_clip (clip each client "
                        "to the cohort median l2 before the weighted "
                        "mean)")
    p.add_argument("--trim_beta", type=float, default=0.2,
                   help="trimmed_mean: fraction of admitted clients "
                        "trimmed from EACH end of every cell's order "
                        "statistics (Config.trim_beta)")
    p.add_argument("--byzantine_rate", type=float, default=0.0,
                   help="scripted adversary harness: per-round "
                        "probability a sampled client is an attacker "
                        "(deterministic in seed+round on its own "
                        "'byzantine' PRNG domain; "
                        "utils/faults.byzantine_mask)")
    p.add_argument("--attack", choices=list(ATTACKS),
                   default="sign_flip",
                   help="crafted update an attacker submits: "
                        "sign_flip/scaled are local corruptions; "
                        "colluding and little_is_enough are "
                        "coordinated, finite, norm-plausible updates "
                        "built from the honest cohort's statistics — "
                        "the class admission screening cannot catch")
    p.add_argument("--target_screened_rate", type=float, default=-1.0,
                   help="adaptive screening: adjust the norm-screen "
                        "threshold toward this per-round screened "
                        "fraction, every adjustment riding the "
                        "journaled RoundPlan (negative = off, static "
                        "--screen_norm_mult; requires --update_screen "
                        "norm; scheduler.AdaptiveScreenController)")
    p.add_argument("--screen_adapt_step", type=float, default=0.5,
                   help="adaptive screening multiplicative step: an "
                        "adjustment scales the threshold by "
                        "(1 + step) up or down "
                        "(Config.screen_adapt_step)")
    p.add_argument("--screen_mult_min", type=float, default=1.5,
                   help="adaptive screening threshold floor "
                        "(Config.screen_mult_min)")
    p.add_argument("--screen_mult_max", type=float, default=64.0,
                   help="adaptive screening threshold ceiling "
                        "(Config.screen_mult_max)")
    p.add_argument("--speed_match", action="store_true",
                   help="cohort speed-matching controller "
                        "(control/speed.py): defer clients measured "
                        "slower than speed_ratio x cohort-median rate "
                        "into --async_admit_rounds slots, the ratio "
                        "self-tuning toward --speed_match_target "
                        "(requires --async_admit_rounds > 0)")
    p.add_argument("--speed_match_target", type=float, default=0.25,
                   help="deferred cohort fraction the speed-matching "
                        "ratio is steered toward "
                        "(Config.speed_match_target)")
    p.add_argument("--speed_match_step", type=float, default=0.25,
                   help="speed-matching multiplicative step per "
                        "observed round (Config.speed_match_step)")
    p.add_argument("--speed_ratio", type=float, default=0.5,
                   help="starting slow-client threshold as a fraction "
                        "of the cohort median rate "
                        "(Config.speed_ratio)")
    p.add_argument("--speed_ratio_min", type=float, default=0.25,
                   help="speed-matching ratio floor "
                        "(Config.speed_ratio_min)")
    p.add_argument("--speed_ratio_max", type=float, default=0.9,
                   help="speed-matching ratio ceiling; must stay < 1 "
                        "(Config.speed_ratio_max)")
    p.add_argument("--scan_span_palette", type=str, default="",
                   help="adaptive span cadence (control/span.py): "
                        "comma-separated span lengths the scanned "
                        "staging loop may flush at, e.g. 1,2,4 — each "
                        "traces once at warmup, the seconds-per-round "
                        "EMA picks the steady-state length; must "
                        "include 1; empty = static --scan_span "
                        "(Config.scan_span_palette)")
    p.add_argument("--adapt_staleness", action="store_true",
                   help="adaptive staleness decay "
                        "(control/staleness.py): drive "
                        "async_staleness_decay from the "
                        "estimate_residual metric between the "
                        "configured bounds (requires "
                        "--async_admit_rounds > 0)")
    p.add_argument("--staleness_target", type=float, default=0.3,
                   help="estimate_residual level above which late "
                        "admissions are discounted harder "
                        "(Config.staleness_target)")
    p.add_argument("--staleness_step", type=float, default=0.25,
                   help="staleness-decay multiplicative step per "
                        "observed round (Config.staleness_step)")
    p.add_argument("--staleness_decay_min", type=float, default=0.2,
                   help="adaptive staleness decay floor "
                        "(Config.staleness_decay_min)")
    p.add_argument("--staleness_decay_max", type=float, default=0.95,
                   help="adaptive staleness decay ceiling "
                        "(Config.staleness_decay_max)")
    p.add_argument("--rollback_screen_rounds", type=int, default=8,
                   help="after a numeric_trip rollback, force update "
                        "screening on for this many rounds so the "
                        "replayed fault is screened instead of "
                        "re-tripping (Config.rollback_screen_rounds)")
    p.add_argument("--max_numeric_rollbacks", type=int, default=2,
                   help="cap on numeric_trip rollbacks per run; past "
                        "it the driver fails loud instead of "
                        "thrashing (Config.max_numeric_rollbacks)")
    p.add_argument("--keep_checkpoints", type=int, default=3,
                   help="keep the newest k rotated mid-run checkpoints "
                        "(utils/checkpoint.save_rotating)")
    p.add_argument("--ckpt_max_age_hours", type=float, default=0.0,
                   help="also prune rotated checkpoints older than "
                        "this wall-clock age in hours; 0 disables "
                        "(utils/checkpoint.save_rotating)")
    p.add_argument("--ckpt_every_spans", type=int, default=1,
                   help="with --scan_rounds and --checkpoint_every: "
                        "save at every k-th span boundary (1 bounds a "
                        "mid-span preemption's loss to one span; each "
                        "save is a full state gather — raise k to "
                        "bound the save rate; 0 = epoch cadence only)")

    p.add_argument("--pipeline", action="store_true",
                   help="pipelined round engine: double-buffered "
                        "scanned dispatch (span t+1 stages while span "
                        "t runs on device) + journal/checkpoint "
                        "persistence on bounded-queue writer threads. "
                        "OFF by default — the default loop is bit-"
                        "identical to the pre-feature program "
                        "(Config.pipeline)")
    p.add_argument("--async_admit_rounds", type=int, default=0,
                   help="buffered async aggregation: defer a "
                        "straggler's contribution out of its round "
                        "(bit-exactly the dropped-client path) and "
                        "admit it k rounds later with a staleness-"
                        "discounted work fraction on the existing "
                        "straggler operand (0 = synchronous; "
                        "Config.async_admit_rounds)")
    p.add_argument("--async_staleness_decay", type=float, default=0.5,
                   help="per-round decay of a late-admitted "
                        "contribution's work fraction: weight = "
                        "decay**rounds_late (1.0 = undiscounted)")
    p.add_argument("--state_tier", choices=("device", "host"),
                   default="device",
                   help="client-state residency tier: device (full "
                        "population sharded in device HBM, the "
                        "default — bit-identical to the pre-feature "
                        "program) or host (LRU working set of "
                        "--state_working_set rows on device, cold "
                        "tail spilled to host off the critical path; "
                        "federated/statestore.py)")
    p.add_argument("--state_working_set", type=int, default=0,
                   help="with --state_tier host: device-HBM working-"
                        "set size in client rows (>= num_workers; "
                        "on the scanned path >= a span's distinct "
                        "clients)")
    p.add_argument("--state_spill_dir", type=str, default="",
                   help="with --state_tier host: disk-back the host "
                        "tail with sparse f32 memmaps under this "
                        "directory (scratch state, rebuilt from "
                        "crows_* checkpoints on resume)")
    p.add_argument("--plan_transport",
                   choices=("", "collective", "emulated"), default="",
                   help="coordinator-broadcast control plane (ISSUE "
                        "12, parallel/plantransport.py): collective = "
                        "the production one-to-all host collective "
                        "(lifts the single-controller rejection of "
                        "non-default schedulers / --async_admit_rounds "
                        "in multihost runs), emulated = the in-process "
                        "N-controller harness (--plan_controllers; "
                        "chaos scripting via CCTPU_EMU_COORD_CRASH / "
                        "CCTPU_EMU_COORDINATOR env vars), '' = none "
                        "(the default — bit-identical to the "
                        "transport-free build)")
    p.add_argument("--plan_controllers", type=int, default=2,
                   help="controller count of the emulated plan-"
                        "transport harness (>= 2 when --plan_transport "
                        "emulated)")
    p.add_argument("--writer_drain_timeout_s", type=float, default=0.0,
                   help="flush/drain timeout for the bounded-queue "
                        "writer threads (journal, checkpoint, state "
                        "spill): a hung fsync raises TimeoutError "
                        "naming the stuck writer instead of hanging "
                        "the crash-time drain (0 = wait forever)")
    p.add_argument("--sampler", choices=("uniform", "throughput"),
                   default="uniform",
                   help="participant-sampling policy: uniform (bit-"
                        "identical to the pre-scheduler draw) or "
                        "throughput (deprioritize measured-slow "
                        "clients; commefficient_tpu/scheduler)")
    p.add_argument("--explore_floor", type=float, default=0.1,
                   help="throughput sampler's exploration floor: every "
                        "alive client keeps >= floor/num_alive "
                        "selection probability per slot")
    p.add_argument("--deadline_quantile", type=float, default=0.0,
                   help="per-round wall-clock deadline as this "
                        "quantile of participants' measured time "
                        "estimates; slower participants get truncated "
                        "work fractions on the straggler operand "
                        "(0 = no deadline)")
    p.add_argument("--deadline_min_work", type=float, default=0.1,
                   help="floor of a deadline-truncated work fraction "
                        "(fractions below --straggler_cutoff still "
                        "degrade to dropout)")
    p.add_argument("--target_survivors", type=int, default=0,
                   help="over-provision sampling so expected round "
                        "survivors hit this count; surplus slots ride "
                        "as survivor-mask zeros (0 = fill all slots)")
    p.add_argument("--port", type=int, default=5315)
    p.add_argument("--num_clients", type=int)
    p.add_argument("--num_workers", type=int, default=1)
    p.add_argument("--max_local_batch", type=int, default=-1,
                   help="cap the static per-client batch dim when "
                        "local_batch_size=-1 (bounds device staging "
                        "memory at ImageNet scale)")
    p.add_argument("--device", type=str, default="tpu")
    p.add_argument("--num_devices", type=int, default=1)
    p.add_argument("--share_ps_gpu", action="store_true")
    p.add_argument("--scan_rounds", action="store_true",
                   help="run each epoch as one scanned device program")
    p.add_argument("--scan_span", type=int, default=0,
                   help="flush scanned rounds every N rounds (0=epoch)")
    p.add_argument("--model_parallel", type=int, default=1,
                   help="tensor-parallel degree over the mesh's model "
                        "axis (GPT2-scale models; parallel/tp.py)")
    p.add_argument("--num_slices", type=int, default=1,
                   help="slice-major clients layout over DCN "
                        "(emulated when devices report no slice "
                        "topology; parallel/mesh.py)")
    p.add_argument("--multihost", action="store_true",
                   help="multi-controller run: jax.distributed."
                        "initialize before any backend use (auto-"
                        "detected grid on TPU pods; explicit "
                        "--coordinator_address/--num_processes/"
                        "--process_id elsewhere)")
    p.add_argument("--coordinator_address", type=str, default="",
                   help="host:port of process 0's coordination service")
    p.add_argument("--num_processes", type=int, default=0,
                   help="total controller processes (0 = auto-detect)")
    p.add_argument("--process_id", type=int, default=-1,
                   help="this process's index (-1 = auto-detect)")
    p.add_argument("--bf16", action="store_true", dest="do_bf16",
                   help="bfloat16 client fwd/bwd (f32 master weights)")
    p.add_argument("--remat", action="store_true", dest="do_remat",
                   help="rematerialize GPT2 blocks on backward "
                        "(activation memory -> O(1) blocks)")
    p.add_argument("--iid", action="store_true", dest="do_iid")
    p.add_argument("--train_dataloader_workers", type=int, default=0)
    p.add_argument("--val_dataloader_workers", type=int, default=0)

    p.add_argument("--model_checkpoint", type=str, default="gpt2")
    p.add_argument("--num_candidates", type=int, default=2)
    p.add_argument("--max_history", type=int, default=2)
    p.add_argument("--local_batch_size", type=int, default=8)
    p.add_argument("--valid_batch_size", type=int, default=8)
    p.add_argument("--microbatch_size", type=int, default=-1)
    p.add_argument("--lm_coef", type=float, default=1.0)
    p.add_argument("--mc_coef", type=float, default=1.0)
    p.add_argument("--max_grad_norm", type=float)
    p.add_argument("--personality_permutations", type=int, default=1)
    p.add_argument("--eval_before_start", action="store_true")

    p.add_argument("--dp", action="store_true", dest="do_dp")
    p.add_argument("--dp_mode", choices=list(DP_MODES), default="worker")
    p.add_argument("--l2_norm_clip", type=float, default=1.0)
    p.add_argument("--noise_multiplier", type=float, default=0.0)

    p.add_argument("--powersgd_rank", type=int, default=2,
                   help="with --mode powersgd: rank of the per-client "
                        "P/Q power-iteration factors — the wire "
                        "carries (m+n)*rank floats per client "
                        "(compress/powersgd.py)")
    p.add_argument("--dp_clip", type=float, default=1.0,
                   help="with --mode dp_sketch: per-client Frobenius "
                        "clip of the count-scaled sketch table — the "
                        "sum query's l2 sensitivity bound "
                        "(compress/dp_sketch.py)")
    p.add_argument("--dp_noise_mult", type=float, default=0.0,
                   help="with --mode dp_sketch: Gaussian noise "
                        "multiplier — noise std dp_noise_mult*dp_clip "
                        "added once per round to the aggregated table "
                        "inside the jitted round")
    p.add_argument("--dp_target_epsilon", type=float, default=0.0,
                   help="with --mode dp_sketch: fail-loud privacy "
                        "budget ceiling at --dp_delta; the Rényi "
                        "accountant journals cumulative epsilon per "
                        "round as `privacy` events and the run raises "
                        "when the budget is exhausted (0 = track but "
                        "never fail)")
    p.add_argument("--dp_delta", type=float, default=1e-5,
                   help="with --mode dp_sketch: the delta of the "
                        "(epsilon, delta)-DP guarantee the accountant "
                        "reports")
    return p


def parse_args(default_lr: Optional[float] = None, argv=None) -> Config:
    ns = _build_parser(default_lr).parse_args(argv)
    cfg = Config(**vars(ns))
    return cfg.validate()

"""ResNet9 (cifar10-fast style) in Flax, NHWC.

Capability parity with the reference model (reference:
CommEfficient/models/resnet9.py): prep/layer1+res/layer2/layer3+res
conv stack, optional BatchNorm (off by default — BN is problematic in
federated learning, reference utils.py:138 & SURVEY.md §7.3 #6), 0.125
logit scale (reference resnet9.py:9-14,93 `Mul`), head-swap finetune
support (reference :105-130).

TPU-first notes: NHWC layout (XLA:TPU's native conv layout), 3x3
convs without bias feed the MXU directly; when do_batchnorm is on,
normalization always uses the current batch's statistics — the
reference never synchronizes BN running stats across clients (worker
processes keep private stale buffers), so carrying running averages
would only replicate noise; computing batch stats keeps the model a
pure function of (params, batch).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

DEFAULT_CHANNELS = {"prep": 64, "layer1": 128, "layer2": 256, "layer3": 512}


class StatelessBatchNorm(nn.Module):
    """Batch normalization as a pure function of the current batch:
    learnable scale/bias, no running-average state (see module
    docstring for why running stats are deliberately absent)."""
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        mean = x.mean(axis=(0, 1, 2))
        var = x.var(axis=(0, 1, 2))
        scale = self.param("scale", nn.initializers.ones, (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        return (x - mean) * jax.lax.rsqrt(var + self.epsilon) * scale + bias


class ConvBlock(nn.Module):
    """conv3x3 (no bias) -> [BN] -> ReLU -> [pool] (reference ConvBN,
    models/resnet9.py:32-50)."""
    features: int
    do_batchnorm: bool = False
    pool: bool = False

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, (3, 3), strides=1, padding=1,
                    use_bias=False,
                    kernel_init=nn.initializers.he_normal())(x)
        if self.do_batchnorm:
            x = StatelessBatchNorm()(x)
        x = nn.relu(x)
        if self.pool:
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return x


class Residual(nn.Module):
    """x + relu-stack of two conv blocks (reference Residual,
    models/resnet9.py:61-68)."""
    features: int
    do_batchnorm: bool = False

    @nn.compact
    def __call__(self, x):
        y = ConvBlock(self.features, self.do_batchnorm)(x)
        y = ConvBlock(self.features, self.do_batchnorm)(y)
        return x + y


class ResNet9(nn.Module):
    num_classes: int = 10
    channels: Optional[Dict[str, int]] = None
    weight: float = 0.125           # logit scale (reference Mul)
    do_batchnorm: bool = False
    initial_channels: int = 3       # 1 for EMNIST (cv_train.py:353-354)

    @nn.compact
    def __call__(self, x):
        ch = self.channels or DEFAULT_CHANNELS
        x = ConvBlock(ch["prep"], self.do_batchnorm)(x)
        x = ConvBlock(ch["layer1"], self.do_batchnorm, pool=True)(x)
        x = Residual(ch["layer1"], self.do_batchnorm)(x)
        x = ConvBlock(ch["layer2"], self.do_batchnorm, pool=True)(x)
        x = ConvBlock(ch["layer3"], self.do_batchnorm, pool=True)(x)
        x = Residual(ch["layer3"], self.do_batchnorm)(x)
        # global max pool: equals the reference's MaxPool2d(4) on the
        # 4x4 CIFAR feature map, and stays well-defined for the 3x3
        # map that 28x28 EMNIST inputs produce
        x = x.max(axis=(1, 2))
        x = nn.Dense(self.num_classes, use_bias=False,
                     name="head")(x)
        return x * self.weight

"""GPT-2 with double heads (LM + multiple-choice), in Flax.

The reference imports `GPT2DoubleHeadsModel` from pytorch_transformers
(reference: CommEfficient/gpt2_train.py:4-6,262-273) — the model that
scores PersonaChat candidates with a language-modeling head and a
multiple-choice head at once. Here the architecture is built natively:

  * pre-LN transformer blocks with a fused QKV projection — one big
    [E, 3E] matmul per block keeps the MXU busy instead of three
    skinny ones;
  * the candidate axis is folded into the batch axis before the
    transformer ([B, C, L] -> [B*C, L]) so every matmul sees the full
    batch;
  * the LM head is weight-tied to the token embedding via
    `nn.Embed.attend` (no duplicate [V, E] parameter — 38M floats at
    GPT2-small scale);
  * causal masking is a static lower-triangular bias added pre-softmax
    (no dynamic shapes, jit-stable);
  * `resize_token_embeddings` is a pure function returning new params
    (the reference mutates the torch module in place,
    gpt2_train.py:101-112).

Pretrained GPT-2 weights can be imported from a local HuggingFace
`transformers` PyTorch checkpoint via `params_from_hf_state_dict`
(no network access is assumed — random init is the fallback, matching
a from-scratch federated run).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    # rematerialize each transformer block on the backward pass
    # (jax.checkpoint via nn.remat): activation memory drops from
    # O(n_layer) to O(1) blocks at ~1/3 extra FLOPs — the standard
    # trade for fitting bigger models/longer sequences per chip
    remat: bool = False

    def replace(self, **kw) -> "GPT2Config":
        return dataclasses.replace(self, **kw)


# GPT2-family presets (model_checkpoint flag values, reference
# gpt2_train.py:262-273 resolves "gpt2"/"gpt2-medium"/... the same way)
PRESETS = {
    "gpt2": GPT2Config(),
    "gpt2-medium": GPT2Config(n_embd=1024, n_layer=24, n_head=16),
    "gpt2-large": GPT2Config(n_embd=1280, n_layer=36, n_head=20),
    "gpt2-xl": GPT2Config(n_embd=1600, n_layer=48, n_head=25),
}


# sequences at/above this length route through the flash-attention
# path (ops/attention.py) instead of materializing [B, H, L, L]
FLASH_ATTENTION_MIN_LEN = 256


def _dense(features, cfg, name):
    return nn.Dense(
        features, name=name,
        kernel_init=nn.initializers.normal(cfg.initializer_range))


class SelfAttention(nn.Module):
    """Causal multi-head self-attention with a fused QKV projection."""
    cfg: GPT2Config

    @nn.compact
    def __call__(self, h):
        cfg = self.cfg
        B, L, E = h.shape
        H = cfg.n_head
        hd = E // H

        qkv = _dense(3 * E, cfg, "c_attn")(h)            # [B, L, 3E]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(x):  # [B, L, E] -> [B, H, L, hd]
            return x.reshape(B, L, H, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if L >= FLASH_ATTENTION_MIN_LEN:
            # long-context path: the Pallas flash kernel (XLA
            # scan-tiled on non-TPU backends) — O(L * block) memory
            # instead of the [B, H, L, L] score matrix
            from commefficient_tpu.ops.attention import flash_attention
            out = flash_attention(q, k, v).astype(v.dtype)
        else:
            # short sequences: plain einsum attention; logits in f32
            # regardless of activation dtype
            att = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                             preferred_element_type=jnp.float32)
            att = att / jnp.sqrt(jnp.float32(hd))
            causal = jnp.tril(jnp.ones((L, L), bool))
            att = jnp.where(causal[None, None], att, jnp.float32(-1e9))
            att = jax.nn.softmax(att, axis=-1).astype(v.dtype)
            out = jnp.einsum("bhqk,bhkd->bhqd", att, v)
        out = out.transpose(0, 2, 1, 3).reshape(B, L, E)
        return _dense(E, cfg, "c_proj")(out)


class MLP(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, h):
        E = self.cfg.n_embd
        h = _dense(4 * E, self.cfg, "c_fc")(h)
        h = nn.gelu(h, approximate=True)
        return _dense(E, self.cfg, "c_proj")(h)


class Block(nn.Module):
    """Pre-LN transformer block (GPT-2 ordering)."""
    cfg: GPT2Config

    @nn.compact
    def __call__(self, h):
        eps = self.cfg.layer_norm_epsilon
        h = h + SelfAttention(self.cfg, name="attn")(
            nn.LayerNorm(epsilon=eps, name="ln_1")(h))
        h = h + MLP(self.cfg, name="mlp")(
            nn.LayerNorm(epsilon=eps, name="ln_2")(h))
        return h


class GPT2Transformer(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None):
        cfg = self.cfg
        wte = nn.Embed(cfg.vocab_size, cfg.n_embd, name="wte",
                       embedding_init=nn.initializers.normal(
                           cfg.initializer_range))
        wpe = nn.Embed(cfg.n_positions, cfg.n_embd, name="wpe",
                       embedding_init=nn.initializers.normal(
                           cfg.initializer_range))
        L = input_ids.shape[-1]
        h = wte(input_ids) + wpe(jnp.arange(L))
        if token_type_ids is not None:
            # GPT-2 looks token types up in the SAME token embedding
            # (they are ordinary special-token ids)
            h = h + wte(token_type_ids)
        block_cls = nn.remat(Block) if cfg.remat else Block
        for i in range(cfg.n_layer):
            h = block_cls(cfg, name=f"h_{i}")(h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, name="ln_f")(h)
        # weight-tied LM logits
        lm_logits = wte.attend(h)
        return h, lm_logits


class GPT2DoubleHeads(nn.Module):
    """LM head + multiple-choice head over candidate sequences.

    __call__(input_ids [..., C, L], token_type_ids [..., C, L],
             mc_token_ids [..., C]) ->
        (lm_logits [..., C, L, V], mc_logits [..., C])

    The MC head reads the hidden state at each candidate's
    `mc_token_ids` position (the last real token) and projects to one
    scalar per candidate — the reference's SequenceSummary head.
    """
    cfg: GPT2Config

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, mc_token_ids=None):
        cfg = self.cfg
        lead = input_ids.shape[:-1]      # [..., C]
        L = input_ids.shape[-1]
        flat_ids = input_ids.reshape(-1, L)
        flat_tt = (token_type_ids.reshape(-1, L)
                   if token_type_ids is not None else None)

        h, lm_logits = GPT2Transformer(cfg, name="transformer")(
            flat_ids, flat_tt)

        if mc_token_ids is None:
            mc_pos = jnp.full((h.shape[0],), L - 1, jnp.int32)
        else:
            mc_pos = mc_token_ids.reshape(-1).astype(jnp.int32)
        summary = jnp.take_along_axis(
            h, mc_pos[:, None, None], axis=1)[:, 0]       # [N, E]
        mc_logits = _dense(1, cfg, "mc_head")(summary)[:, 0]

        # reshape by the logits' own vocab axis (it can exceed
        # cfg.vocab_size after resize_token_embeddings)
        return (lm_logits.reshape(lead + (L, lm_logits.shape[-1])),
                mc_logits.reshape(lead))


def build_gpt2(model_checkpoint: str = "gpt2",
               **overrides) -> GPT2DoubleHeads:
    """Resolve a GPT2 preset by flag name (reference resolves the HF
    checkpoint string the same way, gpt2_train.py:262-273)."""
    cfg = PRESETS.get(model_checkpoint, PRESETS["gpt2"])
    if overrides:
        cfg = cfg.replace(**overrides)
    return GPT2DoubleHeads(cfg)


def resize_token_embeddings(params, new_vocab_size: int,
                            key: Optional[jax.Array] = None,
                            initializer_range: float = 0.02):
    """Grow the (tied) token embedding to `new_vocab_size` rows,
    returning new params — the functional form of the reference's
    in-place `model.resize_token_embeddings` after special tokens are
    added (gpt2_train.py:101-112). New rows are N(0, initializer_range)
    like fresh GPT-2 embeddings. Pair the returned params with a module
    rebuilt as `GPT2DoubleHeads(cfg.replace(vocab_size=new))` — flax
    validates parameter shapes against the module config."""
    params = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
    wte = params["params"]["transformer"]["wte"]["embedding"]
    old_vocab, E = wte.shape
    if new_vocab_size <= old_vocab:
        return params
    if key is None:
        key = jax.random.PRNGKey(0)
    new_rows = jax.random.normal(
        key, (new_vocab_size - old_vocab, E), wte.dtype) * initializer_range
    params["params"]["transformer"]["wte"]["embedding"] = jnp.concatenate(
        [wte, new_rows], axis=0)
    return params


def resize_position_embeddings(params, new_n_positions: int,
                               key: Optional[jax.Array] = None,
                               initializer_range: float = 0.02):
    """Grow the position embedding to cover a longer corpus, returning
    new params. Needed when a saved artifact (whose n_positions rides
    along in config.json) is loaded against a corpus padded longer than
    the one it was trained on — without this, out-of-range position ids
    silently clamp to the last row under jit."""
    params = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
    wpe = params["params"]["transformer"]["wpe"]["embedding"]
    old_n, E = wpe.shape
    if new_n_positions <= old_n:
        return params
    if key is None:
        key = jax.random.PRNGKey(0)
    new_rows = jax.random.normal(
        key, (new_n_positions - old_n, E), wpe.dtype) * initializer_range
    params["params"]["transformer"]["wpe"]["embedding"] = jnp.concatenate(
        [wpe, new_rows], axis=0)
    return params


# ---- pretrained-weight import (local HF torch checkpoints) --------------

def params_from_hf_state_dict(state_dict: Dict[str, Any],
                              cfg: GPT2Config,
                              key: Optional[jax.Array] = None) -> dict:
    """Convert a HuggingFace PyTorch GPT-2 state dict to this module's
    parameter pytree. HF's Conv1D stores weights as [in, out] — the
    same layout as flax.linen.Dense kernels — so projection weights map
    without transposition; LayerNorm weight/bias map to scale/bias.

    Works with `GPT2LMHeadModel`/`GPT2Model` checkpoints: the MC head
    (absent from LM-only checkpoints) gets a fresh
    N(0, initializer_range) kernel from `key` — it is always trained
    from scratch for PersonaChat anyway."""
    def t(name):
        arr = state_dict[name]
        # torch tensors and numpy arrays both convert via np.asarray
        return jnp.asarray(np.asarray(arr.detach().cpu()
                                      if hasattr(arr, "detach") else arr))

    prefix = ""
    if any(k.startswith("transformer.") for k in state_dict):
        prefix = "transformer."

    tr: Dict[str, Any] = {
        "wte": {"embedding": t(prefix + "wte.weight")},
        "wpe": {"embedding": t(prefix + "wpe.weight")},
        "ln_f": {"scale": t(prefix + "ln_f.weight"),
                 "bias": t(prefix + "ln_f.bias")},
    }
    for i in range(cfg.n_layer):
        p = f"{prefix}h.{i}."
        tr[f"h_{i}"] = {
            "ln_1": {"scale": t(p + "ln_1.weight"),
                     "bias": t(p + "ln_1.bias")},
            "ln_2": {"scale": t(p + "ln_2.weight"),
                     "bias": t(p + "ln_2.bias")},
            "attn": {
                "c_attn": {"kernel": t(p + "attn.c_attn.weight"),
                           "bias": t(p + "attn.c_attn.bias")},
                "c_proj": {"kernel": t(p + "attn.c_proj.weight"),
                           "bias": t(p + "attn.c_proj.bias")},
            },
            "mlp": {
                "c_fc": {"kernel": t(p + "mlp.c_fc.weight"),
                         "bias": t(p + "mlp.c_fc.bias")},
                "c_proj": {"kernel": t(p + "mlp.c_proj.weight"),
                           "bias": t(p + "mlp.c_proj.bias")},
            },
        }
    E = cfg.n_embd
    # MC head: present in double-heads checkpoints (HF names it
    # `multiple_choice_head.summary`, a torch Linear with [out, in]
    # weights — transpose into the Dense kernel layout); LM-only
    # checkpoints get a fresh N(0, initializer_range) kernel
    mc_name = "multiple_choice_head.summary."
    if mc_name + "weight" in state_dict:
        mc_kernel = t(mc_name + "weight").T            # [1, E] -> [E, 1]
        mc_bias = t(mc_name + "bias")
    else:
        if key is None:
            key = jax.random.PRNGKey(0)
        mc_kernel = (jax.random.normal(key, (E, 1), jnp.float32)
                     * cfg.initializer_range)
        mc_bias = jnp.zeros((1,), jnp.float32)
    return {"params": {
        "transformer": tr,
        "mc_head": {"kernel": mc_kernel, "bias": mc_bias},
    }}


def hf_state_dict_from_params(params, cfg: GPT2Config) -> Dict[str, np.ndarray]:
    """Inverse of params_from_hf_state_dict: emit a HuggingFace
    GPT2DoubleHeadsModel-style state dict (numpy values). Projection
    kernels keep the Conv1D [in, out] layout; the MC head transposes
    back to torch Linear [out, in]; `lm_head.weight` aliases the tied
    token embedding, as HF serializes it."""
    def a(x):
        return np.asarray(x)

    p = params["params"]
    tr = p["transformer"]
    sd: Dict[str, np.ndarray] = {
        "transformer.wte.weight": a(tr["wte"]["embedding"]),
        "transformer.wpe.weight": a(tr["wpe"]["embedding"]),
        "transformer.ln_f.weight": a(tr["ln_f"]["scale"]),
        "transformer.ln_f.bias": a(tr["ln_f"]["bias"]),
        "lm_head.weight": a(tr["wte"]["embedding"]),
        "multiple_choice_head.summary.weight": a(p["mc_head"]["kernel"]).T,
        "multiple_choice_head.summary.bias": a(p["mc_head"]["bias"]),
    }
    for i in range(cfg.n_layer):
        b = tr[f"h_{i}"]
        pre = f"transformer.h.{i}."
        sd[pre + "ln_1.weight"] = a(b["ln_1"]["scale"])
        sd[pre + "ln_1.bias"] = a(b["ln_1"]["bias"])
        sd[pre + "ln_2.weight"] = a(b["ln_2"]["scale"])
        sd[pre + "ln_2.bias"] = a(b["ln_2"]["bias"])
        sd[pre + "attn.c_attn.weight"] = a(b["attn"]["c_attn"]["kernel"])
        sd[pre + "attn.c_attn.bias"] = a(b["attn"]["c_attn"]["bias"])
        sd[pre + "attn.c_proj.weight"] = a(b["attn"]["c_proj"]["kernel"])
        sd[pre + "attn.c_proj.bias"] = a(b["attn"]["c_proj"]["bias"])
        sd[pre + "mlp.c_fc.weight"] = a(b["mlp"]["c_fc"]["kernel"])
        sd[pre + "mlp.c_fc.bias"] = a(b["mlp"]["c_fc"]["bias"])
        sd[pre + "mlp.c_proj.weight"] = a(b["mlp"]["c_proj"]["kernel"])
        sd[pre + "mlp.c_proj.bias"] = a(b["mlp"]["c_proj"]["bias"])
    return sd


def save_pretrained(log_dir: str, params, cfg: GPT2Config,
                    tokenizer=None) -> str:
    """HF-style final artifact (the reference saves tokenizer + config
    into log_dir at startup, gpt2_train.py:275-283, and the finetuned
    weights via model.save_pretrained(log_dir) at teardown,
    fed_aggregator.py:208-211): writes `pytorch_model.bin` (torch state
    dict in HF double-heads naming), `config.json`, and the tokenizer's
    own files when it can save itself. The directory round-trips
    through `load_pretrained_dir` and — for the transformer weights —
    through stock `transformers` `from_pretrained`."""
    import json
    import os

    os.makedirs(log_dir, exist_ok=True)
    hf_sd = hf_state_dict_from_params(params, cfg)
    try:
        import torch
        sd = {k: torch.from_numpy(np.ascontiguousarray(v))
              for k, v in hf_sd.items()}
        torch.save(sd, os.path.join(log_dir, "pytorch_model.bin"))
    except ImportError:
        # torch-less environment: same state dict, npz container (the
        # artifact still round-trips through load_pretrained_dir; only
        # stock-transformers interop needs the .bin)
        from commefficient_tpu.utils.atomic_io import atomic_savez
        atomic_savez(os.path.join(log_dir, "pytorch_model.npz"), **hf_sd)
    conf = {
        "model_type": "gpt2",
        "architectures": ["GPT2DoubleHeadsModel"],
        "vocab_size": cfg.vocab_size,
        "n_positions": cfg.n_positions,
        "n_ctx": cfg.n_positions,
        "n_embd": cfg.n_embd,
        "n_layer": cfg.n_layer,
        "n_head": cfg.n_head,
        "layer_norm_epsilon": cfg.layer_norm_epsilon,
        "initializer_range": cfg.initializer_range,
    }
    from commefficient_tpu.utils.atomic_io import atomic_write_text
    atomic_write_text(os.path.join(log_dir, "config.json"),
                      json.dumps(conf, indent=1))
    if tokenizer is not None:
        inner = getattr(tokenizer, "tok", tokenizer)
        if hasattr(inner, "save_pretrained"):
            inner.save_pretrained(log_dir)
        else:
            # offline HashTokenizer: record enough to rebuild it
            atomic_write_text(
                os.path.join(log_dir, "tokenizer_config.json"),
                json.dumps({"tokenizer_class": "HashTokenizer",
                            "vocab_size": len(tokenizer)}))
    return log_dir


def load_pretrained_dir(path: str,
                        key: Optional[jax.Array] = None
                        ) -> Optional[Tuple[dict, GPT2Config]]:
    """Load a `save_pretrained` artifact directly — config.json +
    pytorch_model.bin — without instantiating a transformers model (the
    double-heads class differs across transformers versions; the state
    dict doesn't). Returns (params, cfg) or None if `path` is not such
    a directory."""
    import json
    import os

    cfg_path = os.path.join(path, "config.json")
    bin_path = os.path.join(path, "pytorch_model.bin")
    npz_path = os.path.join(path, "pytorch_model.npz")
    if not os.path.isfile(cfg_path):
        return None
    if os.path.isfile(bin_path):
        import torch
        sd = torch.load(bin_path, map_location="cpu", weights_only=True)
    elif os.path.isfile(npz_path):
        sd = dict(np.load(npz_path))
    else:
        return None

    with open(cfg_path) as f:
        raw = json.load(f)
    cfg = GPT2Config(
        vocab_size=raw["vocab_size"],
        n_positions=raw.get("n_positions", 1024),
        n_embd=raw["n_embd"], n_layer=raw["n_layer"],
        n_head=raw["n_head"],
        layer_norm_epsilon=raw.get("layer_norm_epsilon", 1e-5),
        initializer_range=raw.get("initializer_range", 0.02))
    return params_from_hf_state_dict(sd, cfg, key=key), cfg


def try_load_pretrained(model_checkpoint: str, cfg: GPT2Config,
                        key: Optional[jax.Array] = None) -> Optional[dict]:
    """Best-effort local pretrained load through `transformers` (torch
    CPU). Returns None when no local checkpoint exists — network
    download is never attempted (zero-egress environment)."""
    try:
        from transformers import GPT2LMHeadModel
        pt = GPT2LMHeadModel.from_pretrained(
            model_checkpoint, local_files_only=True)
    except (ImportError, OSError, ValueError, RuntimeError):
        # transformers/torch missing, no locally-cached checkpoint, or
        # a torn cache — the expected offline failure modes; anything
        # else (incl. InjectedFault from the fault harness) raises
        return None
    return params_from_hf_state_dict(pt.state_dict(), cfg, key=key)

"""Torchvision-style ResNet family in Flax, NHWC, with pluggable
normalization.

Capability parity with the reference's modified-torchvision copy
(reference: CommEfficient/models/resnets.py — ResNet18..Wide101 with a
`norm_layer` hook extended to support LayerNorm by threading the
spatial size through blocks, :79-98,191+; and
models/resnet101ln.py:8-13 `ResNet101LN`), plus a Fixup bottleneck
variant covering the capability of models/fixup_resnet.py
(FixupResNet50, whose implementation the reference imports from an
external, non-vendored package).

Norm options: "batch" (stateless batch statistics — see
resnet9.StatelessBatchNorm), "layer" (normalizes over (H, W, C) like
torch nn.LayerNorm([C, H, W]); no spatial-size threading needed — in
Flax the shape is known at trace time), "group", "none".
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from commefficient_tpu.models.resnet9 import StatelessBatchNorm
from commefficient_tpu.models.fixup_resnet import (
    ScalarAdd, ScalarMul, _fixup_branch_init, _out_fan_init,
)


def _norm(kind: str, name: str):
    if kind == "batch":
        return StatelessBatchNorm(name=name)
    if kind == "layer":
        return nn.LayerNorm(reduction_axes=(-3, -2, -1),
                            feature_axes=(-3, -2, -1), name=name)
    if kind == "group":
        return nn.GroupNorm(num_groups=32, name=name)
    if kind == "none":
        return lambda x: x
    raise ValueError(f"unknown norm {kind}")


class BasicBlock(nn.Module):
    features: int
    stride: int = 1
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        y = nn.Conv(self.features, (3, 3), strides=self.stride, padding=1,
                    use_bias=False, name="conv1")(x)
        y = nn.relu(_norm(self.norm, "bn1")(y))
        y = nn.Conv(self.features, (3, 3), strides=1, padding=1,
                    use_bias=False, name="conv2")(y)
        y = _norm(self.norm, "bn2")(y)
        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.features:
            shortcut = nn.Conv(self.features, (1, 1), strides=self.stride,
                               use_bias=False, name="downsample")(x)
            shortcut = _norm(self.norm, "bn_down")(shortcut)
        return nn.relu(y + shortcut)


class Bottleneck(nn.Module):
    features: int      # bottleneck width; output is 4x
    stride: int = 1
    norm: str = "batch"
    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        out_ch = self.features * self.expansion
        y = nn.Conv(self.features, (1, 1), use_bias=False, name="conv1")(x)
        y = nn.relu(_norm(self.norm, "bn1")(y))
        y = nn.Conv(self.features, (3, 3), strides=self.stride, padding=1,
                    use_bias=False, name="conv2")(y)
        y = nn.relu(_norm(self.norm, "bn2")(y))
        y = nn.Conv(out_ch, (1, 1), use_bias=False, name="conv3")(y)
        y = _norm(self.norm, "bn3")(y)
        shortcut = x
        if self.stride != 1 or x.shape[-1] != out_ch:
            shortcut = nn.Conv(out_ch, (1, 1), strides=self.stride,
                               use_bias=False, name="downsample")(x)
            shortcut = _norm(self.norm, "bn_down")(shortcut)
        return nn.relu(y + shortcut)


class FixupBottleneck(nn.Module):
    """Fixup-initialized bottleneck (norm-free ResNet50-class nets)."""
    features: int
    stride: int = 1
    num_layers: int = 16
    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        out_ch = self.features * self.expansion
        y = ScalarAdd(name="add1a")(x)
        y = nn.Conv(self.features, (1, 1), use_bias=False,
                    kernel_init=_fixup_branch_init(self.num_layers),
                    name="conv1")(y)
        y = nn.relu(ScalarAdd(name="add1b")(y))
        y = ScalarAdd(name="add2a")(y)
        y = nn.Conv(self.features, (3, 3), strides=self.stride, padding=1,
                    use_bias=False,
                    kernel_init=_fixup_branch_init(self.num_layers),
                    name="conv2")(y)
        y = nn.relu(ScalarAdd(name="add2b")(y))
        y = ScalarAdd(name="add3a")(y)
        y = nn.Conv(out_ch, (1, 1), use_bias=False,
                    kernel_init=nn.initializers.zeros, name="conv3")(y)
        y = ScalarAdd(name="add3b")(ScalarMul(name="mul")(y))
        shortcut = x
        if self.stride != 1 or x.shape[-1] != out_ch:
            shortcut = nn.Conv(out_ch, (1, 1), strides=self.stride,
                               use_bias=False, kernel_init=_out_fan_init(),
                               name="downsample")(x)
        return nn.relu(y + shortcut)


class ResNet(nn.Module):
    """Generic ImageNet-stem ResNet (reference models/resnets.py ResNet)."""
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    block: str = "bottleneck"   # "basic" | "bottleneck" | "fixup_bottleneck"
    norm: str = "batch"
    width: int = 64             # base width (128 for wide variants)
    initial_channels: int = 3
    small_input: bool = False   # CIFAR-style 3x3 stem, no maxpool

    @nn.compact
    def __call__(self, x):
        L = sum(self.stage_sizes)
        if self.small_input:
            x = nn.Conv(64, (3, 3), strides=1, padding=1, use_bias=False,
                        name="conv1")(x)
        else:
            x = nn.Conv(64, (7, 7), strides=2, padding=3, use_bias=False,
                        name="conv1")(x)
        if self.block != "fixup_bottleneck":
            x = _norm(self.norm, "bn1")(x)
        x = nn.relu(x)
        if not self.small_input:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for stage, n in enumerate(self.stage_sizes):
            feats = self.width * (2 ** stage)
            for i in range(n):
                stride = 2 if (stage > 0 and i == 0) else 1
                if self.block == "basic":
                    x = BasicBlock(feats, stride, self.norm)(x)
                elif self.block == "bottleneck":
                    x = Bottleneck(feats, stride, self.norm)(x)
                else:
                    x = FixupBottleneck(feats, stride, num_layers=L)(x)

        x = x.mean(axis=(1, 2))
        head_init = (nn.initializers.zeros
                     if self.block == "fixup_bottleneck"
                     else nn.initializers.lecun_normal())
        x = nn.Dense(self.num_classes, kernel_init=head_init, name="fc")(x)
        return x


# ---- named constructors (reference models/resnets.py:250+ factory fns) ----

def resnet18(**kw):
    return ResNet(stage_sizes=(2, 2, 2, 2), block="basic", **kw)

def resnet34(**kw):
    return ResNet(stage_sizes=(3, 4, 6, 3), block="basic", **kw)

def resnet50(**kw):
    return ResNet(stage_sizes=(3, 4, 6, 3), block="bottleneck", **kw)

def resnet101(**kw):
    return ResNet(stage_sizes=(3, 4, 23, 3), block="bottleneck", **kw)

def resnet152(**kw):
    return ResNet(stage_sizes=(3, 8, 36, 3), block="bottleneck", **kw)

def wide_resnet50_2(**kw):
    return ResNet(stage_sizes=(3, 4, 6, 3), block="bottleneck", width=128, **kw)

def wide_resnet101_2(**kw):
    return ResNet(stage_sizes=(3, 4, 23, 3), block="bottleneck", width=128, **kw)

def resnet101ln(**kw):
    """(reference models/resnet101ln.py:8-13)"""
    kw.setdefault("norm", "layer")
    return resnet101(**kw)

def fixup_resnet50(**kw):
    """(capability of reference models/fixup_resnet.py FixupResNet50)"""
    return ResNet(stage_sizes=(3, 4, 6, 3), block="fixup_bottleneck", **kw)


# Mark each **kw factory with the dataclass it forwards to and the
# keywords it binds itself, so the model registry can filter a shared
# model_config dict against the real field set (the reference passes
# one config dict to every model class, cv_train.py:329-364) without
# forwarding keys the factory already fixes.
for _f, _bound in (
    (resnet18, {"stage_sizes", "block"}),
    (resnet34, {"stage_sizes", "block"}),
    (resnet50, {"stage_sizes", "block"}),
    (resnet101, {"stage_sizes", "block"}),
    (resnet152, {"stage_sizes", "block"}),
    (wide_resnet50_2, {"stage_sizes", "block", "width"}),
    (wide_resnet101_2, {"stage_sizes", "block", "width"}),
    (resnet101ln, {"stage_sizes", "block", "norm"}),
    (fixup_resnet50, {"stage_sizes", "block"}),
):
    _f.__forwards_to__ = ResNet
    _f.__bound_fields__ = _bound
del _f, _bound

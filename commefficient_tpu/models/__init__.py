"""Model registry.

Name-compatible with the reference's string-resolved model flags
(reference: CommEfficient/utils.py:114-118 builds --model choices from
dir(models); cv_train.py:363 resolves by getattr). The reference only
exports ResNet9 (models/__init__.py:1-7) but ships the whole family;
here everything ships working.
"""
from __future__ import annotations

import dataclasses
import inspect
from typing import Callable, Dict

from commefficient_tpu.models.resnet9 import ResNet9, StatelessBatchNorm  # noqa: F401
from commefficient_tpu.models.fixup_resnet import (  # noqa: F401
    FixupResNet18, FixupResNet9, ResNet18,
)
from commefficient_tpu.models import resnets
from commefficient_tpu.models.resnets import ResNet  # noqa: F401
from commefficient_tpu.models.gpt2 import (  # noqa: F401
    GPT2Config, GPT2DoubleHeads, build_gpt2,
)

_REGISTRY: Dict[str, Callable] = {
    "ResNet9": ResNet9,
    "FixupResNet9": FixupResNet9,
    "ResNet18": ResNet18,
    "FixupResNet18": FixupResNet18,
    "ResNet34": resnets.resnet34,
    "ResNet50": resnets.resnet50,
    "ResNet101": resnets.resnet101,
    "ResNet152": resnets.resnet152,
    "WideResNet50_2": resnets.wide_resnet50_2,
    "WideResNet101_2": resnets.wide_resnet101_2,
    "ResNet101LN": resnets.resnet101ln,
    "FixupResNet50": resnets.fixup_resnet50,
}


def model_names():
    return sorted(_REGISTRY)


def build_model(name: str, **config):
    """Instantiate a model by flag name, dropping config keys the
    target model doesn't take (the reference passes one shared
    model_config dict to every model class, cv_train.py:329-364)."""
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; known: {model_names()}")

    # **kw factory functions declare the dataclass they forward to via
    # __forwards_to__ and the keywords they bind via __bound_fields__;
    # introspect those for the real forwardable field set
    target = getattr(cls, "__forwards_to__", cls)
    if dataclasses.is_dataclass(target):
        fields = {f.name for f in dataclasses.fields(target)}
    else:
        fields = set(inspect.signature(target).parameters)
    fields -= getattr(cls, "__bound_fields__", set())
    fields -= {"name", "parent"}  # flax.linen internals
    kept = {k: v for k, v in config.items() if k in fields}
    return cls(**kept)

"""Fixup (normalization-free) and PreAct residual nets in Flax, NHWC.

Capability parity with the reference's norm-free model family
(reference: CommEfficient/models/fixup_resnet18.py — `FixupResNet18`
at :66-135, `ResNet18` (PreAct) at :138-216, scalar `Mul`/`Add`
modules at :8-22) and its Fixup-ResNet9 variant (reference
models/fixup_resnet9.py imports an external non-vendored `fixup`
package; rebuilt here from the Fixup recipe directly).

Fixup exists precisely because BatchNorm is ill-posed in federated
simulation (SURVEY.md §7.3 #6): tiny non-IID per-client batches make
batch statistics garbage, so these nets replace normalization with
careful init + scalar biases/scales:
  * conv1 of each block: normal(0, sqrt(2/(c_out*k*k)) * L^-0.5)
  * conv2 of each block: zeros; classifier: zeros
  * scalar Add before/after each conv, scalar Mul on the branch
(reference init loop at models/fixup_resnet18.py:85-106).
"""
from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.models.resnet9 import (
    DEFAULT_CHANNELS, StatelessBatchNorm,
)


def _fixup_branch_init(num_layers: int):
    def init(key, shape, dtype=jnp.float32):
        # NHWC kernel shape (kh, kw, c_in, c_out); the reference's
        # formula uses c_out * kh * kw (fixup_resnet18.py:88-91)
        kh, kw, _, c_out = shape
        std = np.sqrt(2.0 / (c_out * kh * kw)) * num_layers ** (-0.5)
        return jax.random.normal(key, shape, dtype) * std
    return init


def _out_fan_init():
    def init(key, shape, dtype=jnp.float32):
        kh, kw, _, c_out = shape
        std = np.sqrt(2.0 / (c_out * kh * kw))
        return jax.random.normal(key, shape, dtype) * std
    return init


class ScalarAdd(nn.Module):
    """Learnable scalar bias (reference Add, fixup_resnet18.py:16-22)."""
    @nn.compact
    def __call__(self, x):
        return x + self.param("bias", nn.initializers.zeros, (1,))


class ScalarMul(nn.Module):
    """Learnable scalar scale (reference Mul, fixup_resnet18.py:8-14)."""
    @nn.compact
    def __call__(self, x):
        return x * self.param("scale", nn.initializers.ones, (1,))


class FixupBlock(nn.Module):
    """(reference FixupBlock, fixup_resnet18.py:24-63)"""
    features: int
    stride: int = 1
    num_layers: int = 8  # total blocks in net, for the L^-0.5 factor

    @nn.compact
    def __call__(self, x):
        needs_proj = self.stride != 1 or x.shape[-1] != self.features
        shortcut = x
        if needs_proj:
            shortcut = nn.Conv(self.features, (1, 1), strides=self.stride,
                               use_bias=False, kernel_init=_out_fan_init(),
                               name="shortcut")(x)
        y = ScalarAdd(name="add1a")(x)
        y = nn.Conv(self.features, (3, 3), strides=self.stride, padding=1,
                    use_bias=False,
                    kernel_init=_fixup_branch_init(self.num_layers),
                    name="conv1")(y)
        y = nn.relu(ScalarAdd(name="add1b")(y))
        y = ScalarAdd(name="add2a")(y)
        y = nn.Conv(self.features, (3, 3), strides=1, padding=1,
                    use_bias=False, kernel_init=nn.initializers.zeros,
                    name="conv2")(y)
        y = ScalarAdd(name="add2b")(ScalarMul(name="mul")(y))
        return nn.relu(y + shortcut)


class PreActBlock(nn.Module):
    """conv->BN->relu twice + shortcut (reference PreActBlock,
    fixup_resnet18.py:138-165; despite the name, the as-shipped
    reference applies norm *after* each conv — we keep its actual
    dataflow)."""
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        y = nn.Conv(self.features, (3, 3), strides=self.stride, padding=1,
                    use_bias=False, name="conv1")(x)
        y = nn.relu(StatelessBatchNorm(name="bn1")(y))
        y = nn.Conv(self.features, (3, 3), strides=1, padding=1,
                    use_bias=False, name="conv2")(y)
        y = nn.relu(StatelessBatchNorm(name="bn2")(y))
        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.features:
            shortcut = nn.Conv(self.features, (1, 1), strides=self.stride,
                               use_bias=False, name="shortcut")(x)
        return y + shortcut


def _dual_pool_head(x):
    """Global avg-pool || max-pool concat (reference
    fixup_resnet18.py:125-131)."""
    x_avg = x.mean(axis=(1, 2))
    x_max = x.max(axis=(1, 2))
    return jnp.concatenate([x_avg, x_max], axis=-1)


class FixupResNet18(nn.Module):
    """(reference FixupResNet18, fixup_resnet18.py:66-135)"""
    num_classes: int = 10
    num_blocks: Tuple[int, ...] = (2, 2, 2, 2)
    widths: Tuple[int, ...] = (64, 128, 256, 256)
    initial_channels: int = 3

    @nn.compact
    def __call__(self, x):
        L = sum(self.num_blocks)
        x = nn.Conv(64, (3, 3), strides=1, padding=1, use_bias=False,
                    kernel_init=_out_fan_init(), name="prep")(x)
        x = nn.relu(x)
        for stage, (w, n) in enumerate(zip(self.widths, self.num_blocks)):
            for i in range(n):
                stride = 2 if (stage > 0 and i == 0) else 1
                x = FixupBlock(w, stride, num_layers=L)(x)
        x = _dual_pool_head(x)
        x = nn.Dense(self.num_classes, kernel_init=nn.initializers.zeros,
                     bias_init=nn.initializers.zeros, name="classifier")(x)
        return x


class ResNet18(nn.Module):
    """PreAct-style ResNet18 with stateless BN (reference ResNet18,
    fixup_resnet18.py:168-216)."""
    num_classes: int = 10
    num_blocks: Tuple[int, ...] = (2, 2, 2, 2)
    widths: Tuple[int, ...] = (64, 128, 256, 256)
    initial_channels: int = 3

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(64, (3, 3), strides=1, padding=1, use_bias=False,
                    name="prep")(x)
        x = nn.relu(x)
        for stage, (w, n) in enumerate(zip(self.widths, self.num_blocks)):
            for i in range(n):
                stride = 2 if (stage > 0 and i == 0) else 1
                x = PreActBlock(w, stride)(x)
        x = _dual_pool_head(x)
        x = nn.Dense(self.num_classes, name="classifier")(x)
        return x


class FixupResNet9(nn.Module):
    """ResNet9 topology with Fixup-style scalar bias/scale and no
    normalization (the capability of reference models/fixup_resnet9.py,
    whose implementation lives in an external non-vendored package)."""
    num_classes: int = 10
    weight: float = 0.125
    initial_channels: int = 3

    @nn.compact
    def __call__(self, x):
        ch = DEFAULT_CHANNELS
        L = 2  # residual blocks

        def conv_block(x, feats, pool=False):
            x = ScalarAdd()(x)
            x = nn.Conv(feats, (3, 3), strides=1, padding=1, use_bias=False,
                        kernel_init=_out_fan_init())(x)
            x = nn.relu(ScalarAdd()(x))
            if pool:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            return x

        def residual(x, feats):
            y = ScalarAdd()(x)
            y = nn.Conv(feats, (3, 3), padding=1, use_bias=False,
                        kernel_init=_fixup_branch_init(L))(y)
            y = nn.relu(ScalarAdd()(y))
            y = ScalarAdd()(y)
            y = nn.Conv(feats, (3, 3), padding=1, use_bias=False,
                        kernel_init=nn.initializers.zeros)(y)
            y = ScalarAdd()(ScalarMul()(y))
            return x + nn.relu(y)

        x = conv_block(x, ch["prep"])
        x = conv_block(x, ch["layer1"], pool=True)
        x = residual(x, ch["layer1"])
        x = conv_block(x, ch["layer2"], pool=True)
        x = conv_block(x, ch["layer3"], pool=True)
        x = residual(x, ch["layer3"])
        x = nn.max_pool(x, (4, 4), strides=(4, 4))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, use_bias=False,
                     kernel_init=nn.initializers.zeros, name="head")(x)
        return x * self.weight

"""Flat-parameter-vector substrate.

The whole framework, like the reference, operates on a single flattened
fp32 vector of all trainable parameters (reference:
CommEfficient/utils.py:232-313 — `_topk`, `get_param_vec`,
`set_param_vec`, `get_grad`, `clip_grad`). Here flattening is
`jax.flatten_util.ravel_pytree` (one fused reshape/concat under jit, no
per-parameter Python loop), and every op is a pure function usable
inside `jit`/`shard_map`.
"""
from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def flatten_params(params) -> Tuple[jax.Array, Callable]:
    """Flatten a parameter pytree to one fp32 vector.

    Returns (vec, unravel) where unravel(vec) rebuilds the pytree
    (replaces reference get_param_vec/set_param_vec,
    utils.py:281-297).
    """
    vec, unravel = ravel_pytree(params)
    return vec.astype(jnp.float32), unravel


# Above this d, masked_topk selects by SAMPLED THRESHOLD instead of
# index top-k. Same motivation and regime as the sketch decoder's
# THRESHOLD_DECODE_MIN_D (ops/sketch.py): ApproxTopK's partial reduce
# shrinks the input only 4x at the reference's k/d ~ 1/130 ratio
# (approx_top_k_reduction_output_size: d=5.25M, k=40402 -> a
# 1.31M-element exact sort PER CLIENT at BASELINE config #3), where
# the threshold route is one ~1M-sample approx_max_k plus an
# elementwise mask. Selected count is k within ~1% sampling noise
# rather than exactly k; every caller (true_topk/local_topk error
# accumulation, topk_down staleness tracking) runs under error
# feedback, which retransmits anything a high threshold briefly
# excludes. Small geometries — all closed-form tests — keep exact-k
# semantics. d-based, not backend-based, so a geometry has one
# semantics everywhere.
TOPK_THRESHOLD_MIN_D = 4 * 1024 * 1024

_TOPK_SAMPLE = 1024 * 1024


def masked_topk(vec: jax.Array, k: int) -> jax.Array:
    """Dense vector equal to `vec` at its ~k largest-magnitude entries
    and zero elsewhere (reference `_topk`, utils.py:232-252).

    Works on 1-D [d] and batched 2-D [b, d] input (top-k taken per
    row), like the reference.

    Below TOPK_THRESHOLD_MIN_D, selection is `jax.lax.approx_max_k`:
    on TPU the native partial-reduce kernel (exact `lax.top_k` sorts
    the full vector — ~9 ms at d=6.6M, k=50k on a v5e) recovering
    ~95% of the true top-k; missed coordinates stay in the error
    accumulator and transmit on later rounds. On CPU — where the
    golden tests run — approx_max_k is exact. Above the gate, the
    sampled-threshold route (constant's docstring) replaces the index
    select entirely.
    """
    d = vec.shape[-1]
    one = (_topk_threshold_1d if d > TOPK_THRESHOLD_MIN_D
           else _topk_exact_1d)

    def _topk_1d(v):
        return one(v, k)

    if vec.ndim == 1:
        return _topk_1d(vec)
    elif vec.ndim == 2:
        return jax.vmap(_topk_1d)(vec)
    raise ValueError(f"masked_topk supports 1-D/2-D input, got {vec.ndim}-D")


def _topk_exact_1d(v: jax.Array, k: int) -> jax.Array:
    _, idx = jax.lax.approx_max_k(v * v, k)
    mask = jnp.zeros_like(v).at[idx].set(1.0)
    return v * mask


def _topk_threshold_1d(v: jax.Array, k: int) -> jax.Array:
    return sampled_threshold_mask(v, k)


def threshold_from_sq_sample(sq_sample: jax.Array, k: int,
                             total: int) -> jax.Array:
    """THE k-th-largest-square threshold estimate from a sample of
    squared magnitudes — one copy of the quantile math (ks clamp,
    approx_max_k, tiny floor) shared by sampled_threshold_mask below
    and the fused Pallas decode (ops/kernels/sketch_pallas), so the
    two routes' selection contracts cannot drift apart.

    sq_sample: [n] squared values sampled ~uniformly from a vector of
    `total` squared values; returns the scalar threshold: a vector
    with fewer than k nonzeros floors the threshold at f32-tiny so
    callers' `sq >= thr` select exactly the nonzeros, not everything."""
    n = sq_sample.shape[0]
    ks = max(1, min(int(round(k * n / total)), n))
    vals, _ = jax.lax.approx_max_k(sq_sample, ks)
    return jnp.maximum(vals[-1], jnp.finfo(jnp.float32).tiny)


def sampled_threshold_mask(v: jax.Array, k: int) -> jax.Array:
    """THE sampled-threshold selection (one algorithm, shared by
    masked_topk's large-d route and CSVec.decode_topk_dense): estimate
    the k-th largest v^2 from a ~_TOPK_SAMPLE strided sample, then
    keep every coordinate at or above it. Coordinates the caller wants
    excluded (e.g. a padding tail) must already be zero — zeros sort
    last, so they dilute the sample and the selection identically and
    the quantile math stays exact.

    TIE CAVEAT: the `sq >= thr` select keeps EVERY coordinate whose
    squared magnitude ties the estimated threshold, so the realized
    count can exceed k by the tie multiplicity on top of the ~1%
    sampling noise. Real gradients have measure-zero ties, but
    structured inputs (quantized values, repeated embeddings, adv
    synthetic tests) can tie arbitrarily many coordinates — a
    degenerate vector with one repeated magnitude selects ALL its
    nonzeros. Error feedback keeps the math correct either way (the
    selection is a superset of intent), but the WIRE cost grows with
    the realized support, which is why local_topk accounting records
    the realized nonzero count next to the analytic k
    (federated/accounting.CommAccountant.realized_nonzeros) — a tie
    blowout shows up there instead of silently under-billing."""
    d = v.shape[0]
    k = min(k, d)
    sq = v * v
    stride = max(1, d // _TOPK_SAMPLE)
    thr = threshold_from_sq_sample(sq[::stride], k, d)
    return jnp.where(sq >= thr, v, 0.0)


def clip_to_l2(vec: jax.Array, clip: float) -> jax.Array:
    """Scale `vec` down to L2 norm `clip` if it exceeds it; identity
    otherwise (reference `clip_grad`, utils.py:305-313). Unlike the
    reference this is branch-free (jnp.where) so it traces under jit.
    """
    norm = jnp.linalg.norm(vec)
    scale = jnp.where(norm > clip, clip / jnp.maximum(norm, 1e-30), 1.0)
    return vec * scale


def clip_table_to_l2(table: jax.Array, l2_est: jax.Array, clip: float) -> jax.Array:
    """Clip a sketch table by an externally-supplied L2 estimate
    (reference clips sketches via CSVec.l2estimate(),
    utils.py:307-309)."""
    scale = jnp.where(l2_est > clip, clip / jnp.maximum(l2_est, 1e-30), 1.0)
    return table * scale


def global_norm_clip(vec: jax.Array, max_norm: float) -> jax.Array:
    """torch.nn.utils.clip_grad_norm_ semantics: multiply by
    max_norm/(norm+1e-6) when norm exceeds max_norm (reference use:
    fed_worker.py:290-292)."""
    norm = jnp.linalg.norm(vec)
    scale = jnp.where(norm > max_norm, max_norm / (norm + 1e-6), 1.0)
    return vec * scale


def dp_noise(key: jax.Array, shape, noise_multiplier: float,
             scale: float = 1.0) -> jax.Array:
    """Gaussian DP noise N(0, noise_multiplier) * scale (reference:
    fed_worker.py:304-309 worker-side — scale=sqrt(num_workers);
    fed_aggregator.py:505-508 server-side — scale=1)."""
    return jax.random.normal(key, shape) * (noise_multiplier * scale)

"""Count-sketch for gradient compression, TPU-native.

Re-designs the capability the reference gets from the external `csvec`
package (CSVec: github.com/nikitaivkin/csh; used at reference
CommEfficient/fed_worker.py:312-320 and fed_aggregator.py:464-467,
584-595): an r x c count-sketch of a length-d vector supporting
linear accumulation, top-k heavy-hitter recovery, and L2 estimation.

TPU-first design decisions:
  * No stored hash index arrays (csvec materializes r*d hash tables on
    the GPU and splits them into `numBlocks` chunks to fit memory).
    Here bucket/sign hashes are *computed on the fly* from the
    coordinate index with a murmur3-style integer mixer — pure uint32
    VPU arithmetic, zero HBM footprint, and `num_blocks` degrades into
    a pure scheduling knob (chunk count for the encode/decode scans)
    that cannot change results.
  * Encode is a blockwise `lax.scan` of scatter-adds; decode-top-k is
    a blockwise `lax.scan` holding a running top-k buffer, so the d
    median-estimates are never materialized at once (SURVEY.md §7.3
    hard part #1: d = O(1e8) must not materialize).
  * Everything is a pure function of (table, static hash params), so
    sketches are linear by construction: psum of worker tables over
    the client mesh axis == the sketch of the summed gradient. That
    linearity is the whole point of FetchSGD, and it is what lets the
    reference's lone NCCL reduce (fed_worker.py:138) become a single
    `lax.psum` here.

The sketch state is just a jnp array [r, c]; this class is a frozen,
hashable bundle of static geometry + hash salts, safe to close over
under jit.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

_M32 = np.uint32(0xFFFFFFFF)


def _mix32(x: jax.Array) -> jax.Array:
    """murmur3 finalizer: a fast, well-distributed uint32->uint32 mixer."""
    x = x ^ (x >> 16)
    x = x * np.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * np.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


@dataclasses.dataclass(frozen=True)
class CSVecHashes:
    """Per-row hash salts, generated deterministically from `seed` so
    that every participant (every client shard, and the server) builds
    the identical sketch geometry — the analogue of csvec seeding its
    hash generation with a fixed manual seed."""
    bucket_salts: Tuple[int, ...]
    sign_salts: Tuple[int, ...]

    @staticmethod
    def make(r: int, seed: int) -> "CSVecHashes":
        rng = np.random.RandomState(seed)
        return CSVecHashes(
            bucket_salts=tuple(int(s) for s in rng.randint(1, 2**31, size=r)),
            sign_salts=tuple(int(s) for s in rng.randint(1, 2**31, size=r)),
        )


@dataclasses.dataclass(frozen=True)
class CSVec:
    """Count-sketch geometry: d-dim vectors into an [r, c] table.

    API parity map with the reference's csvec.CSVec:
      encode(v)                  ~ CSVec(...).accumulateVec(v); .table
      (table arithmetic is just +)~ accumulateTable / zero()
      decode_topk(table, k)      ~ unSketch(k=k)
      l2estimate(table)          ~ l2estimate()
    """
    d: int
    c: int
    r: int
    num_blocks: int = 1
    seed: int = 42

    def __post_init__(self):
        object.__setattr__(self, "hashes", CSVecHashes.make(self.r, self.seed))

    # --- hashing ---------------------------------------------------------
    def hash_indices(self, idx: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Buckets [r, n] (int32 in [0, c)) and signs [r, n] (+-1 f32)
        for an int32 index array [n]."""
        iu = idx.astype(jnp.uint32)
        buckets = []
        signs = []
        for j in range(self.r):
            hb = _mix32(iu ^ np.uint32(self.hashes.bucket_salts[j]))
            hs = _mix32(iu ^ np.uint32(self.hashes.sign_salts[j]))
            buckets.append((hb % np.uint32(self.c)).astype(jnp.int32))
            signs.append(1.0 - 2.0 * (hs & np.uint32(1)).astype(jnp.float32))
        return jnp.stack(buckets), jnp.stack(signs)

    # --- geometry helpers ------------------------------------------------
    @property
    def _chunk(self) -> int:
        return -(-self.d // max(self.num_blocks, 1))

    @property
    def table_shape(self) -> Tuple[int, int]:
        return (self.r, self.c)

    def zeros(self) -> jax.Array:
        return jnp.zeros(self.table_shape, jnp.float32)

    # --- encode ----------------------------------------------------------
    def encode(self, vec: jax.Array) -> jax.Array:
        """Sketch a dense [d] vector into an [r, c] table."""
        chunk = self._chunk
        n_blocks = -(-self.d // chunk)
        row_ids = jnp.repeat(jnp.arange(self.r, dtype=jnp.int32), chunk)

        def body(table, b):
            start = b * chunk
            i = start + jnp.arange(chunk, dtype=jnp.int32)
            valid = (i < self.d).astype(jnp.float32)
            vals = jax.lax.dynamic_slice_in_dim(
                self._padded(vec), start, chunk) * valid
            buckets, signs = self.hash_indices(i)
            contrib = (signs * vals[None, :]).reshape(-1)
            table = table.at[row_ids, buckets.reshape(-1)].add(contrib)
            return table, None

        # init carry derived from `vec` (not a fresh constant) so that
        # under shard_map the carry inherits vec's varying-axes type
        init = jnp.zeros_like(vec, shape=self.table_shape)
        table, _ = jax.lax.scan(
            body, init, jnp.arange(n_blocks, dtype=jnp.int32))
        return table

    def _padded(self, vec: jax.Array) -> jax.Array:
        chunk = self._chunk
        n_blocks = -(-self.d // chunk)
        pad = n_blocks * chunk - self.d
        return jnp.pad(vec, (0, pad)) if pad else vec

    def encode_sparse(self, indices: jax.Array, values: jax.Array) -> jax.Array:
        """Sketch a sparse vector given as (indices [n], values [n]).
        Out-of-range indices (e.g. i >= d padding) are dropped. Used by
        the server's sketched error-feedback step, which re-sketches the
        k-sparse recovered update (reference fed_aggregator.py:593-595)
        — an O(k) scatter instead of an O(d) re-encode."""
        buckets, signs = self.hash_indices(indices.astype(jnp.int32))
        valid = ((indices >= 0) & (indices < self.d)).astype(jnp.float32)
        vals = values * valid
        row_ids = jnp.repeat(
            jnp.arange(self.r, dtype=jnp.int32), indices.shape[0])
        return self.zeros().at[
            row_ids, buckets.reshape(-1)
        ].add((signs * vals[None, :]).reshape(-1))

    # --- decode ----------------------------------------------------------
    def estimate(self, table: jax.Array, idx: jax.Array) -> jax.Array:
        """Median-of-rows unbiased estimates of coordinates `idx` [n]."""
        buckets, signs = self.hash_indices(idx.astype(jnp.int32))
        ests = signs * table[jnp.arange(self.r)[:, None], buckets]  # [r, n]
        return jnp.median(ests, axis=0)

    def decode_topk(self, table: jax.Array, k: int) -> jax.Array:
        """Dense [d] vector holding the k largest-magnitude estimated
        coordinates (reference csvec unSketch(k)). Blockwise scan with
        a running top-k buffer: never materializes all d estimates."""
        sparse_idx, sparse_vals = self.decode_topk_sparse(table, k)
        dense = jnp.zeros(self.d, jnp.float32)
        return dense.at[sparse_idx].set(sparse_vals, mode="drop")

    def decode_topk_sparse(
        self, table: jax.Array, k: int
    ) -> Tuple[jax.Array, jax.Array]:
        """(indices [k], values [k]) of the top-k estimates. Unfilled
        slots carry index d (out of range; dropped by `mode='drop'`
        scatters downstream)."""
        k = min(k, self.d)
        chunk = self._chunk
        n_blocks = -(-self.d // chunk)

        def body(carry, b):
            best_idx, best_vals = carry
            start = b * chunk
            i = start + jnp.arange(chunk, dtype=jnp.int32)
            est = self.estimate(table, i)
            est = jnp.where(i < self.d, est, 0.0)
            cand_idx = jnp.concatenate([best_idx, i])
            cand_vals = jnp.concatenate([best_vals, est])
            _, sel = jax.lax.top_k(cand_vals * cand_vals, k)
            return (cand_idx[sel], cand_vals[sel]), None

        init = (jnp.full_like(table, self.d, dtype=jnp.int32, shape=(k,)),
                jnp.zeros_like(table, shape=(k,)))
        (idx, vals), _ = jax.lax.scan(
            body, init, jnp.arange(n_blocks, dtype=jnp.int32))
        return idx, vals

    # --- norms -----------------------------------------------------------
    def l2estimate(self, table: jax.Array) -> jax.Array:
        """Estimated L2 norm of the sketched vector: median over rows of
        per-row L2 (csvec l2estimate; used for clipping sketches at
        reference utils.py:307-309)."""
        return jnp.sqrt(jnp.median(jnp.sum(table * table, axis=1)))

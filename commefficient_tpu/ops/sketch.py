"""Count-sketch for gradient compression, TPU-native.

Re-designs the capability the reference gets from the external `csvec`
package (CSVec: github.com/nikitaivkin/csh; used at reference
CommEfficient/fed_worker.py:312-320 and fed_aggregator.py:464-467,
584-595): an r x c count-sketch of a length-d vector supporting
linear accumulation, top-k heavy-hitter recovery, and L2 estimation.

TPU-first design. csvec hashes every coordinate independently, which
on an accelerator means r*d-element scatter (encode) and gather
(decode) through HBM — measured at ~600 ms per op for d=6.6M on a
v5e. Both are eliminated by choosing a hash family that vector
hardware can evaluate with contiguous memory ops only (~2-5 ms, i.e.
memory-bound optimal):

  * View the vector as B = ceil(d/c) contiguous chunks of length c.
    Row j's bucket hash is a random cyclic rotation per chunk:
        bucket_j(i) = ((i mod c) + offset[j, i // c]) mod c
    Encode row j = sum over chunks of rotate(sign * chunk): pure
    slices and adds. Decode-estimate inverts the rotations.
  * Signs factor as sign_j(i) = eps_j[i mod c] * delta_j[i // c] with
    eps ([r, c]) and delta ([r, B]) i.i.d. Rademacher drawn once from
    the seed. TPUs multiply floats far faster than they evaluate
    integer hash mixers (int multiplies are emulated), and the eps
    table is 4rc bytes regardless of d.
  * Validity: two coords in the same chunk never collide (same
    rotation — strictly better than the classic family). Coords in
    different chunks b != b' collide with probability exactly 1/c over
    the independent uniform offsets, and their sign product
    eps(p)eps(p')delta(b)delta(b') (or delta(b)delta(b') when p = p')
    has zero mean, so estimates are unbiased with variance
    <= ||v||^2/c per row; median-of-rows and heavy-hitter recovery
    guarantees carry over unchanged.
  * Everything is a pure function of (table, static geometry), so
    sketches are linear by construction: psum of worker tables over
    the client mesh axis == the sketch of the summed gradient. That
    linearity is the whole point of FetchSGD, and it is what lets the
    reference's lone NCCL reduce (fed_worker.py:138) become a single
    `lax.psum` here.
  * `num_blocks` (csvec's GPU-memory workaround) is accepted for API
    parity but cannot change results; chunking here is intrinsic
    (B = ceil(d/c)).

The sketch state is just a jnp array [r, c]; this class is a frozen
bundle of static geometry + sign/offset tables, safe to close over
under jit.

Performance notes (measured on TPU v5e, d=6.6M, c=500k, r=5 — see
PERF.md):
  * The rotation offsets are STATIC (numpy, fixed at construction), so
    encode/decode unroll into `jnp.roll` with compile-time shifts (two
    contiguous slices + concat each, fully fusible) instead of a
    `lax.scan` carrying traced offsets whose `dynamic_slice` of a
    doubled row defeats fusion. Measured: encode 4.3 ms -> 0.7 ms,
    full estimate 17 ms -> 4 ms. The scan path is kept as a fallback
    for very large r * n_chunks where unrolling would bloat compile
    time.
  * Heavy-hitter selection uses `jax.lax.approx_max_k` — the TPU-native
    partial-reduce top-k. On TPU it recovers ~95% (default
    recall_target) of the true top-k; missed coordinates are caught by
    error feedback on later rounds, the regime FetchSGD already
    operates in (sketch estimates are themselves approximate). On CPU
    (the test mesh) approx_max_k is exact, so golden tests see exact
    semantics.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Unroll encode/decode over (row, chunk) pairs with static shifts up to
# this many pairs; beyond it, fall back to a lax.scan over chunks
# (bounded compile time, ~4x slower per element on TPU).
STATIC_UNROLL_LIMIT = 2048

# decode_topk_sparse may materialize the full [n_chunks, c] estimate
# (fast single approx_max_k select) only below this element count.
# The estimate is ~padded-d floats, so 256M elements = 1 GiB f32 (x2
# transient for the squared copy): GPT2-small's D=124M decodes on the
# fast path, while d = O(1e9) — where several d-sized f32 temporaries
# would crowd a 16 GiB HBM — falls back to the blockwise scan that
# keeps live memory at O(c) (SURVEY.md §7.3 hard part #1).
DECODE_MATERIALIZE_LIMIT = 256 * 1024 * 1024

# Above this d, decode_topk_dense selects heavy hitters by SAMPLED
# THRESHOLD instead of index top-k. Motivation (measured via
# _jax.approx_top_k_reduction_output_size): at GPT2-small geometry
# (d=124M, k=952k) the TPU ApproxTopK partial reduce only shrinks the
# input 4x before its exact sort — a 31M-element sort per decode. The
# threshold route estimates the k-th largest |estimate| from a ~1M
# strided sample (a cheap approx_max_k), then selects every coordinate
# >= that threshold with one elementwise mask: no large sort, no
# gather, no scatter. The selected count is k +- sampling noise (~1-2%
# at a 1M sample) rather than exactly k — the FetchSGD regime already
# treats k as a budget on approximate sketch estimates, and error
# feedback re-transmits anything a high threshold briefly excludes.
# Small geometries (all golden tests, the flagship CV bench) keep
# index top-k and its exact-k semantics. The gate is d-based, not
# backend-based, so a given geometry has one semantics everywhere
# (multihost bitwise-equality proofs compare like with like). The
# selection algorithm itself is ops/flat.py's sampled_threshold_mask
# (one shared implementation).
THRESHOLD_DECODE_MIN_D = 32 * 1024 * 1024


@dataclasses.dataclass(frozen=True, eq=False)
class CSVec:
    """Count-sketch geometry: d-dim vectors into an [r, c] table.

    API parity map with the reference's csvec.CSVec:
      encode(v)                  ~ CSVec(...).accumulateVec(v); .table
      (table arithmetic is just +)~ accumulateTable / zero()
      decode_topk(table, k)      ~ unSketch(k=k)
      l2estimate(table)          ~ l2estimate()
    """
    d: int
    c: int
    r: int
    num_blocks: int = 1   # accepted for parity; results are invariant
    seed: int = 42
    # kernel backend for the dense hot-path ops (Config.kernel_backend,
    # ISSUE 6): "xla" keeps every method on the code below — the
    # default program is bit-identical to a build without the field —
    # while "pallas" routes encode / estimate_all / the threshold
    # decode through the fused kernels in ops/kernels/sketch_pallas
    # (interpret-mode off TPU, so CPU tests run the kernel bodies).
    # Geometries past the kernels' VMEM gate (pallas_fits) fall back
    # to the XLA route per method — static per geometry, so a given
    # CSVec takes ONE route everywhere. The hash/gather paths
    # (estimate, encode_sparse) have no kernel: they are the
    # scatter/gather formulation the kernels exist to avoid.
    backend: str = "xla"

    def __post_init__(self):
        rng = np.random.RandomState(self.seed)
        B = self.n_chunks
        object.__setattr__(
            self, "_offsets", rng.randint(0, self.c, size=(self.r, B))
            .astype(np.int32))
        object.__setattr__(
            self, "_eps",
            rng.choice([-1.0, 1.0], size=(self.r, self.c))
            .astype(np.float32))
        object.__setattr__(
            self, "_delta",
            rng.choice([-1.0, 1.0], size=(self.r, B)).astype(np.float32))

    # --- geometry helpers ------------------------------------------------
    @property
    def n_chunks(self) -> int:
        return -(-self.d // self.c)

    @property
    def _static_path(self) -> bool:
        return self.r * self.n_chunks <= STATIC_UNROLL_LIMIT

    def _pallas(self, kind: str) -> bool:
        """Whether `kind` ('encode' | 'estimate') runs on the fused
        Pallas kernel for this sketch (backend field + VMEM gate)."""
        if self.backend != "pallas":
            return False
        from commefficient_tpu.ops.kernels import pallas_fits
        return pallas_fits(self, kind)

    @property
    def table_shape(self) -> Tuple[int, int]:
        return (self.r, self.c)

    def zeros(self) -> jax.Array:
        return jnp.zeros(self.table_shape, jnp.float32)

    def _rotate(self, row: jax.Array, shift) -> jax.Array:
        """out[p] = row[(p - shift) mod c]: two contiguous slices."""
        doubled = jnp.concatenate([row, row], axis=-1)
        return jax.lax.dynamic_slice_in_dim(
            doubled, self.c - shift, self.c, axis=-1)

    def _unrotate(self, row: jax.Array, shift) -> jax.Array:
        """out[p] = row[(p + shift) mod c] (inverse of _rotate)."""
        doubled = jnp.concatenate([row, row], axis=-1)
        return jax.lax.dynamic_slice_in_dim(doubled, shift, self.c, axis=-1)

    def _padded_chunks(self, vec: jax.Array) -> jax.Array:
        B = self.n_chunks
        pad = B * self.c - self.d
        if pad:
            vec = jnp.pad(vec, (0, pad))
        return vec.reshape(B, self.c)

    # --- hashing (for sparse / per-coordinate paths) ---------------------
    def hash_indices(self, idx: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Buckets [r, n] (int32 in [0, c)) and signs [r, n] (+-1 f32)
        for an int32 index array [n]. Out-of-range indices get an
        arbitrary valid bucket (callers mask their values)."""
        safe = jnp.clip(idx, 0, self.d - 1)
        b = (safe // self.c).astype(jnp.int32)             # chunk [n]
        p = (safe % self.c).astype(jnp.int32)              # position [n]
        off = jnp.asarray(self._offsets)[:, b]             # [r, n]
        buckets = (p[None, :] + off) % self.c
        signs = jnp.asarray(self._eps)[:, p] * jnp.asarray(self._delta)[:, b]
        return buckets.astype(jnp.int32), signs

    # --- encode ----------------------------------------------------------
    def encode(self, vec: jax.Array) -> jax.Array:
        """Sketch a dense [d] vector into an [r, c] table: one
        multiply + rotate + add per (row, chunk), all contiguous.

        Static-offset unroll (shifts known at trace time -> `jnp.roll`
        lowers to fusible static slices; see module perf notes); scan
        fallback above STATIC_UNROLL_LIMIT; the fused Pallas kernel
        (one VMEM pass per row, hardware dynamic rotate, compile time
        flat in r * B) replaces BOTH when backend == 'pallas'."""
        if self._pallas("encode"):
            from commefficient_tpu.ops.kernels import pallas_encode
            return pallas_encode(self, vec)
        chunks = self._padded_chunks(vec)                  # [B, c]
        eps = jnp.asarray(self._eps)                       # [r, c]

        if self._static_path:
            rows = []
            for j in range(self.r):
                acc = jnp.zeros_like(vec, shape=(self.c,))
                for b in range(self.n_chunks):
                    acc = acc + (jnp.roll(eps[j] * chunks[b],
                                          int(self._offsets[j, b]))
                                 * float(self._delta[j, b]))
                rows.append(acc)
            return jnp.stack(rows)

        def body(table, xs):
            chunk, off_b, delta_b = xs                     # [c], [r], [r]
            signed = eps * chunk[None, :] * delta_b[:, None]   # [r, c]
            rows = [self._rotate(signed[j], off_b[j]) for j in range(self.r)]
            return table + jnp.stack(rows), None

        init = jnp.zeros_like(vec, shape=self.table_shape)
        table, _ = jax.lax.scan(
            body, init,
            (chunks, jnp.asarray(self._offsets).T,
             jnp.asarray(self._delta).T))
        return table

    def encode_sparse(self, indices: jax.Array, values: jax.Array) -> jax.Array:
        """Sketch a sparse vector given as (indices [n], values [n]).
        Out-of-range indices (e.g. i >= d padding) are dropped. Used by
        the server's sketched error-feedback step, which re-sketches the
        k-sparse recovered update (reference fed_aggregator.py:593-595)
        — an O(k) scatter instead of an O(d) re-encode."""
        buckets, signs = self.hash_indices(indices.astype(jnp.int32))
        valid = ((indices >= 0) & (indices < self.d)).astype(jnp.float32)
        vals = values * valid
        row_ids = jnp.repeat(
            jnp.arange(self.r, dtype=jnp.int32), indices.shape[0])
        return self.zeros().at[
            row_ids, buckets.reshape(-1)
        ].add((signs * vals[None, :]).reshape(-1))

    def encode_k_sparse(self, indices: jax.Array, values: jax.Array,
                        dense: Optional[jax.Array] = None) -> jax.Array:
        """Sketch a k-sparse vector, choosing the faster of the two
        mathematically identical routes (linearity — their equality is
        asserted by tests/test_sketch.py):

          * `encode_sparse`: O(r*k) scatter-add. Cheap everywhere when
            k is small, and on CPU backends at any k.
          * dense `encode(dense)`: O(r*d) contiguous rotations. TPU
            scatter throughput is orders of magnitude below streaming
            bandwidth, so past ~1M scattered elements (GPT2-small's
            server re-sketch: r*k = 4.8M) the dense route wins.

        `dense` is the already-materialized dense form of the sparse
        vector, if the caller has one in hand (the server's
        error-feedback step does); without it the dense route pays one
        extra O(k) scatter to build it.

        BACKEND-DISPATCH CAVEAT: unlike this module's other route
        gates (THRESHOLD_DECODE_MIN_D, DECODE_MATERIALIZE_LIMIT),
        which are d-based so a geometry has ONE semantics everywhere,
        this gate consults `jax.default_backend()` at TRACE time. The
        two routes are mathematically identical by sketch linearity,
        but floating-point summation ORDER differs (scatter-add
        accumulation vs. dense rotation reduction), so at large r*k a
        CPU trace and a TPU trace of the same geometry can produce
        sketch tables differing in final-ulp rounding. Cross-backend
        bitwise-equality comparisons (e.g. a CPU golden against a TPU
        run) must therefore pin the route — pass `dense` explicitly or
        compare within one backend; same-backend runs (all tests, all
        multihost bit-equality proofs) are unaffected because the
        dispatch is deterministic per backend."""
        use_dense = (self.r * int(indices.shape[0]) > 1_000_000
                     and jax.default_backend() != "cpu")
        if not use_dense:
            return self.encode_sparse(indices, values)
        if dense is None:
            dense = jnp.zeros(self.d, jnp.float32).at[indices].set(
                values, mode="drop")
        return self.encode(dense)

    # --- decode ----------------------------------------------------------
    def estimate(self, table: jax.Array, idx: jax.Array) -> jax.Array:
        """Median-of-rows unbiased estimates of coordinates `idx` [n]."""
        buckets, signs = self.hash_indices(idx.astype(jnp.int32))
        ests = signs * table[jnp.arange(self.r)[:, None], buckets]  # [r, n]
        return jnp.median(ests, axis=0)

    def estimate_all(self, table: jax.Array) -> jax.Array:
        """[B, c] median-of-rows estimates for every coordinate
        (flattened [: d] is the full estimate vector): r inverse
        rotations + sign correction per chunk, no gathers. Static
        unroll when small enough (module perf notes); one fused
        rotate+median kernel pass when backend == 'pallas' (the
        Pallas route additionally zeroes the padding tail — a
        superset of this method's contract that every caller
        re-zeroes anyway)."""
        if self._pallas("estimate"):
            from commefficient_tpu.ops.kernels import pallas_estimate_all
            return pallas_estimate_all(self, table)
        eps = jnp.asarray(self._eps)

        if self._static_path:
            delta = jnp.asarray(self._delta)
            ests = []
            for b in range(self.n_chunks):
                rows = jnp.stack(
                    [jnp.roll(table[j], -int(self._offsets[j, b]))
                     for j in range(self.r)])
                ests.append(jnp.median(
                    rows * eps * delta[:, b][:, None], axis=0))
            return jnp.stack(ests)                            # [B, c]

        def body(_, xs):
            off_b, delta_b = xs
            rows = [self._unrotate(table[j], off_b[j])
                    for j in range(self.r)]
            ests = jnp.stack(rows) * eps * delta_b[:, None]     # [r, c]
            return None, jnp.median(ests, axis=0)

        _, est = jax.lax.scan(
            body, None,
            (jnp.asarray(self._offsets).T, jnp.asarray(self._delta).T))
        return est                                            # [B, c]

    def _flat_estimates(self, table: jax.Array) -> jax.Array:
        """Materialized [padded] estimate vector with the padding tail
        (coords >= d) zeroed — the shared prologue of both
        materialize-path decode routes."""
        flat = self.estimate_all(table).reshape(-1)
        if self.n_chunks * self.c != self.d:
            iota = jnp.arange(flat.shape[0], dtype=jnp.int32)
            flat = jnp.where(iota < self.d, flat, 0.0)
        return flat

    def decode_topk(self, table: jax.Array, k: int) -> jax.Array:
        """Dense [d] vector holding the k largest-magnitude estimated
        coordinates (reference csvec unSketch(k))."""
        sparse_idx, sparse_vals = self.decode_topk_sparse(table, k)
        dense = jnp.zeros(self.d, jnp.float32)
        return dense.at[sparse_idx].set(sparse_vals, mode="drop")

    @property
    def _threshold_decode(self) -> bool:
        """Whether decode_topk_dense uses the sampled-threshold route
        (see THRESHOLD_DECODE_MIN_D). Requires the materialized-
        estimate path; beyond DECODE_MATERIALIZE_LIMIT the blockwise
        sparse decode stays the only option."""
        padded = self.n_chunks * self.c
        return (self.d > THRESHOLD_DECODE_MIN_D
                and padded <= DECODE_MATERIALIZE_LIMIT)

    def decode_topk_dense(self, table: jax.Array, k: int) -> jax.Array:
        """decode_topk for callers that only need the DENSE update
        (the server's error-feedback step): at large d takes the
        sampled-threshold route — one approx_max_k over a ~1M sample
        plus one elementwise mask, instead of an index top-k whose TPU
        partial-reduce sort grows with k*d — otherwise identical to
        decode_topk. With backend == 'pallas' the threshold route is
        the FUSED estimate+select kernel pair: the full [D] estimate
        vector is never materialized in HBM (estimates recompute in
        VMEM for the sample and the mask pass; kernels module
        docstring covers the sample-phase difference the selection
        tolerance already absorbs)."""
        if not self._threshold_decode:
            return self.decode_topk(table, k)
        if self._pallas("estimate"):
            from commefficient_tpu.ops.kernels import (
                pallas_threshold_decode,
            )
            return pallas_threshold_decode(self, table, min(k, self.d))

        from commefficient_tpu.ops.flat import sampled_threshold_mask
        # the padding tail of _flat_estimates is already zeroed, which
        # is exactly the contract sampled_threshold_mask needs
        flat = self._flat_estimates(table)
        return sampled_threshold_mask(flat, min(k, self.d))[: self.d]

    def decode_topk_sparse(
        self, table: jax.Array, k: int
    ) -> Tuple[jax.Array, jax.Array]:
        """(indices [k], values [k]) of the top-k estimates. Unfilled
        slots carry index d (out of range; dropped by `mode='drop'`
        scatters downstream)."""
        k = min(k, self.d)
        kc = min(k, self.c)
        eps = jnp.asarray(self._eps)

        if self._static_path and self.n_chunks * self.c <= DECODE_MATERIALIZE_LIMIT:
            # materialize the full [B, c] estimate (28 MB at the
            # flagship geometry) and select once with the TPU-native
            # approx_max_k partial reduce (module perf notes).
            flat = self._flat_estimates(table)
            _, idx = jax.lax.approx_max_k(flat * flat, k)
            vals = flat[idx]
            idx = jnp.where(vals == 0.0, self.d, idx)
            return idx.astype(jnp.int32), vals

        # blockwise fallback: per chunk keep the top-min(k, c)
        # candidates (a chunk holds at most c coords, so this loses
        # nothing), then one final top-k over the B * kc survivors.
        # Never materializes all d estimates at once (SURVEY.md §7.3
        # hard part #1: d = O(1e8) must stay bounded).
        def body(_, xs):
            off_b, delta_b, b = xs
            rows = [self._unrotate(table[j], off_b[j])
                    for j in range(self.r)]
            est = jnp.median(jnp.stack(rows) * eps * delta_b[:, None],
                             axis=0)                          # [c]
            i_global = b * self.c + jnp.arange(self.c, dtype=jnp.int32)
            est = jnp.where(i_global < self.d, est, 0.0)
            _, sel = jax.lax.approx_max_k(est * est, kc)
            return None, (i_global[sel], est[sel])

        _, (cand_idx, cand_vals) = jax.lax.scan(
            body, None,
            (jnp.asarray(self._offsets).T, jnp.asarray(self._delta).T,
             jnp.arange(self.n_chunks, dtype=jnp.int32)))
        cand_idx = cand_idx.reshape(-1)                       # [B * kc]
        cand_vals = cand_vals.reshape(-1)
        _, sel = jax.lax.approx_max_k(cand_vals * cand_vals, k)
        idx, vals = cand_idx[sel], cand_vals[sel]
        # slots holding a zero estimate are "unfilled": report index d
        # so downstream drop-mode scatters ignore them
        idx = jnp.where(vals == 0.0, self.d, idx)
        return idx.astype(jnp.int32), vals

    # --- norms -----------------------------------------------------------
    def l2estimate(self, table: jax.Array) -> jax.Array:
        """Estimated L2 norm of the sketched vector: median over rows of
        per-row L2 (csvec l2estimate; used for clipping sketches at
        reference utils.py:307-309)."""
        return jnp.sqrt(jnp.median(jnp.sum(table * table, axis=1)))

"""Quantized sketch-table wire transport (--sketch_table_dtype).

FetchSGD's error-feedback argument (PAPER.md) extends directly from
sketch compression noise to quantization noise: the server's virtual
error accumulator keeps whatever update mass the decode did not
transmit, so rounding the [r, c] client-sum table to bf16 or int8 on
the wire perturbs WHICH mass transmits this round, not whether it
eventually does. Telemetry's `estimate_residual` metric is the gauge:
if quantization makes the channel fall behind the gradient, the
residual fraction rises (telemetry/metrics.py).

Placement: the round engine applies `wire_roundtrip` to each mesh
shard's locally-summed sketch table immediately before the
`lax.psum` (federated/round.py shard_train) — modeling each
client-group's upload being quantized at the sender and dequantized
at the server before aggregation/decode. The psum itself then moves
dequantized f32 (in the single-program SPMD simulation the psum IS
the wire stand-in); the accountant bills the bytes the QUANTIZED
table would occupy (`wire_table_bytes`, Config.upload_bytes), which
is the quantity the ISSUE-6 accounting satellite corrects.

Determinism: quantization is round-to-nearest-even (jnp.round), no
stochastic rounding — a resumed run replays identical tables, which
the crash->resume bit-exactness contract requires. The f32 "wire
dtype" is the identity (the function returns its argument
UNTOUCHED), so the default config's program is bit-identical to a
build without this module.

Pure elementwise jnp by design: XLA already fuses a cast or a
scale/round/clip chain into the surrounding encode/psum — a Pallas
kernel would add launch overhead for zero fusion win, so the kernel
budget goes to the rotation/median ops (sketch_pallas) instead.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# wire dtype -> bytes per table element
TABLE_DTYPES = {"f32": 4, "bf16": 2, "int8": 1}

# int8 symmetric range: +-127 (the -128 code is unused so the scale
# is symmetric and dequantization is a single multiply)
_INT8_MAX = 127.0


def table_elem_bytes(dtype: str) -> int:
    """Bytes per sketch-table element at wire dtype `dtype`."""
    return TABLE_DTYPES[dtype]


def quantize_table(table: jax.Array, dtype: str):
    """Quantize an [r, c] f32 sketch table for the wire.

    Returns (wire_values, scales) — `scales` is None for f32/bf16 and
    the per-row [r, 1] f32 dequantization scale for int8 (symmetric
    per-row absmax / 127; an all-zero row gets scale 1 so dequantize
    is exact zeros).
    """
    if dtype == "f32":
        return table, None
    if dtype == "bf16":
        return table.astype(jnp.bfloat16), None
    if dtype == "int8":
        absmax = jnp.max(jnp.abs(table), axis=1, keepdims=True)
        scale = jnp.where(absmax > 0, absmax / _INT8_MAX, 1.0)
        q = jnp.clip(jnp.round(table / scale), -_INT8_MAX, _INT8_MAX)
        return q.astype(jnp.int8), scale
    raise ValueError(f"unknown sketch table dtype {dtype!r} "
                     f"(choices: {sorted(TABLE_DTYPES)})")


def dequantize_table(wire, scale) -> jax.Array:
    """Inverse of quantize_table back to f32 (exact for f32 input;
    the bf16/int8 round-trips carry the rounding the error feedback
    absorbs)."""
    out = wire.astype(jnp.float32)
    if scale is not None:
        out = out * scale
    return out


def wire_roundtrip(table: jax.Array, dtype: str) -> jax.Array:
    """Quantize + dequantize: the f32 table the server actually sees
    after a `dtype` wire. Identity (the same array object) for f32."""
    if dtype == "f32":
        return table
    return dequantize_table(*quantize_table(table, dtype))


def wire_table_bytes(num_rows: int, num_cols: int, dtype: str) -> int:
    """Bytes one [r, c] sketch table occupies on a `dtype` wire:
    r * c elements at the wire element size, plus the r f32 per-row
    dequantization scales int8 must ship alongside."""
    n = num_rows * num_cols * table_elem_bytes(dtype)
    if dtype == "int8":
        n += 4 * num_rows
    return n

"""Fused Pallas TPU kernels for the rotation-hash count-sketch.

The XLA path (ops/sketch.py) runs the sketch as r * B separate
rotate-multiply-add stages: below STATIC_UNROLL_LIMIT it unrolls them
into `jnp.roll` calls XLA fuses well; above it, a `lax.scan` whose
traced-offset `dynamic_slice` defeats fusion (~4x slower per element
— the PERF.md scan-fallback cost). These kernels replace both with
ONE `pallas_call` per operation, the hot path of PERF.md's remaining
sketch overhead:

  * `pallas_encode` — grid (r, B): row j's accumulator lives in VMEM
    across all B chunk steps; each step is multiply (eps row * chunk
    * delta scalar) + one hardware dynamic rotate (`pltpu.roll`, the
    TPU lane-rotate the XLA scan path cannot reach with traced
    shifts) + add. One pass over the vector per row, no HBM
    round-trips between chunks, compile time flat in r * B.
  * `pallas_estimate_all` — grid (B, r): the r un-rotated signed rows
    of one chunk collect in VMEM scratch; the last row step computes
    the median in-register (a compare-exchange sorting network over
    the r rows — branch-free min/max, exactly `jnp.median`'s
    sort-then-middle semantics for finite values) and writes the
    chunk's [c] estimates once. The [r, c] rotated intermediate the
    XLA path materializes per chunk never exists.
  * `pallas_threshold_decode` — the fused estimate+threshold
    selection for the large-d decode route (THRESHOLD_DECODE_MIN_D):
    pass 1 re-derives chunk estimates in VMEM and emits only a
    strided ~1M-element sample; the k-th-largest-square threshold
    comes from one cheap `approx_max_k` over that sample; pass 2
    re-derives the estimates again and writes the thresholded
    k-sparse update directly. The full [D] estimate vector is never
    materialized in HBM — estimates are recomputed (cheap: r rotates
    + multiplies per element) instead of stored, trading ~2x VMEM
    compute for d-sized HBM traffic, the same trade flash attention
    makes with attention scores.

Sampling note: the XLA route samples the flat estimate at one global
stride; the fused route samples each chunk at the same stride
truncated to `c // stride` positions (a ragged tail cannot leave a
static kernel). Both are ~1M-point estimators of the same k-th
largest square, so selection counts agree to the documented ~1%
sampling noise (tests/test_kernels.py bounds it); exact-k small
geometries never reach this route.

Interpret mode: every `pallas_call` here takes `interpret=True` off
TPU (trace-time backend consult, same caveat class as
`CSVec.encode_k_sparse`), so the tier-1 CPU suite runs the identical
kernel bodies — the ISSUE-6 testing contract.

VMEM sizing: per-step residency is 3 rows of c f32 for encode and
(r + 3) rows for the estimate/decode kernels (the scratch holds all
r rotated rows of a chunk). `pallas_fits` gates each kernel on a
conservative VMEM budget; an oversized geometry silently keeps the
XLA route for THAT method — same route-gate discipline as
DECODE_MATERIALIZE_LIMIT, static per geometry. At the flagship
5 x 500k table the estimate/decode kernels sit at the 16 MiB edge,
so the shipped budget keeps them on XLA there until the kernels are
re-tiled on real hardware (PERF.md "Kernel backends" records this as
the open TPU-tuning item).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Conservative per-kernel VMEM budget (bytes). TPU cores expose
# ~16 MiB of VMEM; leave headroom for Pallas' pipelining buffers.
PALLAS_VMEM_BUDGET = 14 * 1024 * 1024

# Strided-sample size target for the fused threshold decode — same
# ~1M-point quantile estimator as ops/flat._TOPK_SAMPLE.
_SAMPLE_TARGET = 1024 * 1024


def _interpret() -> bool:
    """Trace-time backend consult: compiled Mosaic on TPU, the Pallas
    interpreter (plain jax ops, identical math) everywhere else."""
    return jax.default_backend() != "tpu"


def pallas_fits(sk, kind: str) -> bool:
    """Whether `kind` ('encode' | 'estimate') fits the VMEM budget at
    this geometry. Static per geometry — a given CSVec takes one route
    everywhere, so multihost bit-equality proofs compare like with
    like."""
    rows = 3 if kind == "encode" else sk.r + 3
    return rows * sk.c * 4 <= PALLAS_VMEM_BUDGET


# ---------------------------------------------------------------------------
# shared in-kernel helpers


def _median_rows(rows):
    """Median over a static list of equal-shape arrays via a
    compare-exchange (bubble) sorting network: branch-free
    jnp.minimum/maximum only, so it lowers on the VPU and in the
    interpreter alike. Matches jnp.median for finite inputs (middle
    element for odd r, mean of the two middles for even)."""
    rows = list(rows)
    r = len(rows)
    for i in range(r):
        for j in range(r - 1 - i):
            lo = jnp.minimum(rows[j], rows[j + 1])
            hi = jnp.maximum(rows[j], rows[j + 1])
            rows[j], rows[j + 1] = lo, hi
    if r % 2:
        return rows[r // 2]
    return 0.5 * (rows[r // 2 - 1] + rows[r // 2])


def _chunk_estimate_rows(b, j, off_ref, delta_ref, table_ref, eps_ref,
                         rows_scr, *, c: int):
    """One (b, j) step of the estimate-family kernels: un-rotate row j
    of the table for chunk b (out[p] = table[j, (p + off) % c], i.e. a
    left-rotate by off — implemented as a right-rotate by c - off so
    the traced shift stays non-negative), apply the factored signs,
    and park the row in VMEM scratch. `b`/`j` are the grid ids, read
    once at the kernel top (program_id is unavailable inside pl.when
    bodies under the interpreter — same hoisting as ops/attention).
    The % c canonicalizes the off == 0 boundary (c - 0 == c): the
    interpreter's jnp.roll is modular but Mosaic's dynamic_rotate is
    not guaranteed to be at shift == axis size."""
    shift = (c - off_ref[j, b]) % c
    unrot = pltpu.roll(table_ref[...], shift, axis=1)
    rows_scr[j, :] = unrot[0] * eps_ref[0] * delta_ref[j, b]


def _masked_est(b, rows_scr, *, r: int, c: int, d: int):
    """Median over the collected scratch rows with the padding tail
    (global index >= d) zeroed — the final chunk's contract, shared by
    all three estimate-family kernels."""
    est = _median_rows([rows_scr[jj, :] for jj in range(r)])  # [c]
    gidx = b * c + jax.lax.broadcasted_iota(jnp.int32, (1, c), 1)
    return jnp.where(gidx < d, est[None, :], 0.0)             # [1, c]


# ---------------------------------------------------------------------------
# fused encode


def _encode_kernel(off_ref, delta_ref, chunk_ref, eps_ref, out_ref,
                   *, c: int):
    """Grid (r, B), chunks innermost: row j's [c] accumulator stays
    resident in VMEM across every chunk step — the 'one VMEM pass'
    of the ISSUE-6 tentpole. Each step: sign-multiply, one hardware
    dynamic rotate, add."""
    b = pl.program_id(1)

    @pl.when(b == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    j = pl.program_id(0)
    signed = eps_ref[...] * chunk_ref[...] * delta_ref[j, b]
    out_ref[...] += pltpu.roll(signed, off_ref[j, b], axis=1)


def pallas_encode(sk, vec: jax.Array) -> jax.Array:
    """Sketch a dense [d] vector into the [r, c] table with the fused
    accumulate kernel. Bit-for-bit the same sum ORDER as the XLA
    static path (chunks accumulate in ascending order per row), so
    equivalence tests can demand tight tolerances."""
    chunks = sk._padded_chunks(vec.astype(jnp.float32))       # [B, c]
    B = sk.n_chunks
    kernel = functools.partial(_encode_kernel, c=sk.c)
    return pl.pallas_call(
        kernel,
        grid=(sk.r, B),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),            # offsets
            pl.BlockSpec(memory_space=pltpu.SMEM),            # delta
            pl.BlockSpec((1, sk.c), lambda j, b: (b, 0)),     # chunk
            pl.BlockSpec((1, sk.c), lambda j, b: (j, 0)),     # eps row
        ],
        out_specs=pl.BlockSpec((1, sk.c), lambda j, b: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((sk.r, sk.c), jnp.float32),
        interpret=_interpret(),
    )(jnp.asarray(sk._offsets), jnp.asarray(sk._delta),
      chunks, jnp.asarray(sk._eps))


# ---------------------------------------------------------------------------
# fused estimate-all


def _estimate_kernel(off_ref, delta_ref, table_ref, eps_ref, out_ref,
                     rows_scr, *, r: int, c: int, d: int):
    """Grid (B, r), rows innermost: collect the chunk's r un-rotated
    signed rows in scratch, emit the median once at the last row."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    _chunk_estimate_rows(b, j, off_ref, delta_ref, table_ref, eps_ref,
                         rows_scr, c=c)

    @pl.when(j == r - 1)
    def _emit():
        out_ref[...] = _masked_est(b, rows_scr, r=r, c=c, d=d)


def pallas_estimate_all(sk, table: jax.Array) -> jax.Array:
    """[B, c] median-of-rows estimates (the padding tail zeroed — a
    superset of the XLA estimate_all contract, whose callers zero the
    tail themselves; zeros-for-zeros either way)."""
    B = sk.n_chunks
    kernel = functools.partial(_estimate_kernel, r=sk.r, c=sk.c, d=sk.d)
    return pl.pallas_call(
        kernel,
        grid=(B, sk.r),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),            # offsets
            pl.BlockSpec(memory_space=pltpu.SMEM),            # delta
            pl.BlockSpec((1, sk.c), lambda b, j: (j, 0)),     # table row
            pl.BlockSpec((1, sk.c), lambda b, j: (j, 0)),     # eps row
        ],
        out_specs=pl.BlockSpec((1, sk.c), lambda b, j: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((B, sk.c), jnp.float32),
        scratch_shapes=[pltpu.VMEM((sk.r, sk.c), jnp.float32)],
        interpret=_interpret(),
    )(jnp.asarray(sk._offsets), jnp.asarray(sk._delta),
      table.astype(jnp.float32), jnp.asarray(sk._eps))


# ---------------------------------------------------------------------------
# fused estimate + threshold selection (large-d decode)


def _sample_kernel(off_ref, delta_ref, table_ref, eps_ref, samp_ref,
                   rows_scr, *, r: int, c: int, d: int, stride: int,
                   ns: int):
    """Pass 1: per chunk, emit estimates at positions 0, stride, ...,
    (ns-1)*stride — the strided quantile sample — without writing the
    estimates themselves anywhere."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    _chunk_estimate_rows(b, j, off_ref, delta_ref, table_ref, eps_ref,
                         rows_scr, c=c)

    @pl.when(j == r - 1)
    def _emit():
        est = _masked_est(b, rows_scr, r=r, c=c, d=d)         # [1, c]
        strided = est[:, : ns * stride].reshape((ns, stride))[:, :1]
        samp_ref[...] = strided.reshape((1, ns))


def _mask_kernel(off_ref, delta_ref, thr_ref, table_ref, eps_ref,
                 out_ref, rows_scr, *, r: int, c: int, d: int):
    """Pass 2: re-derive the chunk estimates and write the thresholded
    selection (>= keeps threshold ties, matching
    ops/flat.sampled_threshold_mask and its documented tie caveat)."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    _chunk_estimate_rows(b, j, off_ref, delta_ref, table_ref, eps_ref,
                         rows_scr, c=c)

    @pl.when(j == r - 1)
    def _emit():
        est = _masked_est(b, rows_scr, r=r, c=c, d=d)
        out_ref[...] = jnp.where(est * est >= thr_ref[0], est, 0.0)


def threshold_sample_geometry(sk) -> Tuple[int, int]:
    """(stride, per-chunk sample count) for the fused decode's
    quantile sample — the per-chunk restriction of the XLA route's
    global stride (module docstring 'Sampling note'). The stride is
    clamped to c so ns * stride <= c always holds (a chunk narrower
    than the global stride still contributes its position-0 element;
    without the clamp the sample kernel's reshape would receive c !=
    ns * stride elements and fail at trace time)."""
    padded = sk.n_chunks * sk.c
    stride = min(max(1, padded // _SAMPLE_TARGET), sk.c)
    return stride, sk.c // stride


def pallas_threshold_decode(sk, table: jax.Array, k: int) -> jax.Array:
    """Dense [d] k-sparse update: estimates >= the sampled k-th
    largest magnitude, computed without materializing the full [D]
    estimate (two fused estimate passes; module docstring)."""
    B = sk.n_chunks
    stride, ns = threshold_sample_geometry(sk)
    common = dict(r=sk.r, c=sk.c, d=sk.d)
    offsets = jnp.asarray(sk._offsets)
    delta = jnp.asarray(sk._delta)
    eps = jnp.asarray(sk._eps)
    table = table.astype(jnp.float32)

    sample = pl.pallas_call(
        functools.partial(_sample_kernel, stride=stride, ns=ns,
                          **common),
        grid=(B, sk.r),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, sk.c), lambda b, j: (j, 0)),
            pl.BlockSpec((1, sk.c), lambda b, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, ns), lambda b, j: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((B, ns), jnp.float32),
        scratch_shapes=[pltpu.VMEM((sk.r, sk.c), jnp.float32)],
        interpret=_interpret(),
    )(offsets, delta, table, eps)

    # threshold from the sample: THE shared quantile math
    # (ops/flat.threshold_from_sq_sample — one copy for both routes),
    # with the sample drawn per chunk
    from commefficient_tpu.ops.flat import threshold_from_sq_sample
    sq = (sample * sample).reshape(-1)
    thr = threshold_from_sq_sample(sq, k, B * sk.c)

    masked = pl.pallas_call(
        functools.partial(_mask_kernel, **common),
        grid=(B, sk.r),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),            # thr
            pl.BlockSpec((1, sk.c), lambda b, j: (j, 0)),
            pl.BlockSpec((1, sk.c), lambda b, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, sk.c), lambda b, j: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((B, sk.c), jnp.float32),
        scratch_shapes=[pltpu.VMEM((sk.r, sk.c), jnp.float32)],
        interpret=_interpret(),
    )(offsets, delta, thr.reshape(1), table, eps)
    return masked.reshape(-1)[: sk.d]

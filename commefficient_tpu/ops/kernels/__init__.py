"""Pallas TPU kernel backend for the compression hot path (ISSUE 6).

The package holds the hand-written kernels behind
`Config.kernel_backend = "pallas"` plus the quantized sketch-table
wire transport behind `--sketch_table_dtype`:

  * `sketch_pallas` — fused count-sketch accumulate (hash + signed
    rotate-add across all r rows in one VMEM pass), fused
    estimate-all, and the fused estimate+threshold selection that
    produces the k-sparse server update without materializing the
    full [D] estimate vector in HBM.
  * `quant` — bf16/int8 sketch-table wire round-trip (quantize the
    shard's client-sum table, dequantize before the psum/decode) and
    the wire-byte math the accountant bills.

Backend selection is STATIC config: `ops/sketch.CSVec` dispatches per
method on its `backend` field, so a given Config traces exactly the
same number of programs either way and the `xla` default never
imports a kernel. Every kernel also runs under
`pallas_call(interpret=True)` — the automatic non-TPU fallback — so
the tier-1 CPU suite executes the identical kernel bodies the TPU
compiles (tests/test_kernels.py, `pallas` marker).
"""
from commefficient_tpu.ops.kernels.quant import (  # noqa: F401
    TABLE_DTYPES, table_elem_bytes, wire_roundtrip,
)
from commefficient_tpu.ops.kernels.sketch_pallas import (  # noqa: F401
    pallas_encode, pallas_estimate_all, pallas_fits,
    pallas_threshold_decode,
)

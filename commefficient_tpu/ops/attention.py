"""Causal attention for long sequences: Pallas flash kernel + tiled VJP.

The reference's GPT2 path materializes the full [B, H, L, L] score
matrix inside pytorch_transformers (and our baseline einsum path does
the same — models/gpt2.py SelfAttention), which is fine at PersonaChat
lengths but quadratic-memory at long context. This module provides the
long-context path, TPU-first:

  * forward: a hand-written Pallas kernel (`_flash_fwd_kernel`) — grid
    (batch*head, q-block, k-block) with the online-softmax state
    (running max, denominator, accumulator) carried across k-block
    grid steps in VMEM scratch, so per-program VMEM holds one q block
    and one k/v block (O(block * Dh)), never a full [L, Dh] row or an
    [L, L] score tile. Blocks strictly above the causal diagonal skip
    their compute via `pl.when` (their DMAs still stream — the cost of
    the dense-grid schedule, bounded at 2x bandwidth).
  * backward: flash-style recomputation from the saved output and
    per-row logsumexp, tiled as a `lax.scan` over k-blocks so the
    backward also never materializes [L, L].
  * `flash_attention` wraps both in a `jax.custom_vjp`, padding any
    sequence length up to a block multiple internally (causality keeps
    tail padding invisible to real queries; pad rows of the saved
    logsumexp are poisoned to +big so the backward's recomputed
    probabilities vanish there). On non-TPU backends (the CPU test
    mesh) the forward runs the same online-softmax math as a scan
    (`_flash_fwd_xla`); the Pallas kernel itself is covered by
    interpret-mode tests (tests/test_attention.py).

The online-softmax block fold is shared (`online_softmax_fold`)
between the XLA forward and `parallel/ring.py`'s ring attention — one
copy of the numerically delicate rescaling.

Shapes: q, k, v [B, H, L, Dh], any L. Returns [B, H, L, Dh].
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 128
NEG_INF = -1e30
# pad rows of the saved logsumexp carry this so exp(s - lse) == 0
LSE_PAD = 1e30


def _resolve_scale(sm_scale: Optional[float], dh: int) -> float:
    return sm_scale if sm_scale is not None else 1.0 / math.sqrt(dh)


def _pad_len(L: int, block: int) -> int:
    return -(-L // block) * block


def _pad_seq(x, Lp):
    pad = Lp - x.shape[2]
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


# ---------------- shared online-softmax fold ----------------------------

def online_softmax_fold(state, qs, kt, vt, q_pos, k_pos):
    """One flash block fold: fold keys `kt`/values `vt` (global
    positions `k_pos`) into the running (m, l, acc) softmax state of
    queries `qs` (already scaled; global positions `q_pos`). Shapes:
    qs [..., Lq, Dh], kt/vt [..., Lk, Dh], state m/l [..., Lq],
    acc [..., Lq, Dh]. Causal: k > q masked."""
    m, l, acc = state
    s = jnp.einsum("...qd,...kd->...qk", qs, kt.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    rescale = jnp.exp(m - m_new)
    l = l * rescale + p.sum(axis=-1)
    acc = acc * rescale[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p, vt.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l, acc


# ---------------- Pallas forward kernel ---------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      m_scr, l_scr, acc_scr, *,
                      sm_scale: float, block_q: int, block_k: int):
    """Grid (B*H, n_q, n_k), k innermost: scratch carries the online
    state across k steps of one q block. Compute is skipped above the
    causal diagonal."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # blocks strictly above the diagonal contribute nothing
    @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
    def _fold():
        q = q_ref[0].astype(jnp.float32) * sm_scale     # [bq, Dh]
        k = k_ref[0].astype(jnp.float32)                # [bk, Dh]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bq, bk]
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m = m_scr[:, 0]                                  # [bq]
        l = l_scr[:, 0]
        m_new = jnp.maximum(m, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        rescale = jnp.exp(m - m_new)
        l_new = l * rescale + p.sum(axis=1)
        acc_scr[:] = acc_scr[:] * rescale[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(kj == n_k - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:, 0] + jnp.log(l_safe)).astype(jnp.float32)


def _flash_fwd_pallas(q, k, v, sm_scale, block_q, block_k,
                      interpret=False):
    B, H, L, Dh = q.shape
    assert L % block_q == 0 and L % block_k == 0
    qf = q.reshape(B * H, L, Dh)
    kf = k.reshape(B * H, L, Dh)
    vf = v.reshape(B * H, L, Dh)

    kernel = functools.partial(_flash_fwd_kernel, sm_scale=sm_scale,
                               block_q=block_q, block_k=block_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=(B * H, L // block_q, L // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, Dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, Dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, Dh), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, Dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, L, Dh), q.dtype),
            jax.ShapeDtypeStruct((B * H, L), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # denominator
            pltpu.VMEM((block_q, Dh), jnp.float32),    # accumulator
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return o.reshape(B, H, L, Dh), lse.reshape(B, H, L)


# ---------------- XLA forward (same math, scan-tiled) -------------------

def _flash_fwd_xla(q, k, v, sm_scale, block_k) -> Tuple[jax.Array, jax.Array]:
    """Online-softmax forward as a lax.scan over k blocks — identical
    semantics to the kernel, runs on any backend, O(L * block) live."""
    B, H, L, Dh = q.shape
    qs = q.astype(jnp.float32) * sm_scale
    n_blocks = L // block_k
    kb = k.reshape(B, H, n_blocks, block_k, Dh)
    vb = v.reshape(B, H, n_blocks, block_k, Dh)
    q_pos = jnp.arange(L)

    def body(carry, xs):
        kj, vj, j = xs
        k_pos = j * block_k + jnp.arange(block_k)
        return online_softmax_fold(carry, qs, kj, vj, q_pos, k_pos), None

    m0 = jnp.full((B, H, L), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, L), jnp.float32)
    acc0 = jnp.zeros((B, H, L, Dh), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, acc0),
        (kb.transpose(2, 0, 1, 3, 4), vb.transpose(2, 0, 1, 3, 4),
         jnp.arange(n_blocks)))
    l_safe = jnp.maximum(l, 1e-30)
    o = (acc / l_safe[..., None]).astype(q.dtype)
    return o, m + jnp.log(l_safe)


# ---------------- tiled backward (shared) -------------------------------

def _flash_bwd_xla(q, k, v, o, lse, do, sm_scale, block_k):
    """Flash-style backward from saved (o, lse): recompute p per
    k-block, never materializing [L, L]. Zero-padded `do` and
    LSE_PAD-poisoned `lse` rows make sequence padding contribute
    exactly zero to every gradient."""
    B, H, L, Dh = q.shape
    qs = q.astype(jnp.float32)
    do_f = do.astype(jnp.float32)
    o_f = o.astype(jnp.float32)
    delta = (do_f * o_f).sum(axis=-1)                   # [B, H, L]
    n_blocks = L // block_k
    kb = k.reshape(B, H, n_blocks, block_k, Dh).astype(jnp.float32)
    vb = v.reshape(B, H, n_blocks, block_k, Dh).astype(jnp.float32)
    q_pos = jnp.arange(L)

    def body(dq, xs):
        kj, vj, j = xs
        s = jnp.einsum("bhqd,bhkd->bhqk", qs * sm_scale, kj,
                       preferred_element_type=jnp.float32)
        k_pos = j * block_k + jnp.arange(block_k)
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                 # [B,H,L,bk]
        dv_j = jnp.einsum("bhqk,bhqd->bhkd", p, do_f,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do_f, vj,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None])                # [B,H,L,bk]
        dq = dq + sm_scale * jnp.einsum(
            "bhqk,bhkd->bhqd", ds, kj,
            preferred_element_type=jnp.float32)
        dk_j = sm_scale * jnp.einsum(
            "bhqk,bhqd->bhkd", ds, qs,
            preferred_element_type=jnp.float32)
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros((B, H, L, Dh), jnp.float32)
    dq, (dk_b, dv_b) = jax.lax.scan(
        body, dq0,
        (kb.transpose(2, 0, 1, 3, 4), vb.transpose(2, 0, 1, 3, 4),
         jnp.arange(n_blocks)))
    dk = dk_b.transpose(1, 2, 0, 3, 4).reshape(B, H, L, Dh)
    dv = dv_b.transpose(1, 2, 0, 3, 4).reshape(B, H, L, Dh)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------- public op ---------------------------------------------

def _on_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, sm_scale: Optional[float] = None):
    """Causal flash attention, [B, H, L, Dh] -> [B, H, L, Dh]."""
    o, _ = _fa_fwd_impl(q, k, v, sm_scale)
    return o


def _fa_fwd_impl(q, k, v, sm_scale):
    L = q.shape[2]
    scale = _resolve_scale(sm_scale, q.shape[-1])
    block = min(DEFAULT_BLOCK, L)
    Lp = _pad_len(L, block)
    qp, kp, vp = (_pad_seq(x, Lp) for x in (q, k, v))
    # tail padding is invisible to real queries under the causal mask
    # (pad positions are strictly later), so outputs [:L] are exact
    if _on_tpu():
        o, lse = _flash_fwd_pallas(qp, kp, vp, scale, block, block)
    else:
        o, lse = _flash_fwd_xla(qp, kp, vp, scale, block)
    return o[:, :, :L], lse[:, :, :L]


def _fa_fwd(q, k, v, sm_scale):
    o, lse = _fa_fwd_impl(q, k, v, sm_scale)
    return o, (q, k, v, o, lse)


def _fa_bwd(sm_scale, res, do):
    q, k, v, o, lse = res
    L = q.shape[2]
    scale = _resolve_scale(sm_scale, q.shape[-1])
    block = min(DEFAULT_BLOCK, L)
    Lp = _pad_len(L, block)
    qp, kp, vp, op, dop = (_pad_seq(x, Lp) for x in (q, k, v, o, do))
    pad = Lp - L
    lsep = (jnp.pad(lse, ((0, 0), (0, 0), (0, pad)),
                    constant_values=LSE_PAD) if pad else lse)
    dq, dk, dv = _flash_bwd_xla(qp, kp, vp, op, lsep, dop, scale, block)
    return dq[:, :, :L], dk[:, :, :L], dv[:, :, :L]


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def reference_attention(q, k, v, sm_scale: Optional[float] = None):
    """O(L^2)-memory einsum attention (the models/gpt2.py baseline
    path), for equivalence tests."""
    scale = _resolve_scale(sm_scale, q.shape[-1])
    L = q.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    causal = jnp.tril(jnp.ones((L, L), bool))
    s = jnp.where(causal[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)

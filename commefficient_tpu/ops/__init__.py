from commefficient_tpu.ops.flat import (  # noqa: F401
    flatten_params,
    masked_topk,
    clip_to_l2,
    global_norm_clip,
    dp_noise,
)
from commefficient_tpu.ops.sketch import CSVec  # noqa: F401

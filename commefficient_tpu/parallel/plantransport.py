"""Coordinator-broadcast control plane (ISSUE 12).

Every non-default scheduling decision — throughput-aware participant
sampling, deadlines, buffered async admission — used to be
single-controller only, because the decisions read process-local state
(wall-clock throughput EMAs, the admit buffer). This module makes the
coordinator's per-round `RoundPlan` an AUTHORITATIVE broadcast control
stream instead:

  * the coordinator serializes each round's plan (participants, work
    fractions, deadlines — scheduler.RoundPlan) to a fixed small
    host-side payload and broadcasts it ONCE per round;
  * every process — the coordinator included — installs the *received*
    plan, so all controllers run the identical install code path;
  * each process computes a digest of the control decision it is about
    to execute (the installed plan PLUS the async-admission merge,
    federated/api._write_ahead_plan) and cross-checks it against the
    other controllers: a diverged process fails loud
    (`PlanDigestError`) instead of silently desyncing;
  * the digest is write-ahead journaled (`schedule` events gain a
    `digest` field, flushed durable BEFORE dispatch), so a plan is
    never executed before it is durable, and a coordinator lost
    mid-run is survivable: ANY process can load the shared checkpoint
    (thr_*/sched_*/smp_* state), be promoted to coordinator, replay
    the deterministic selection stream past the boundary, and verify
    its recomputed digests against the journaled plan stream —
    deterministic takeover, proven bit-exact in
    tests/test_controlplane.py.

Two transports implement the broadcast:

  * `HostCollectiveTransport` — the production path: one
    fixed-size one-to-all host collective per round
    (multihost_utils.broadcast_one_to_all) plus a digest allgather for
    the cross-check. This container cannot run multi-process jax CPU
    (known limitation, CHANGES.md PR 11), so the collective itself is
    exercised only at process_count() == 1; the payload pack/unpack
    and serialization round-trip are unit-tested.
  * `EmulatedPlanNetwork` + `EmulatedTransport` — the primary CI
    surface: N controller objects in ONE process over an in-memory
    bus, with scriptable faults from utils/faults.FaultSchedule —
    `coordinator_crash_at` (the coordinator dies mid-broadcast),
    `broadcast_drop` / `broadcast_dup` / `broadcast_slow` (lost,
    duplicated, late deliveries). Sends and receives ride
    utils/retry.with_retries, so a dropped or slow broadcast is
    ridden out exactly like a coordinator blip on a preemptible pod.

`MirroredControllers` is the emulated multi-controller harness proper:
it drives N `RoundScheduler`s in lockstep the way N SPMD processes
would run the identical sampler code — per-controller rng replicas for
the shared-stream draws, broadcast-received plans for the
process-local ones — and `take_plan` cross-checks every controller's
installed plan byte-for-byte before the model consumes it.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from commefficient_tpu.utils.faults import FaultSchedule, InjectedFault
from commefficient_tpu.utils.retry import with_retries

PLAN_WIRE_VERSION = 1

# fixed payload buffer of the production collective: 8-byte length
# header + the serialized plan. One [8 + PLAN_MAX_BYTES] u8 collective
# per round regardless of plan content; a W=4096 cohort's plan is
# ~100 KB of JSON, far under the cap.
PLAN_MAX_BYTES = 1 << 20


class PlanDigestError(RuntimeError):
    """A controller's installed control decision diverged from the
    broadcast plan stream (or from the write-ahead journaled stream on
    a deterministic restart). Always fatal: a silent desync here means
    different processes dispatch different rounds."""


# ---------------------------------------------------------------------------
# serialization: RoundPlan <-> a fixed small host-side payload


def _float_list(arr) -> Optional[List[float]]:
    if arr is None:
        return None
    # float() of an f32 round-trips exactly through JSON (shortest
    # repr), so deserialize(serialize(p)) is BIT-identical — the
    # identity the N-controller bit-exactness tests rest on
    return [float(v) for v in np.asarray(arr, np.float32)]


def _opt_float(v) -> Optional[float]:
    return None if v is None else float(v)


def serialize_plan(plan) -> bytes:
    """One RoundPlan as canonical JSON bytes (sorted keys, compact
    separators) — deterministic, so its sha256 is a well-defined plan
    identity."""
    obj = {
        "v": PLAN_WIRE_VERSION,
        "round": int(plan.round_idx),
        "n_sampled": int(plan.n_sampled),
        "sampler": str(plan.sampler),
        "participants": (None if plan.participants is None
                         else [int(c) for c in
                               np.asarray(plan.participants)]),
        "active": _float_list(plan.active),
        "work": _float_list(plan.work),
        "deadline_s": _opt_float(plan.deadline_s),
        "est_round_s": _opt_float(plan.est_round_s),
        "expected_round_s": _opt_float(plan.expected_round_s),
    }
    if plan.screen_mult is not None:
        # adaptive screening (ISSUE 17): the key is CONDITIONAL so a
        # non-adaptive run's wire bytes (and their sha256 plan
        # identities) stay byte-identical to a pre-17 build
        obj["screen_mult"] = float(np.float32(plan.screen_mult))
    if plan.controls:
        # controller bank (ISSUE 20): conditional for the same
        # reason. Ints (span picks) ride exact; floats are f32-rounded
        # at stamp time and float() round-trips them bit-exactly
        # through JSON, so install == stamp on every controller.
        obj["controls"] = {
            str(k): (int(v) if isinstance(v, (int, np.integer))
                     else float(np.float32(v)))
            for k, v in plan.controls.items()}
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode()


def deserialize_plan(payload: bytes):
    """Inverse of serialize_plan; raises PlanDigestError on a payload
    this version cannot faithfully install (wire-version skew is a
    deployment error, not a silent downgrade)."""
    from commefficient_tpu.scheduler import RoundPlan
    obj = json.loads(payload.decode())
    if obj.get("v") != PLAN_WIRE_VERSION:
        raise PlanDigestError(
            f"plan wire version {obj.get('v')!r} != "
            f"{PLAN_WIRE_VERSION} — mixed-build controller fleet")

    def arr(key, dtype):
        v = obj.get(key)
        return None if v is None else np.asarray(v, dtype)

    return RoundPlan(
        int(obj["round"]), int(obj["n_sampled"]),
        arr("active", np.float32), arr("work", np.float32),
        obj.get("deadline_s"), obj.get("est_round_s"),
        obj.get("expected_round_s"), str(obj["sampler"]),
        arr("participants", np.int64),
        screen_mult=obj.get("screen_mult"),
        controls=obj.get("controls"))


def payload_digest(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


def plan_digest(plan) -> str:
    return payload_digest(serialize_plan(plan))


def install_digest(round_idx: int, client_ids, survivors, work,
                   admits: Sequence = (), poison=None,
                   screen_on=None) -> str:
    """Digest of the control decision a process is about to EXECUTE:
    the post-composition cohort (ids after async admission), the
    survivor/work operands, and the admit merges themselves — the
    plan-carried form of the admission stream. Every controller must
    compute the identical value (transport.verify), and the value is
    write-ahead journaled so a deterministic restart can prove its
    recomputed stream matches the pre-crash run's.

    poison/screen_on (ISSUE 16): a screened-family dispatch folds its
    value-fault mask and its per-round screen-enable decision into the
    digest too, so multi-controller screened runs stay digest-
    consistent (a process whose rollback window diverged fails loud).
    Left at None — every default-family dispatch — the digest bytes
    are identical to the pre-feature build's."""
    obj = {
        "round": int(round_idx),
        "ids": [int(c) for c in np.asarray(client_ids).reshape(-1)],
        "surv": _float_list(survivors),
        "work": _float_list(work),
        "admits": [[int(s), int(c), float(np.float32(f)), int(o)]
                   for (s, c, f, o) in admits],
    }
    if poison is not None or screen_on is not None:
        obj["poison"] = _float_list(poison)
        obj["screen_on"] = (None if screen_on is None
                            else float(np.float32(screen_on)))
    return payload_digest(json.dumps(
        obj, sort_keys=True, separators=(",", ":")).encode())


def journaled_plan_stream(
        journal_path: str) -> Tuple[Dict[int, str], Dict[int, bytes]]:
    """The write-ahead plan stream of an existing run journal, in ONE
    read: ({round_idx: digest}, {round_idx: serialized RoundPlan
    bytes}) from its `schedule` events (later segments override
    earlier ones — a resumed run legitimately re-journals replayed
    rounds). Transport-run journals carry the full plan payload on
    every event, so a long run's journal is large — the
    deterministic-restart path (FedModel.load_plan_stream) needs both
    maps and must not parse the file twice.

    The plan bytes are the AUTHORITATIVE decision log: a restart
    hands them to RoundScheduler.load_replay_plans so replayed rounds
    INSTALL the durably committed decisions instead of recomputing
    them — a throughput selection recomputed against the restored
    tracker would diverge wherever wall-clock EMA feeds landed
    between the checkpoint boundary and the crash. The digests
    cross-check every replayed round's recomputed install digest, so
    a replay that still diverges fails loud."""
    from commefficient_tpu.telemetry.journal import read_journal
    digests: Dict[int, str] = {}
    plans: Dict[int, bytes] = {}
    if not os.path.exists(journal_path):
        return digests, plans
    records, _ = read_journal(journal_path)
    for rec in records:
        if (rec.get("event") != "schedule"
                or not isinstance(rec.get("round"), int)):
            continue
        if isinstance(rec.get("digest"), str):
            digests[rec["round"]] = rec["digest"]
        if isinstance(rec.get("plan"), str):
            plans[rec["round"]] = rec["plan"].encode()
    return digests, plans


def journaled_schedule_digests(journal_path: str) -> Dict[int, str]:
    """{round_idx: digest} of the write-ahead stream (one-map
    convenience over journaled_plan_stream)."""
    return journaled_plan_stream(journal_path)[0]


def journaled_plans(journal_path: str) -> Dict[int, bytes]:
    """{round_idx: plan bytes} of the write-ahead stream (one-map
    convenience over journaled_plan_stream)."""
    return journaled_plan_stream(journal_path)[1]


# ---------------------------------------------------------------------------
# transport interface


class PlanTransport:
    """One-to-all broadcast of serialized RoundPlans plus the
    cross-controller digest check. `broadcast(r, payload)` is called
    with the payload on the coordinator and None elsewhere; every
    caller returns the round's DELIVERED payload (the coordinator
    installs the round-tripped bytes too — identical code path)."""

    @property
    def is_coordinator(self) -> bool:
        raise NotImplementedError

    def broadcast(self, round_idx: int,
                  payload: Optional[bytes] = None) -> bytes:
        raise NotImplementedError

    def verify(self, round_idx: int, digest: str,
               scope: str = "plan") -> None:
        """Cross-check this process's digest against the other
        controllers'; raises PlanDigestError on divergence. Two scopes
        ride the same transport: "plan" (the scheduler's installed
        RoundPlan bytes, checked at install) and "install" (the
        model's executed-decision digest — cohort + operands + admit
        merges — checked at dispatch); they hash different objects, so
        the cross-checks are namespaced per scope."""
        raise NotImplementedError


class HostCollectiveTransport(PlanTransport):
    """Production transport: one fixed-size one-to-all host collective
    per round (the thin DCN-friendly payload the ISSUE specifies) and
    a digest allgather for verify. Degenerates to the identity at
    process_count() == 1 — which is all this container can execute
    (multi-process jax CPU is unavailable here), so the collective
    path is serialization-unit-tested while the emulated harness is
    the CI surface for the fault story."""

    def __init__(self, max_bytes: int = PLAN_MAX_BYTES):
        self.max_bytes = int(max_bytes)

    @property
    def is_coordinator(self) -> bool:
        from commefficient_tpu.parallel import multihost as mh
        return mh.is_coordinator()

    def pack(self, payload: Optional[bytes]) -> np.ndarray:
        """[8 + max_bytes] u8 buffer: little-endian length header +
        payload; non-coordinators contribute zeros (ignored by the
        one-to-all collective)."""
        buf = np.zeros(8 + self.max_bytes, np.uint8)
        if payload is not None:
            if len(payload) > self.max_bytes:
                raise ValueError(
                    f"serialized plan is {len(payload)} bytes > "
                    f"transport max {self.max_bytes}")
            buf[:8] = np.frombuffer(
                len(payload).to_bytes(8, "little"), np.uint8)
            buf[8:8 + len(payload)] = np.frombuffer(payload, np.uint8)
        return buf

    @staticmethod
    def unpack(buf: np.ndarray) -> bytes:
        buf = np.asarray(buf, np.uint8)
        n = int.from_bytes(buf[:8].tobytes(), "little")
        return buf[8:8 + n].tobytes()

    def broadcast(self, round_idx: int,
                  payload: Optional[bytes] = None) -> bytes:
        from jax.experimental import multihost_utils

        def send():
            out = multihost_utils.broadcast_one_to_all(
                self.pack(payload))
            return self.unpack(np.asarray(out))

        # transient coordination blips (a neighbor host restarting)
        # retry symmetrically on every process — the same failure is
        # visible fleet-wide, so the retrying collective re-forms
        return with_retries(
            send, describe=f"round {round_idx} plan broadcast")

    def verify(self, round_idx: int, digest: str,
               scope: str = "plan") -> None:
        from commefficient_tpu.parallel import multihost as mh
        if not mh.is_multihost():
            return
        from jax.experimental import multihost_utils
        mine = np.frombuffer(bytes.fromhex(digest), np.uint8)
        all_d = np.asarray(
            multihost_utils.process_allgather(mine))
        if not (all_d == mine[None, :]).all():
            bad = [p for p in range(all_d.shape[0])
                   if not (all_d[p] == mine).all()]
            raise PlanDigestError(
                f"round {round_idx}: {scope} digest diverged across "
                f"controllers (processes {bad} disagree with "
                f"process {mh.process_index()})")


# ---------------------------------------------------------------------------
# emulated multi-controller harness (the primary CI surface)


class EmulatedPlanNetwork:
    """In-memory broadcast bus for N in-process controllers, with the
    FaultSchedule's control-plane faults scripted in: dropped first
    sends, duplicated deliveries, slow receives, and the coordinator
    dying mid-broadcast. `promote` is the deterministic takeover:
    after a coordinator loss the lowest surviving controller id
    becomes the coordinator."""

    def __init__(self, num_controllers: int,
                 schedule: Optional[FaultSchedule] = None):
        if num_controllers < 1:
            raise ValueError("need at least one controller")
        self.num = int(num_controllers)
        self.schedule = schedule
        self.coordinator_id = 0
        self.dead: set = set()
        self._mail: Dict[int, bytes] = {}
        self._send_attempts: Dict[int, int] = {}
        self._recv_attempts: Dict[Tuple[int, int], int] = {}
        # round -> delivery count (2 under broadcast_dup — receivers
        # must install idempotently; tests read this)
        self.deliveries: Dict[int, int] = {}
        # (round, scope) -> {pid: digest} cross-check registrations
        self._digests: Dict[Tuple[int, str], Dict[int, str]] = {}

    def promote(self, pid: Optional[int] = None) -> int:
        """Deterministic takeover after a coordinator loss: mark the
        old coordinator dead and promote `pid` (default: the lowest
        surviving controller id). Returns the new coordinator id."""
        self.dead.add(self.coordinator_id)
        if pid is None:
            pid = min(p for p in range(self.num)
                      if p not in self.dead)
        if pid in self.dead:
            raise ValueError(f"controller {pid} is dead")
        self.coordinator_id = int(pid)
        return self.coordinator_id

    # -- bus primitives (EmulatedTransport drives these) -------------------
    def send(self, round_idx: int, payload: bytes) -> None:
        att = self._send_attempts.get(round_idx, 0)
        self._send_attempts[round_idx] = att + 1
        s = self.schedule
        if s is not None and s.broadcast_dropped(round_idx, att):
            raise TimeoutError(
                f"round {round_idx} plan broadcast lost in flight "
                "(scripted drop)")
        copies = 2 if (s is not None
                       and s.broadcast_duplicated(round_idx)) else 1
        self._mail[round_idx] = payload
        self.deliveries[round_idx] = (
            self.deliveries.get(round_idx, 0) + copies)

    def recv(self, round_idx: int, pid: int) -> bytes:
        key = (round_idx, pid)
        att = self._recv_attempts.get(key, 0)
        self._recv_attempts[key] = att + 1
        s = self.schedule
        if s is not None and att < s.broadcast_slow_attempts(round_idx):
            raise TimeoutError(
                f"round {round_idx} plan not yet visible to "
                f"controller {pid} (scripted slow broadcast)")
        payload = self._mail.get(round_idx)
        if payload is None:
            raise TimeoutError(
                f"round {round_idx} plan not yet broadcast")
        return payload

    def register_digest(self, round_idx: int, pid: int,
                        digest: str, scope: str = "plan") -> None:
        seen = self._digests.setdefault((round_idx, scope), {})
        for other, d in seen.items():
            if d != digest:
                raise PlanDigestError(
                    f"round {round_idx}: controller {pid} installed "
                    f"{scope} digest {digest[:12]}… but controller "
                    f"{other} installed {d[:12]}… — control plane "
                    "diverged")
        seen[pid] = digest


class EmulatedTransport(PlanTransport):
    """One controller's endpoint on an EmulatedPlanNetwork. Sends and
    receives ride utils/retry.with_retries (no real sleeping by
    default — the bus is in-process), so the scripted drop/slow faults
    exercise exactly the retry machinery a pod deployment leans on."""

    def __init__(self, network: EmulatedPlanNetwork, process_id: int,
                 retries: int = 8, retry_sleep=None):
        self.network = network
        self.pid = int(process_id)
        self.retries = int(retries)
        self._sleep = retry_sleep if retry_sleep is not None \
            else (lambda s: None)

    @property
    def is_coordinator(self) -> bool:
        return self.pid == self.network.coordinator_id

    def broadcast(self, round_idx: int,
                  payload: Optional[bytes] = None) -> bytes:
        if self.pid in self.network.dead:
            raise RuntimeError(f"controller {self.pid} is dead")
        if self.is_coordinator and payload is not None:
            s = self.network.schedule
            if s is not None and s.should_crash_coordinator(round_idx):
                # the coordinator dies mid-broadcast: the plan never
                # reaches the bus (it may already be write-ahead
                # journaled — the restart path recomputes and
                # digest-checks it)
                self.network.dead.add(self.pid)
                raise InjectedFault(round_idx - 1)
            with_retries(
                lambda: self.network.send(round_idx, payload),
                retries=self.retries, base_delay=0.0,
                sleep=self._sleep,
                describe=f"round {round_idx} plan broadcast")
        return with_retries(
            lambda: self.network.recv(round_idx, self.pid),
            retries=self.retries, base_delay=0.0, sleep=self._sleep,
            describe=f"round {round_idx} plan receive")

    def verify(self, round_idx: int, digest: str,
               scope: str = "plan") -> None:
        self.network.register_digest(round_idx, self.pid, digest,
                                     scope)


class MirroredControllers:
    """N RoundSchedulers driven in lockstep over one emulated network:
    the in-process stand-in for N SPMD processes running the identical
    sampler code. Duck-types the RoundScheduler surface the FedSampler
    and FedModel consume.

    Per round the coordinator runs first (it owns the live tracker
    and broadcasts at commit_round), then every follower runs the SAME
    select/commit pair with the SAME data-layer inputs — shared-stream
    rng draws replayed from a per-controller rng replica (each real
    process owns an identically-seeded RandomState), process-local
    draws replaced by the broadcast plan. Follower calls are DEFERRED
    to commit time: a real follower process blocks in recv until the
    coordinator's broadcast lands, and in a single-process lockstep
    that ordering is realized by running the followers' select after
    the coordinator's commit has filled the bus. Every follower's
    selection must match the coordinator's, and `take_plan` pops every
    controller's installed plan and cross-checks them byte-for-byte
    (serialize_plan) before the model consumes the coordinator's — a
    diverged controller fails loud either way."""

    def __init__(self, schedulers: List, transports: List,
                 coordinator: int = 0):
        if len(schedulers) != len(transports):
            raise ValueError("one transport per controller")
        self.schedulers = list(schedulers)
        self.transports = list(transports)
        self.coordinator = int(coordinator)
        self._pending_select = None
        self._pending_chosen = None

    @property
    def _coord(self):
        return self.schedulers[self.coordinator]

    @property
    def _followers(self):
        return [(pid, s) for pid, s in enumerate(self.schedulers)
                if pid != self.coordinator
                and pid not in self.transports[pid].network.dead]

    # ---------------- RoundScheduler surface ------------------------------
    @property
    def cfg(self):
        return self._coord.cfg

    @property
    def is_default(self) -> bool:
        return self._coord.is_default

    @property
    def tracker(self):
        return self._coord.tracker

    @property
    def state_prefetch(self):
        return self._coord.state_prefetch

    @state_prefetch.setter
    def state_prefetch(self, fn) -> None:
        self._coord.state_prefetch = fn

    @property
    def screen_ctl(self):
        return self._coord.screen_ctl

    @screen_ctl.setter
    def screen_ctl(self, ctl) -> None:
        # adaptive screening (ISSUE 17): every controller carries the
        # reference — the coordinator stamps plans from it, and a
        # follower's is_default must go False so it installs the
        # broadcast plan instead of skipping commit. Only the model
        # ever calls observe(), so sharing one instance is safe.
        for s in self.schedulers:
            s.screen_ctl = ctl

    @property
    def control_bank(self):
        return self._coord.control_bank

    @control_bank.setter
    def control_bank(self, bank) -> None:
        # controller bank (ISSUE 20): same sharing contract as
        # screen_ctl — the coordinator stamps plans through the bank,
        # followers' is_default goes False so they install the
        # broadcast. Only the model feeds observations and drains
        # adjustment events, so one shared instance is safe here too.
        for s in self.schedulers:
            s.control_bank = bank

    def begin_epoch(self, first_round: int) -> None:
        self._pending_select = None
        for s in self.schedulers:
            s.begin_epoch(first_round)

    def select(self, alive, num_slots: int, rng) -> np.ndarray:
        # coordinator only; the followers' identical select runs at
        # commit time, once the broadcast their recv blocks on has
        # landed. Each real process draws from its OWN
        # identically-seeded RandomState — the stashed rng state
        # replays that per follower, so a shared-stream (uniform) draw
        # advances every controller's rng in lockstep.
        self._pending_select = (np.array(alive, copy=True),
                                int(num_slots), rng.get_state())
        out = self._coord.select(alive, num_slots, rng)
        self._pending_chosen = np.array(out, copy=True)
        return out

    def commit_round(self, client_ids, examples_per_slot) -> None:
        self._coord.commit_round(client_ids, examples_per_slot)
        pending = getattr(self, "_pending_select", None)
        for pid, s in self._followers:
            if pending is not None:
                alive, num_slots, rng_state = pending
                frng = np.random.RandomState()
                frng.set_state(rng_state)
                theirs = np.asarray(s.select(alive, num_slots, frng))
                if not np.array_equal(self._pending_chosen, theirs):
                    raise PlanDigestError(
                        f"controller {pid} selected a different "
                        "cohort than the coordinator at round "
                        f"{s._next_round}")
                fs = self.transports[pid].network.schedule
                if (fs is not None
                        and fs.broadcast_duplicated(s._next_round)):
                    # the duplicated delivery lands AGAIN between the
                    # follower's receive and its commit — the receiver
                    # must install idempotently (same plan, same round
                    # key, counters advanced once)
                    s._recv_plan(s._next_round)
            s.commit_round(client_ids, examples_per_slot)
        self._pending_select = None

    def take_plan(self, round_idx: int):
        plan = self._coord.take_plan(round_idx)
        ref = None if plan is None else serialize_plan(plan)
        for pid, s in self._followers:
            theirs = s.take_plan(round_idx)
            enc = None if theirs is None else serialize_plan(theirs)
            if enc != ref:
                raise PlanDigestError(
                    f"round {round_idx}: controller {pid} installed "
                    "a different plan than the coordinator")
        return plan

    def state_dict(self) -> dict:
        return self._coord.state_dict()

    def load_state_dict(self, state: dict) -> None:
        # the durable state is shared storage: every surviving
        # controller restores the same bytes (how a promoted follower
        # inherits the coordinator's counters/tracker-side state)
        for s in self.schedulers:
            s.load_state_dict(state)

    def load_replay_plans(self, plans: Dict[int, bytes]) -> None:
        # replay bytes install on the COORDINATOR only: it
        # rebroadcasts them verbatim, and the followers receive the
        # journaled stream exactly like live rounds
        self._coord.load_replay_plans(plans)


def attach_emulated_cluster(model, train_loader,
                            num_controllers: int = 2,
                            coordinator: int = 0,
                            schedule: Optional[FaultSchedule] = None,
                            network: Optional[
                                EmulatedPlanNetwork] = None):
    """Test/driver wiring of the emulated multi-controller harness:
    builds N RoundSchedulers — the coordinator over the model's live
    throughput tracker, followers over their own (deliberately
    divergent: never fed) trackers, so any follower decision that
    leaks local state fails the cross-checks — attaches their
    transports, installs the MirroredControllers facade as the run's
    scheduler, and points the model at the coordinator's transport
    for install-digest verification. Returns (mirror, network).

    Pass an existing `network` (with `promote()` already applied) to
    model a deterministic takeover: the promoted controller becomes
    the broadcaster while the dead one is excluded from lockstep."""
    from commefficient_tpu.scheduler import RoundScheduler
    from commefficient_tpu.telemetry.clients import (
        ClientThroughputTracker,
    )
    if network is None:
        network = EmulatedPlanNetwork(num_controllers,
                                      schedule=schedule)
        network.coordinator_id = int(coordinator)
    coordinator = network.coordinator_id
    scheds, transports = [], []
    for pid in range(network.num):
        tracker = (model.throughput if pid == coordinator
                   else ClientThroughputTracker(model.num_clients))
        s = RoundScheduler(model.cfg, model.num_clients, tracker)
        t = EmulatedTransport(network, pid)
        s.attach_transport(t)
        scheds.append(s)
        transports.append(t)
    mirror = MirroredControllers(scheds, transports,
                                 coordinator=coordinator)
    train_loader.sampler.scheduler = mirror
    model.attach_scheduler(mirror)
    model.attach_data_sampler(train_loader.sampler)
    model.attach_transport(transports[coordinator])
    return mirror, network


def attach_config_transport(model, train_loader, cfg):
    """Driver wiring for Config.plan_transport (both drivers call this
    right after scheduler.attach_round_scheduler, BEFORE --resume):

      * "collective" — attach the production HostCollectiveTransport
        to the run's single RoundScheduler (multi-controller SPMD: the
        real processes each run this same line);
      * "emulated"  — replace the scheduler with an in-process
        N-controller MirroredControllers harness
        (cfg.plan_controllers controllers). Chaos scripting rides env
        vars so the production CLI stays clean:
        CCTPU_EMU_COORD_CRASH=<round> kills the coordinator
        mid-broadcast of that round (the tier1.sh smoke's scripted
        crash), CCTPU_EMU_COORDINATOR=<pid> picks the (takeover)
        coordinator id.

    Returns the attached transport/mirror, or None when
    cfg.plan_transport is empty."""
    if not cfg.plan_transport:
        return None
    if cfg.plan_transport == "collective":
        t = HostCollectiveTransport()
        model.scheduler.attach_transport(t)
        model.attach_transport(t)
        return t
    schedule = None
    crash = os.environ.get("CCTPU_EMU_COORD_CRASH", "")
    if crash:
        schedule = FaultSchedule(coordinator_crash_at=int(crash))
    coordinator = int(os.environ.get("CCTPU_EMU_COORDINATOR", "0"))
    mirror, _ = attach_emulated_cluster(
        model, train_loader,
        num_controllers=int(cfg.plan_controllers),
        coordinator=coordinator, schedule=schedule)
    return mirror

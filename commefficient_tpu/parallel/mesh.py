"""Device mesh construction.

The framework's distinctive parallel axis is `clients`: each device
shard simulates a subset of the round's participating clients
(SURVEY.md §2.10 — the reference's only parallelism is one worker
process per GPU, fed_aggregator.py:143-158; here workers are shards).
A second optional `model` axis supports tensor-parallel sharding of
large models (GPT2-scale), mapped so `clients` rides the outer ICI
dimension.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_client_mesh(num_client_shards: Optional[int] = None,
                     devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over the `clients` axis."""
    devices = list(devices) if devices is not None else jax.devices()
    n = num_client_shards or len(devices)
    if n > len(devices):
        raise ValueError(f"asked for {n} shards, have {len(devices)} devices")
    return Mesh(np.asarray(devices[:n]), axis_names=("clients",))


def make_client_model_mesh(num_client_shards: int, model_parallel: int,
                           devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """2-D (clients, model) mesh; model-parallel inner so its
    collectives ride the fastest ICI links."""
    devices = list(devices) if devices is not None else jax.devices()
    need = num_client_shards * model_parallel
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(num_client_shards, model_parallel)
    return Mesh(arr, axis_names=("clients", "model"))

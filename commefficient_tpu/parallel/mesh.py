"""Device mesh construction.

The framework's distinctive parallel axis is `clients`: each device
shard simulates a subset of the round's participating clients
(SURVEY.md §2.10 — the reference's only parallelism is one worker
process per GPU, fed_aggregator.py:143-158; here workers are shards).
A second optional `model` axis supports tensor-parallel sharding of
large models (GPT2-scale), mapped so `clients` rides the outer ICI
dimension.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from commefficient_tpu.analysis.domains import CLIENTS_AXIS, MODEL_AXIS


def make_client_mesh(num_client_shards: Optional[int] = None,
                     devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over the `clients` axis."""
    devices = list(devices) if devices is not None else jax.devices()
    n = num_client_shards or len(devices)
    if n > len(devices):
        raise ValueError(f"asked for {n} shards, have {len(devices)} devices")
    return Mesh(np.asarray(devices[:n]), axis_names=(CLIENTS_AXIS,))


def make_client_model_mesh(num_client_shards: int, model_parallel: int,
                           devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """2-D (clients, model) mesh; model-parallel inner so its
    collectives ride the fastest ICI links."""
    devices = list(devices) if devices is not None else jax.devices()
    need = num_client_shards * model_parallel
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(num_client_shards, model_parallel)
    return Mesh(arr, axis_names=(CLIENTS_AXIS, MODEL_AXIS))


def slice_balanced_prefix(devices: Sequence[jax.Device],
                          count: int) -> Optional[list]:
    """Pick `count` devices spread EQUALLY across physical slices
    (slice-major order), or None when that isn't possible.

    A flat prefix of jax.devices() can span slices unevenly when a run
    uses fewer devices than exist (e.g. 2 slices x 4 devices, count=6
    -> 4+2), and the hybrid mesh construction requires equal per-slice
    counts. Callers fall back to a flat mesh on None."""
    devices = list(devices)
    slices: dict = {}
    for d in devices:
        slices.setdefault(getattr(d, "slice_index", 0) or 0, []).append(d)
    n_sl = len(slices)
    if n_sl <= 1:
        return devices[:count] if count <= len(devices) else None
    per = count // n_sl
    if per * n_sl != count:
        return None
    if any(len(g) < per for g in slices.values()):
        return None
    out = []
    for k in sorted(slices):
        out.extend(slices[k][:per])
    return out


def make_multihost_client_mesh(model_parallel: int = 1,
                               devices: Optional[Sequence[jax.Device]] = None,
                               num_slices: Optional[int] = None) -> Mesh:
    """Mesh spanning every slice/host of a multi-slice TPU job: the
    `clients` axis is laid out slice-major (DCN outer, intra-slice ICI
    inner), the optional `model` axis innermost.

    Why this layout is right for federated rounds: the round's single
    collective is one psum of the compressed update (a sketch table or
    k-sparse vector — federated/round.py), so exactly one table-sized
    all-reduce crosses DCN per round, while the model axis's frequent
    activation collectives stay on intra-slice ICI. This is the
    XLA-collective equivalent of scaling the reference's NCCL reduce
    (fed_worker.py:138) beyond one host.

    On real multi-slice hardware the DCN structure is read from each
    device's `slice_index` (jax.experimental.mesh_utils hybrid mesh).
    `num_slices` forces an emulated layout for single-slice or CPU-mesh
    testing: device i is assigned to slice i % num_slices and the axis
    is regrouped slice-major — a genuine permutation of the flat device
    order, so tests exercise a non-identity placement (the round's
    results must be placement-invariant).

    The emulation is for CORRECTNESS testing only: combined with
    model_parallel > 1 on real single-slice hardware it pairs
    non-adjacent physical devices on the model axis (e.g. (0,2),(4,6)),
    putting TP collectives on slower ICI paths than the 'model axis
    innermost = fastest ICI' contract this module otherwise keeps. Do
    not use --num_slices emulation with model_parallel for performance
    runs on real hardware — on real multi-slice topology the emulation
    is bypassed (the physical layout wins, above).
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by "
                         f"model_parallel={model_parallel}")
    clients = n // model_parallel

    real_slices = {getattr(d, "slice_index", 0) or 0 for d in devices}
    if len(real_slices) > 1:
        # real multi-slice topology always wins: emulating a DIFFERENT
        # slice count would interleave devices of distinct physical
        # slices into one group and put DCN hops inside the supposedly
        # intra-slice inner dimension
        n_sl = len(real_slices)
        if num_slices is not None and num_slices != n_sl:
            raise ValueError(
                f"num_slices={num_slices} but the devices report "
                f"{n_sl} physical slices")
        from jax.experimental import mesh_utils
        if clients % n_sl:
            raise ValueError(f"clients axis {clients} not divisible by "
                             f"{n_sl} slices")
        arr = mesh_utils.create_hybrid_device_mesh(
            (clients // n_sl, model_parallel), (n_sl, 1),
            devices=devices)
    else:
        n_sl = num_slices or 1
        if clients % n_sl:
            raise ValueError(f"clients axis {clients} not divisible by "
                             f"num_slices={n_sl}")
        # emulated slice assignment (device i -> slice i % n_sl),
        # regrouped slice-major: a real permutation of the device
        # order whenever n_sl > 1
        order = np.argsort([i % n_sl for i in range(n)], kind="stable")
        arr = np.asarray(devices)[order].reshape(clients, model_parallel)
    if model_parallel == 1:
        return Mesh(arr.reshape(-1), axis_names=(CLIENTS_AXIS,))
    return Mesh(arr, axis_names=(CLIENTS_AXIS, MODEL_AXIS))

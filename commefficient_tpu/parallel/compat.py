"""JAX version compatibility shims.

The engine targets the modern `jax.shard_map` API (top-level export,
`axis_names=` to leave further mesh axes automatic, `jax.lax.pcast`
for replicated->varying casts). Older jaxlib builds (<= 0.4.x, still
what some TPU images pin) only ship `jax.experimental.shard_map` with
the complementary `auto=` parameter and no varying-type system at all.
This module presents the modern surface on both:

  * `shard_map(f, mesh=..., in_specs=..., out_specs=..., axis_names=...)`
    — on old jax, `axis_names` is translated to
    `auto = mesh.axis_names - axis_names` and rep-checking is disabled
    (partial-auto mode requires that there anyway);
  * `pcast(x, axis_name, to="varying")` — on old jax this is the
    identity: without the varying-type system there is no automatic
    cotangent psum for unvarying operands, which is exactly the
    behavior the modern code uses pcast to opt out of;
  * `axis_size(axis_name)` — `jax.lax.axis_size` where it exists,
    `psum(1, axis)` (the classic static-size idiom) where it doesn't;
  * `abstract_mesh()` — the trace's abstract mesh
    (`jax.sharding.get_abstract_mesh`) on modern jax, None on old jax
    (which has no abstract-mesh machinery; callers fall back to the
    concrete mesh).
"""
from __future__ import annotations

from typing import Optional

import jax

_MODERN = hasattr(jax, "shard_map")

if _MODERN:
    def shard_map(f, *, mesh, in_specs, out_specs,
                  axis_names: Optional[frozenset] = None):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs,
                  axis_names: Optional[frozenset] = None):
        manual = (frozenset(mesh.axis_names) if axis_names is None
                  else frozenset(axis_names))
        auto = frozenset(mesh.axis_names) - manual
        # check_rep must be off in partial-auto mode on legacy jax; off
        # unconditionally so both paths trace the same program class
        return _legacy_shard_map(f, mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False,
                                 auto=auto)


if hasattr(jax.lax, "pcast"):
    def pcast(x, axis_name, to: str = "varying"):
        return jax.lax.pcast(x, axis_name, to=to)
elif hasattr(jax.lax, "pvary"):
    def pcast(x, axis_name, to: str = "varying"):
        assert to == "varying"
        return jax.lax.pvary(x, axis_name)
else:
    def pcast(x, axis_name, to: str = "varying"):
        # legacy jax has no varying types: grads taken inside a
        # shard_map body are already shard-local, so the cast the
        # modern API needs here is a no-op
        return x


if hasattr(jax.lax, "axis_size"):
    def axis_size(axis_name) -> jax.Array:
        return jax.lax.axis_size(axis_name)
else:
    def axis_size(axis_name):
        # psum of 1 over a manual axis folds to the static axis size
        return jax.lax.psum(1, axis_name)


def abstract_mesh():
    """The current trace's abstract mesh, or None when this jax has no
    abstract-mesh machinery (callers use their concrete mesh)."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    return get() if get is not None else None

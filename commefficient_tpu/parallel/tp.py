"""Tensor parallelism over the mesh's `model` axis.

The reference's only parallelism is one worker process per GPU
(fed_aggregator.py:143-158) — model parallelism does not exist there.
Here it is a first-class mesh axis: `make_client_model_mesh` lays
devices out as (clients, model) with `model` innermost so its
collectives ride the fastest ICI links, the round engine runs manual
(`shard_map`) over `clients` only, and GSPMD partitions each client's
forward/backward over `model`, steered by the sharding constraints
below. No communication code changes per model: XLA inserts the
all-reduces where the Megatron-style kernel layout requires them.

Layout (the standard two-matmul sandwich per block):
  * column-parallel first matmuls — QKV projection [E, 3E] and MLP
    up-projection [E, 4E] sharded P(None, MODEL_AXIS), their biases
    P(MODEL_AXIS) — each shard computes a slice of heads / hidden units;
  * row-parallel second matmuls — attention/MLP output projections
    sharded P(MODEL_AXIS, None) — partial products all-reduced by GSPMD;
  * the (tied) token embedding [V, E] sharded over the vocab axis
    P(MODEL_AXIS, None); `attend` logits are likewise reduced by GSPMD.

Usage (workload level — the engine is workload-agnostic):
    params = constrain_params(params, mesh, GPT2_TP_RULES)  # in loss_fn
"""
from __future__ import annotations

import re
from typing import Callable, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from commefficient_tpu.analysis.domains import MODEL_AXIS
from commefficient_tpu.parallel import compat

# (path regex, spec) — first match wins; unmatched leaves replicate.
# Paths are "/"-joined pytree key paths, e.g.
# "params/transformer/h_3/attn/c_attn/kernel".
GPT2_TP_RULES: Sequence[Tuple[str, P]] = (
    (r"attn/c_attn/kernel$", P(None, MODEL_AXIS)),
    (r"attn/c_attn/bias$", P(MODEL_AXIS)),
    (r"attn/c_proj/kernel$", P(MODEL_AXIS, None)),
    (r"mlp/c_fc/kernel$", P(None, MODEL_AXIS)),
    (r"mlp/c_fc/bias$", P(MODEL_AXIS)),
    (r"mlp/c_proj/kernel$", P(MODEL_AXIS, None)),
    (r"wte/embedding$", P(MODEL_AXIS, None)),
)


def _path_str(path) -> str:
    out = []
    for p in path:
        out.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(out)


def constrain_params(params, mesh: Mesh,
                     rules: Sequence[Tuple[str, P]] = GPT2_TP_RULES):
    """Apply with_sharding_constraint to every rule-matched leaf.
    Call inside the traced loss (the params pytree there is rebuilt
    from the flat [D] vector each step, so constraints must be
    re-stated per trace). No-op outside rule matches."""
    compiled = [(re.compile(rx), spec) for rx, spec in rules]
    # constraints must be expressed against the TRACE's mesh: inside
    # the engine's partially-manual shard_map the clients axis is
    # Manual (and params arrive clients-varying via pcast), which the
    # concrete mesh — all-Auto axis types — cannot describe
    am = compat.abstract_mesh()
    target = am if am is not None and MODEL_AXIS in am.axis_names else mesh

    def constrain(path, leaf):
        s = _path_str(path)
        for rx, spec in compiled:
            if rx.search(s):
                return jax.lax.with_sharding_constraint(
                    leaf, NamedSharding(target, spec))
        return leaf

    return jax.tree_util.tree_map_with_path(constrain, params)


def tp_loss(loss_fn: Callable, mesh: Mesh,
            rules: Sequence[Tuple[str, P]] = GPT2_TP_RULES) -> Callable:
    """Wrap a loss_fn(params, batch, mask) so its parameters carry the
    tensor-parallel layout before the model runs."""
    if MODEL_AXIS not in mesh.axis_names:
        return loss_fn

    def wrapped(params, batch, mask):
        return loss_fn(constrain_params(params, mesh, rules), batch, mask)

    return wrapped

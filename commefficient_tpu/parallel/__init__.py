from commefficient_tpu.parallel.mesh import make_client_mesh  # noqa: F401

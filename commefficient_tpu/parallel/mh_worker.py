"""Spawnable multi-controller worker: fixed, deterministic FedModel
scenarios, runnable either single-process or as one process of an
N-process grid (coordination service + Gloo CPU collectives).

This is the executable proof of the multi-host runtime (the reference's
process topology is PS + N workers rendezvousing over
torch.distributed, CommEfficient/fed_aggregator.py:143-164; here it is
N equal controllers of one SPMD program): the SAME global program —
sketch rounds through FedModel's per-round path, a scanned multi-round
span, communication accounting, and an eval pass — must produce the
same results whether one process feeds all 8 mesh devices or two
processes each feed their 4, with per-process batch feeding
(multihost.local_row_slice → make_array_from_process_local_data).

Three scenario variants (--variant):
  * ``base``     — 1-D ``clients`` mesh, per-process row feeding (the
                   round-4 scenario).
  * ``tp``       — (4 clients × 2 model) mesh: multihost × tensor
                   parallelism. The loss is tp-wrapped (parallel/tp.py
                   Megatron-style column/row constraints on an MLP
                   sandwich), so GSPMD model-axis collectives run
                   INSIDE the manual clients-axis shard_map across two
                   controller processes.
  * ``noncontig``— emulated slice-major device permutation
                   (make_multihost_client_mesh num_slices=2): each
                   process's devices are NOT a contiguous block of the
                   clients axis, local_row_slice raises, and the
                   scenario takes the documented globalize() fallback
                   (FedModel.feed_global) — the path real pods with
                   non-process-major layouts will hit.

Used by tests/test_multihost.py and __graft_entry__.dryrun_multichip;
each spawns the interpreter with::

    python -m commefficient_tpu.parallel.mh_worker --out r0.npz \
        --process_id 0 --num_processes 2 --port 29517   # and pid 1
    python -m commefficient_tpu.parallel.mh_worker --out ref.npz  # single

Import discipline: jax is imported inside main() AFTER environment
setup so ``jax.distributed.initialize`` precedes any backend touch.
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

# scenario constants — identical in every process and in the
# single-process reference run
W, B, N_CLIENTS, ROUNDS, SPAN = 8, 2, 16, 3, 2
MESH_DEVICES = 8
VARIANTS = ("base", "tp", "noncontig")
# comparison tolerance for grid-vs-single-process equality; stated in
# every artifact/dryrun line (VERDICT r4 weak #6: the tolerance and
# compared keys must be visible, not buried here)
RTOL, ATOL = 1e-5, 1e-6


def _scenario_batches(variant: str):
    """Deterministic per-round global batches [ROUNDS + SPAN]."""
    rs = np.random.RandomState(0)
    out = []
    for t in range(ROUNDS + SPAN):
        if variant == "tp":
            x = rs.randn(W, B, 12).astype(np.float32)
        else:
            x = rs.randn(W, B, 16, 16, 3).astype(np.float32)
        y = rs.randint(0, 10, (W, B)).astype(np.int32)
        ids = ((np.arange(W) * 2 + t) % N_CLIENTS).astype(np.int32)
        out.append((ids, x, y, np.ones((W, B), np.float32)))
    return out


def _make_model_and_rules(variant: str):
    """(flax module, tp_rules or None, init example x)."""
    import flax.linen as nn
    from jax.sharding import PartitionSpec as P

    from commefficient_tpu.analysis.domains import MODEL_AXIS

    if variant == "tp":
        class TpMLP(nn.Module):
            """Megatron-style two-matmul sandwich: column-parallel up
            projection, row-parallel down projection (parallel/tp.py
            layout), plus a replicated head."""
            @nn.compact
            def __call__(self, x):
                h = nn.Dense(64, name="up")(x)
                h = nn.relu(h)
                h = nn.Dense(16, name="down")(h)
                return nn.Dense(10, name="head")(h)

        rules = (
            (r"up/kernel$", P(None, MODEL_AXIS)),
            (r"up/bias$", P(MODEL_AXIS)),
            (r"down/kernel$", P(MODEL_AXIS, None)),
        )
        return TpMLP(), rules, np.zeros((B, 12), np.float32)

    from commefficient_tpu.models import ResNet9

    model = ResNet9(
        num_classes=10,
        channels={"prep": 4, "layer1": 8, "layer2": 8, "layer3": 8})
    return model, None, np.zeros((B, 16, 16, 3), np.float32)


def _make_mesh(variant: str):
    import jax

    from commefficient_tpu.parallel.mesh import (
        make_client_mesh, make_client_model_mesh,
        make_multihost_client_mesh,
    )

    if variant == "tp":
        # (4 clients x 2 model): in the 2-process grid each process's 4
        # devices are rows {0,1} / {2,3} of the clients axis — local
        # feeding stays valid, while the model axis pairs devices
        # WITHIN a process
        return make_client_model_mesh(MESH_DEVICES // 2, 2)
    if variant == "noncontig":
        # emulated slice-major permutation [d0,d2,d4,d6,d1,d3,d5,d7]:
        # process 0's devices land at clients positions {0,1,4,5} — not
        # contiguous — so local_row_slice must raise in the grid run
        return make_multihost_client_mesh(
            devices=jax.devices()[:MESH_DEVICES], num_slices=2)
    return make_client_mesh(MESH_DEVICES)


def run_scenario(out_path: str, variant: str = "base") -> None:
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.config import Config
    from commefficient_tpu.federated.api import FedModel, FedOptimizer
    from commefficient_tpu.parallel import multihost as mh
    from commefficient_tpu.parallel.tp import tp_loss

    model, tp_rules, x0 = _make_model_and_rules(variant)

    def base_loss(params, batch, mask):
        xb, yb = batch
        logits = model.apply(params, xb)
        logp = jax.nn.log_softmax(logits)
        per_ex = -jnp.take_along_axis(logp, yb[:, None], axis=1)[:, 0]
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = (per_ex * mask).sum() / denom
        acc = ((logits.argmax(-1) == yb) * mask).sum() / denom
        return loss, (acc,)

    mesh = _make_mesh(variant)
    loss_fn = (tp_loss(base_loss, mesh, rules=tp_rules)
               if tp_rules is not None else base_loss)

    # do_topk_down gives the scenario per-client PERSISTENT state (the
    # stale-weights rows), so the cross-process sharded gather/scatter
    # path and the chunked checkpoint gather are both exercised
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 local_momentum=0.0, k=16, num_rows=3, num_cols=512,
                 num_blocks=1, weight_decay=5e-4, microbatch_size=-1,
                 num_workers=W, num_clients=N_CLIENTS, seed=0,
                 do_topk_down=True)
    fed = FedModel(model, loss_fn, cfg, mesh=mesh, init_batch=(x0,))
    opt = FedOptimizer(fed)
    opt.param_groups[0]["lr"] = 0.1

    # per-process feeding where the layout allows it; the documented
    # globalize() fallback where it does not (noncontig grid runs)
    try:
        sl = mh.local_row_slice(mesh, W)
        esl = mh.local_row_slice(mesh, MESH_DEVICES)
    except ValueError:
        assert variant == "noncontig", \
            f"unexpected non-contiguous layout in variant {variant}"
        fed.feed_global = True
        sl = esl = slice(0, None)
    batches = _scenario_batches(variant)

    losses, downloads, uploads = [], None, None
    for ids, x, y, mask in batches[:ROUNDS]:
        out = fed((ids, (x[sl], y[sl]), mask[sl]))
        losses.append(mh.gather_host(out[0]))
        downloads, uploads = out[-2], out[-1]

    # scanned multi-round span through the same multihost feeding path
    span = batches[ROUNDS:]
    ids_s = np.stack([b[0] for b in span])
    x_s = np.stack([b[1][sl] for b in span])
    y_s = np.stack([b[2][sl] for b in span])
    m_s = np.stack([b[3][sl] for b in span])
    out = fed.run_rounds(ids_s, (x_s, y_s), m_s,
                         np.full((SPAN,), 0.1, np.float32))
    span_losses, downloads, uploads = out[0], out[-2], out[-1]

    # eval pass (forward-only shard_map path)
    rs = np.random.RandomState(99)
    if variant == "tp":
        ex = rs.randn(MESH_DEVICES, B, 12).astype(np.float32)
    else:
        ex = rs.randn(MESH_DEVICES, B, 16, 16, 3).astype(np.float32)
    ey = rs.randint(0, 10, (MESH_DEVICES, B)).astype(np.int32)
    emask = np.ones((MESH_DEVICES, B), np.float32)
    fed.train(False)
    eval_out = fed(((ex[esl], ey[esl]), emask[esl]))

    # checkpoint round-trip: collective chunked gather of the sharded
    # per-client state; only the coordinator writes/reads the file
    from commefficient_tpu.utils.checkpoint import (
        load_checkpoint, save_checkpoint,
    )
    ckpt_path = out_path + ".ckpt"
    save_checkpoint(ckpt_path, fed.server, fed.clients,
                    scheduler_step=7, accountant=fed.accountant,
                    prev_change_words=fed._prev_change_words,
                    chunk_rows=4)
    if mh.is_coordinator():
        ck = load_checkpoint(ckpt_path)
        assert ck.scheduler_step == 7
        np.savez(out_path,
                 ps_weights=np.asarray(fed.ps_weights),
                 losses=np.stack(losses),
                 span_losses=np.asarray(span_losses),
                 eval_loss=np.asarray(eval_out[0]),
                 download=np.asarray(downloads),
                 upload=np.asarray(uploads),
                 ckpt_ps_weights=np.asarray(ck.server.ps_weights),
                 ckpt_client_weights=np.asarray(ck.clients.weights),
                 process_count=mh.process_count(),
                 feed_global=int(fed.feed_global))
    mh.sync_processes("scenario-done")
    print(f"mh_worker[{variant}] pid={mh.process_index()}"
          f"/{mh.process_count()} feed_global={fed.feed_global} ok",
          flush=True)


# keys every scenario artifact carries; the grid runner compares all
# of them against the single-process reference
RESULT_KEYS = ("ps_weights", "losses", "span_losses", "eval_loss",
               "download", "upload", "ckpt_ps_weights",
               "ckpt_client_weights")


def run_grid_vs_reference(out_dir: str, timeout: float = 600.0,
                          rtol: float = RTOL, atol: float = ATOL,
                          variant: str = "base") -> dict:
    """Spawn the scenario as a 2-process × 4-device grid AND as one
    8-device process, then assert every RESULT_KEYS entry matches to
    (rtol, atol). Returns the grid's loaded arrays. Shared by
    tests/test_multihost.py and __graft_entry__.dryrun_multichip —
    one harness, three variants."""
    import socket
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ref = os.path.join(out_dir, f"ref_{variant}.npz")
    two = os.path.join(out_dir, f"two_{variant}.npz")

    def spawn(args):
        return subprocess.Popen(
            [sys.executable, "-m", "commefficient_tpu.parallel.mh_worker",
             "--variant", variant, *args],
            cwd=repo, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT)

    procs = [
        spawn(["--out", two, "--process_id", "0",
               "--num_processes", "2", "--port", str(port)]),
        spawn(["--out", two + ".ignored", "--process_id", "1",
               "--num_processes", "2", "--port", str(port)]),
        spawn(["--out", ref]),
    ]
    logs = [p.communicate(timeout=timeout)[0].decode() for p in procs]
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-2000:]

    a, b = np.load(ref), np.load(two)
    assert int(b["process_count"]) == 2
    if variant == "noncontig":
        # the grid run must have actually exercised the globalize()
        # fallback (its layout makes local_row_slice raise), while the
        # single-process run keeps the local-feeding path — the
        # comparison below is therefore also a cross-path equivalence
        assert int(b["feed_global"]) == 1, \
            "noncontig grid run did not take the globalize() fallback"
        assert int(a["feed_global"]) == 0
    for key in RESULT_KEYS:
        np.testing.assert_allclose(a[key], b[key], rtol=rtol, atol=atol,
                                   err_msg=f"{variant}:{key}")
    return {k: b[k] for k in b.files}


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--variant", choices=VARIANTS, default="base")
    ap.add_argument("--process_id", type=int, default=None)
    ap.add_argument("--num_processes", type=int, default=None)
    ap.add_argument("--port", type=int, default=29517)
    ap.add_argument("--local_devices", type=int, default=None,
                    help="virtual CPU devices in THIS process (default: "
                         "mesh size / num_processes, or mesh size when "
                         "single-process)")
    args = ap.parse_args(argv)

    multi = args.num_processes is not None and args.num_processes > 1
    n_local = args.local_devices or (
        MESH_DEVICES // args.num_processes if multi else MESH_DEVICES)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_local}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    # the interpreter may have pre-imported jax and registered the TPU
    # tunnel plugin (tests/conftest.py documents the freeze); config
    # wins over the captured env
    jax.config.update("jax_platforms", "cpu")

    if multi:
        from commefficient_tpu.parallel import multihost as mh
        mh.initialize(coordinator_address=f"127.0.0.1:{args.port}",
                      num_processes=args.num_processes,
                      process_id=args.process_id)

    run_scenario(args.out, variant=args.variant)


if __name__ == "__main__":
    main()

"""Multi-host (multi-process) runtime support.

The reference's runtime is a multi-process topology on one box: a PS
process plus N worker processes rendezvousing over
``torch.distributed`` (reference: CommEfficient/fed_aggregator.py:143-164).
Its TPU-native equivalent at the BASELINE v4-32 scale is a multi-HOST
JAX job: one Python process per host, each addressing its local chips,
all running the SAME program over one global mesh (multi-controller
SPMD). This module is everything the rest of the framework needs to
run that way:

  * :func:`initialize` — ``jax.distributed.initialize`` with the
    session's frozen-platform workaround (the interpreter may have
    pre-registered the TPU tunnel plugin; see tests/conftest.py).
  * :func:`globalize` — lift a host value every process holds
    identically (PS weights, client ids, LR vectors, PRNG keys) into a
    global array with an explicit sharding on the global mesh.
  * :func:`shard_rows` — per-process batch feeding: each process
    passes ONLY the batch rows its addressable devices own
    (``jax.make_array_from_process_local_data``), so no host ever
    materializes the global batch — the fix for the round-3 gap where
    FedModel ``jnp.asarray``-ed host-global batches.
  * :func:`local_row_slice` — which rows of a ``[num_workers, ...]``
    round batch this process must feed (FedLoader materializes only
    these).
  * :func:`gather_host` — materialize a possibly cross-process-sharded
    metric on every host (``process_allgather``); the identity in
    single-process runs.
  * :func:`is_coordinator` — process-0 guard for logging, checkpoint
    writes, and accounting output.

Design note: everything degrades to a no-op in single-process runs —
``process_count() == 1`` keeps the exact round-3 code paths, so the
single-chip bench and the 8-device CPU test mesh are untouched.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from commefficient_tpu.analysis.domains import CLIENTS_AXIS

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None,
               connect_timeout_s: float = 300.0,
               connect_retries: int = 3,
               retry_sleep=None) -> None:
    """``jax.distributed.initialize``, before any backend use.

    On TPU pods every argument is auto-detected from the TPU
    environment, so a bare ``initialize()`` suffices; elsewhere
    (CPU/GPU grids, the emulated two-process CPU mode the tests use)
    pass the coordinator and process grid explicitly.

    The coordinator rendezvous is the single most failure-prone moment
    of a preemptible-pod launch (a neighbor host restarting a few
    seconds late looks like a dead coordinator), so the one blocking
    attempt is replaced by a bounded connect policy: each attempt is
    capped at ``connect_timeout_s`` (passed through to jax's
    ``initialization_timeout`` where the installed version supports
    it), and a TRANSIENT failure — connection refused/reset, gRPC
    DEADLINE_EXCEEDED/UNAVAILABLE (utils/retry.is_transient_error) —
    is retried up to ``connect_retries`` more times with exponential
    backoff, each retry logged through utils/logging. Fatal errors
    (bad arguments, mismatched grids) raise immediately.
    ``retry_sleep`` overrides the backoff sleep (tests)."""
    global _initialized
    if _initialized:
        # idempotent: drivers and libraries may both ask for the
        # runtime; the second caller gets the existing one
        return
    kw = {}
    if coordinator_address:
        kw["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    if local_device_ids is not None:
        kw["local_device_ids"] = local_device_ids
    import inspect
    try:
        params = inspect.signature(jax.distributed.initialize).parameters
    except (TypeError, ValueError):  # C-accelerated / wrapped callable
        params = {}
    if "initialization_timeout" in params:
        kw["initialization_timeout"] = int(connect_timeout_s)

    from commefficient_tpu.utils.retry import with_retries

    def attempt():
        try:
            jax.distributed.initialize(**kw)
        except (RuntimeError, ValueError, OSError):
            # The expected rendezvous failure classes: XlaRuntimeError
            # (a RuntimeError) from gRPC timeouts/refusals, ValueError
            # from bad grids, OSError/ConnectionError from the socket
            # layer. jax assigns its global client (and rank 0's
            # coordination service) BEFORE connect(), so a failed
            # connect leaves half-initialized state that would make the
            # next call raise 'initialize should only be called once' —
            # a fatal-looking error masking the real timeout. Tear it
            # down best-effort so the retry is a genuine fresh attempt,
            # then re-raise for with_retries' transient/fatal triage.
            # Anything outside these classes (incl. InjectedFault)
            # propagates untouched, per GL005.
            try:
                jax.distributed.shutdown()
            except (RuntimeError, ValueError, OSError):
                # a half-initialized client may have nothing to shut
                # down; the original connect error is the one to surface
                pass
            raise

    retry_kw = {} if retry_sleep is None else {"sleep": retry_sleep}
    with_retries(attempt,
                 retries=connect_retries,
                 describe="jax.distributed.initialize "
                          f"({coordinator_address or 'auto-detected'})",
                 **retry_kw)
    _initialized = True


def initialize_from_config(cfg) -> None:
    """Driver entry: honor --multihost/--coordinator_address/
    --num_processes/--process_id (config.py flags)."""
    initialize(
        coordinator_address=cfg.coordinator_address or None,
        num_processes=cfg.num_processes if cfg.num_processes > 0 else None,
        process_id=cfg.process_id if cfg.process_id >= 0 else None)


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_coordinator() -> bool:
    """True on the process that owns logging/checkpointing/accounting
    output (the reference's rank-0 PS process)."""
    return jax.process_index() == 0


def is_multihost() -> bool:
    return jax.process_count() > 1


# ---------------------------------------------------------------------------
# partition rules (the `match_partition_rules` / shard-and-gather-fn
# pattern of SNIPPETS.md [1], reduced to what the round engine needs)


def match_partition_rules(rules, tree, default: P = P()):
    """Map every leaf of `tree` to a PartitionSpec by regex over its
    tree path (SNIPPETS.md [1] `match_partition_rules`): the first
    `(pattern, spec)` whose pattern searches the leaf's keystr path
    wins. A leaf with fewer dims than the matched spec's length —
    zero-size placeholders, scalars — falls back to `default`, so an
    unused state field never claims a mesh axis it cannot divide.

    Returns a pytree of PartitionSpecs with `tree`'s treedef — feed it
    to `shardings()` for jit in/out_shardings, or zip it with the
    leaves for explicit device_put placement."""
    import re
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        spec = default
        for pat, s in rules:
            if re.search(pat, name) and getattr(leaf, "ndim", 0) >= len(s):
                spec = s
                break
        out.append(spec)
    return jax.tree_util.tree_unflatten(treedef, out)


def shardings(mesh: Mesh, specs):
    """A pytree of PartitionSpecs -> the matching NamedShardings on
    `mesh` (the make_shard_and_gather_fns half the jit API needs:
    jit(..., out_shardings=shardings(mesh, specs)))."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P))


# ---------------------------------------------------------------------------
# array construction


def globalize(mesh: Mesh, spec: P, value) -> jax.Array:
    """Lift a host value that EVERY process holds identically into a
    global array with sharding ``NamedSharding(mesh, spec)``.

    Single-process: plain ``jax.device_put`` with the sharding (so
    state still lands sharded on the local mesh). Multi-process: each
    process contributes the shards its devices own via
    ``make_array_from_callback`` indexing into the (identical) host
    value — correct for any device→process layout."""
    sharding = NamedSharding(mesh, spec)
    if not is_multihost():
        # EXPLICIT placement (device_put of a host array or an
        # already-device array): the jitted-round transfer-guard
        # contract (analysis/runtime.forbid_transfers) allows explicit
        # transfers only, so the host boundary must never go through an
        # implicit jnp.asarray of host data
        if not isinstance(value, jax.Array):
            value = np.asarray(value)
        return jax.device_put(value, sharding)
    arr = np.asarray(value)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


# jitted allocation builders, cached so repeated shapes reuse one jit
# wrapper (a fresh jax.jit(lambda ...) per call would retrace+compile
# every invocation); NamedSharding/np.dtype/tuple keys are hashable
@functools.lru_cache(maxsize=512)
def _jit_zeros(shape: Tuple[int, ...], dtype, sharding):
    return jax.jit(lambda: jnp.zeros(shape, dtype),
                   out_shardings=sharding)


@functools.lru_cache(maxsize=128)
def _jit_broadcast(shape: Tuple[int, ...], sharding):
    return jax.jit(lambda v: jnp.broadcast_to(v, shape),
                   out_shardings=sharding)


@functools.lru_cache(maxsize=128)
def _jit_copy(sharding):
    return jax.jit(lambda x: x.copy(), out_shardings=sharding)


def globalize_owned(mesh: Mesh, spec: P, value) -> jax.Array:
    """globalize + guarantee the result owns an XLA-allocated buffer.

    For values that enter the round engine's DONATION chain — the
    resumed server/client state a checkpoint loader places — a plain
    device_put of a large aligned numpy array may be ZERO-COPY on the
    CPU backend: the "device" buffer aliases numpy-owned heap memory,
    and the donated in-place update chain then writes into (and
    eventually frees) memory XLA does not own — intermittent glibc
    heap corruption (see zeros() below; found by the ISSUE-11 verify
    drive). The jitted copy forces a fresh XLA output allocation;
    values only ever READ by programs don't need this."""
    arr = globalize(mesh, spec, value)
    return _jit_copy(NamedSharding(mesh, spec))(arr)


def shard_rows(mesh: Mesh, local_rows, leading_axes: int = 0) -> jax.Array:
    """Per-process batch feeding: build the global ``[W, ...]`` round
    array from THIS process's rows only.

    ``local_rows``: the rows owned by this process's devices, in mesh
    order — shape ``[W_local, ...]`` (``leading_axes=0``) or with
    ``leading_axes`` unsharded leading dims before the clients axis
    (the scanned multi-round span's ``[N, W_local, ...]``).

    Single-process: device_put of the (already global) rows."""
    spec = P(*([None] * leading_axes), CLIENTS_AXIS,
             *([None] * (np.ndim(local_rows) - leading_axes - 1)))
    sharding = NamedSharding(mesh, spec)
    if not is_multihost():
        # explicit placement — see globalize
        if not isinstance(local_rows, jax.Array):
            local_rows = np.asarray(local_rows)
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_rows))


def local_row_slice(mesh: Mesh, num_rows: int) -> slice:
    """The contiguous block of a ``[num_rows, ...]`` clients-sharded
    array that this process feeds (and therefore the only rows its
    FedLoader must materialize).

    Requires this process's devices to hold a contiguous block of the
    mesh's ``clients`` axis — true for the standard process-major
    device order of ``jax.devices()``; raises otherwise rather than
    feeding rows to the wrong process."""
    axis_devices = _clients_axis_devices(mesh)
    n_shards = len(axis_devices)
    if num_rows % n_shards:
        raise ValueError(f"num_rows={num_rows} not divisible by the "
                         f"{n_shards}-way clients axis")
    rows_per_shard = num_rows // n_shards
    me = jax.process_index()
    mine = [i for i, d in enumerate(axis_devices) if d.process_index == me]
    if not mine:
        return slice(0, 0)
    lo, hi = min(mine), max(mine)
    if mine != list(range(lo, hi + 1)):
        raise ValueError(
            "this process's devices are not a contiguous block of the "
            "clients axis; feed globally with globalize() instead")
    return slice(lo * rows_per_shard, (hi + 1) * rows_per_shard)


def apply_feed_slices(model, train_loader, val_loader,
                      num_train_rows: int, num_val_rows: int) -> None:
    """Driver-side wiring of per-process batch feeding (both trainers
    share it — the invariants are subtle enough to keep in ONE place):
    compute BOTH row slices before assigning either, so a failure can't
    leave one loader local and the other global; on the non-contiguity
    error only, engage the documented globalize() fallback
    (FedModel.feed_global); anything else (e.g. divisibility) is a
    config error and re-raises."""
    try:
        train_sl = local_row_slice(model.mesh, num_train_rows)
        val_sl = local_row_slice(model.mesh, num_val_rows)
    except ValueError as e:
        if "globalize" not in str(e):
            raise
        model.feed_global = True
        if is_coordinator():
            print(f"non-contiguous device layout ({e}); "
                  "feeding batches globally via globalize()")
    else:
        train_loader.feed_slice = train_sl
        val_loader.feed_slice = val_sl


def _clients_axis_devices(mesh: Mesh):
    """Mesh devices along the clients axis (first model-column when a
    model axis exists: the clients coordinate determines the row
    block; every model-column replica of a row must then live in the
    same process for local feeding, which `local_row_slice` verifies
    via contiguity of the flattened list)."""
    axes = list(mesh.axis_names)
    arr = mesh.devices
    if axes == [CLIENTS_AXIS]:
        return list(arr.reshape(-1))
    # move the clients axis first, take the first element of the rest
    k = axes.index(CLIENTS_AXIS)
    arr = np.moveaxis(arr, k, 0)
    return list(arr.reshape(arr.shape[0], -1)[:, 0])


def zeros(mesh: Mesh, spec: P, shape: Tuple[int, ...],
          dtype=jnp.float32) -> jax.Array:
    """Zero-initialized global array. Multi-process path allocates only
    this process's shards (per-shard callback) — the per-client state
    arrays are the framework's memory hazard (SURVEY.md §7.0) and must
    never materialize host-globally."""
    sharding = NamedSharding(mesh, spec)
    if not is_multihost():
        # allocate ON DEVICE (jitted zeros with explicit out_shardings
        # — no transfer at all, so trivially transfer-guard-clean).
        # Deliberately NOT device_put(np.zeros(...)): jax zero-copies
        # large aligned numpy buffers into CPU device arrays, and the
        # round engine DONATES these blocks — the in-place donation
        # chain then writes into (and eventually frees) numpy-owned
        # heap memory for the rest of the run, which intermittently
        # corrupts the allocator on the CPU thunk runtime (glibc
        # "free(): invalid pointer" / "corrupted size vs. prev_size";
        # observed on the scanned local_topk driver, ISSUE 11 verify).
        # A device-native buffer keeps the whole donation chain inside
        # XLA's allocator.
        return _jit_zeros(tuple(shape), np.dtype(dtype), sharding)()
    # multihost: shard-local host staging. A jitted device-side copy
    # (the single-process fix above) is not an option here — the CPU
    # backend cannot run cross-process computations, so the grid
    # emulation would fail before it ever trained — hence the shard
    # buffers are made un-zero-copyable instead, which forces
    # device_put to copy them into XLA-owned memory (same donation
    # hazard as above, same ownership guarantee, per shard)
    return jax.make_array_from_callback(
        tuple(shape), sharding,
        lambda idx: _unaliasable(
            np.zeros(_shard_shape(idx, shape), dtype)))


def tile_rows(mesh: Mesh, vec, rows: int) -> jax.Array:
    """``[rows, D]`` global array whose every row is ``vec``, sharded
    ``P('clients', None)`` — the per-client stale-weights state of the
    download-top-k path. Shard-local materialization only."""
    host = np.asarray(vec)
    shape = (rows, host.shape[0])
    sharding = NamedSharding(mesh, P(CLIENTS_AXIS, None))
    if not is_multihost():
        # materialize the tile ON DEVICE from the (small, explicit)
        # device_put of the base vector: like zeros() above, the
        # resulting block rides the round engine's donation chain, so
        # its buffer must be XLA-allocated, never a zero-copied numpy
        # broadcast
        base = jax.device_put(host, NamedSharding(mesh, P()))
        return _jit_broadcast(shape, sharding)(base)

    def cb(idx):
        # _unaliasable: these rows ride the donation chain — see
        # zeros() above
        return _unaliasable(np.broadcast_to(
            host[idx[1]], _shard_shape(idx, shape)))

    return jax.make_array_from_callback(shape, sharding, cb)


def _shard_shape(idx: Tuple[slice, ...], shape: Tuple[int, ...]):
    return tuple(len(range(*s.indices(n))) for s, n in zip(idx, shape))


def _unaliasable(arr: np.ndarray) -> np.ndarray:
    """A copy of `arr` whose buffer device_put can NEVER zero-copy:
    the data starts one element into an over-allocated block, so it
    fails XLA's CPU-client alignment check and is always copied into
    an XLA-owned device buffer. Used for host-staged state that rides
    the round engine's donation chain on the multihost path, where
    the jitted on-device allocation of the single-process path is
    unavailable (the CPU backend cannot run cross-process programs).
    If a future backend copies anyway, this is merely one redundant
    host copy at init time."""
    flat = np.empty(arr.size + 1, arr.dtype)
    out = flat[1:].reshape(arr.shape)
    out[...] = arr
    return out


# ---------------------------------------------------------------------------
# result materialization


def gather_host(x) -> np.ndarray:
    """Materialize a (possibly cross-process-sharded) device array on
    every host. An EXPLICIT ``jax.device_get`` when the array is fully
    addressable (so a transfer-guarded round may call this — implicit
    ``np.asarray`` of a device array would trip the guard);
    ``process_allgather`` otherwise."""
    if isinstance(x, np.ndarray) or np.isscalar(x):
        return np.asarray(x)
    if getattr(x, "is_fully_addressable", True) or _fully_replicated(x):
        return jax.device_get(x)
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(x, tiled=True)


def async_gather_host(x):
    """Begin the device->host copy of `x` WITHOUT blocking and return
    a zero-arg completer that materializes it (an explicit
    ``gather_host``, so a transfer-guarded caller may invoke it on any
    thread). The tiered client-state spill path (ISSUE 11,
    federated/statestore.py) uses this to move evicted rows off the
    critical path: the copy is started at dispatch time and the
    writer thread blocks on completion instead of the round loop.

    The completer memoizes its result: a pending spill's rows may be
    read back by several restores (plus the writer-thread commit)
    before the entry retires, and each call would otherwise re-run
    the full gather. A concurrent first call may compute twice —
    both produce the identical host array, so the race is benign."""
    try:
        x.copy_to_host_async()
    except AttributeError:
        # host numpy value or an array type without the async API —
        # the completer below is then the whole (cheap) copy
        pass
    memo = []

    def complete():
        if not memo:
            memo.append(gather_host(x))
        return memo[0]

    return complete


def _fully_replicated(x) -> bool:
    try:
        return bool(x.is_fully_replicated)
    except AttributeError:
        return False


def sync_processes(name: str = "barrier") -> None:
    """Cross-process barrier (checkpoint write ordering)."""
    if is_multihost():
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)

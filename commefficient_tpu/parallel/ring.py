"""Ring attention: causal attention with the SEQUENCE sharded over a
mesh axis — the long-context scaling primitive.

Each shard of the `seq` axis holds one contiguous chunk of the
sequence ([B, H, Lc, Dh] of queries, keys and values). K/V chunks
rotate around the ring via `lax.ppermute` (neighbor exchange — rides
ICI, never DCN on a sane mesh layout), and every shard folds each
arriving chunk into the same online-softmax state the flash kernel
uses (ops/attention.py), so no shard ever materializes more than
[B, H, Lc, Lc] scores. After `S` rotations every (query, key) pair has
met exactly once; causality falls out of comparing GLOBAL positions,
so off-diagonal chunks need no special cases.

This is an extension beyond the reference (which has no sequence
parallelism of any kind); it composes with the framework's mesh axes
the same way tensor parallelism does — `clients` outer, `seq` inner:

    mesh = Mesh(devices.reshape(C, S), ("clients", "seq"))
    shard_map(..., in_specs=P("clients", None, None, "seq", None))

Verified equivalent to single-device attention in tests/test_ring.py.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from commefficient_tpu.ops.attention import NEG_INF, online_softmax_fold
from commefficient_tpu.parallel import compat


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Causal attention over a sequence sharded on `axis_name`.

    q, k, v: [B, H, Lc, Dh] — this shard's chunk (global sequence
    length = Lc * axis_size, chunk i holding positions
    [i*Lc, (i+1)*Lc)). Returns this shard's [B, H, Lc, Dh] output.
    Call INSIDE shard_map/psum context where `axis_name` is manual.
    """
    B, H, Lc, Dh = q.shape
    n = compat.axis_size(axis_name)    # static under shard_map
    my = jax.lax.axis_index(axis_name)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(Dh)

    qs = q.astype(jnp.float32) * scale
    q_pos = my * Lc + jnp.arange(Lc)                     # global positions

    def fold(state, kv_src):
        kt, vt, src = kv_src
        # the same online-softmax fold the flash kernel uses
        # (ops/attention.py) — one copy of the rescaling math
        k_pos = src * Lc + jnp.arange(Lc)
        return online_softmax_fold(state, qs, kt, vt, q_pos, k_pos)

    m = jnp.full((B, H, Lc), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Lc), jnp.float32)
    acc = jnp.zeros((B, H, Lc, Dh), jnp.float32)

    # static ring schedule: at step t this shard holds chunk (my - t);
    # rotate kv to the next shard after each fold so communication
    # overlaps the matmul of the following step under XLA's scheduler
    kt, vt = k, v
    ring = [(i, (i + 1) % n) for i in range(n)]
    for t in range(n):
        src = (my - t) % n
        m, l, acc = fold((m, l, acc), (kt, vt, src))
        if t + 1 < n:
            kt = jax.lax.ppermute(kt, axis_name, ring)
            vt = jax.lax.ppermute(vt, axis_name, ring)

    l_safe = jnp.maximum(l, 1e-30)
    return (acc / l_safe[..., None]).astype(q.dtype)

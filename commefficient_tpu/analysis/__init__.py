"""graftlint: trace-safety static analysis + runtime sanitizers.

PRs 1-2 bought the round engine hard guarantees — exactly three traced
round programs (mask-free, dropout, dropout+stragglers), bit-exact
crash->resume, PRNG domain separation between the dropout and straggler
draws — but nothing enforced them except hand-written tests a future
refactor could silently rot. This package makes the invariants
mechanical:

  * `engine` + `rules` — an AST lint pass (``python -m
    commefficient_tpu.analysis <paths>``) with JAX-specific rules
    GL001-GL010: host nondeterminism reachable from traced code, hidden
    host syncs / trace breaks, PRNG key reuse, Python control flow over
    traced values, fault-swallowing broad ``except`` handlers,
    non-atomic file writes, unconstrained shard_map/pjit layouts,
    large exact top-k, PRNG domain tags outside the `domains`
    registry, and mesh-axis names outside its MESH_AXES registry.
    Per-line ``# graftlint: disable=GLxxx`` suppressions and
    a baseline file grandfather justified hits.
  * `audit` + `costmodel` — the SECOND tier (``graftaudit``, ISSUE 7):
    traces the three round programs per config/backend to ClosedJaxprs
    and walks the program itself — forbidden host-interaction
    primitives, f64, large exact sorts, population-scaling buffers
    (with the named client-state inventory), buffer-donation coverage,
    and a static FLOPs/HBM cost report gated against the committed
    ``audit.baseline.json``.
  * `shardaudit` — the THIRD tier (``graftmesh`` / ``graftaudit
    --mesh``, ISSUE 8): traces the round programs + the scanned span
    under explicit multi-device meshes (the real parallel/mesh.py
    constructors on the simulated 8-device host platform) and checks
    the sharding/collective contracts — replication across the
    clients axis, population-scaling collectives, missing shardings,
    link-class placement (one table-sized DCN reduction per round),
    resharding vs the single-device program — plus a deterministic
    per-link ICI/DCN byte report gated against
    ``meshaudit.baseline.json`` (rules AU007-AU011; exit 1 =
    violations, 2 = baseline drift, shared with graftaudit).
  * `domains` — the central registries: PRNG-domain tags (dropout /
    straggler / sampler) whose uniqueness GL009 and an import-time
    assert both enforce, and the MESH_AXES axis-name registry GL010
    holds the sharding layer to.
  * `runtime` — sanitizers armed by tests: ``assert_program_count(n)``
    (a compilation counter enforcing the three-programs contract) and
    ``forbid_transfers()`` (``jax.transfer_guard`` proving the jitted
    round performs zero implicit host transfers).

The lint pass is deliberately jax-free (pure ``ast``) so it runs in
any environment — only `runtime` and `audit`'s tracing functions
import jax (lazily, with JAX_PLATFORMS pinned to cpu in the CLI so
the auditor never claims an accelerator).
"""
from commefficient_tpu.analysis.engine import (  # noqa: F401
    Baseline, LintError, Violation, lint_paths, lint_source,
)
from commefficient_tpu.analysis.rules import ALL_RULES, RULE_DOCS  # noqa: F401

"""graftlint: trace-safety static analysis + runtime sanitizers.

PRs 1-2 bought the round engine hard guarantees — exactly three traced
round programs (mask-free, dropout, dropout+stragglers), bit-exact
crash->resume, PRNG domain separation between the dropout and straggler
draws — but nothing enforced them except hand-written tests a future
refactor could silently rot. This package makes the invariants
mechanical:

  * `engine` + `rules` — an AST lint pass (``python -m
    commefficient_tpu.analysis <paths>``) with JAX-specific rules
    GL001-GL006: host nondeterminism reachable from traced code, hidden
    host syncs / trace breaks, PRNG key reuse, Python control flow over
    traced values, fault-swallowing broad ``except`` handlers, and
    non-atomic file writes. Per-line ``# graftlint: disable=GLxxx``
    suppressions and a baseline file grandfather justified hits.
  * `runtime` — sanitizers armed by tests: ``assert_program_count(n)``
    (a compilation counter enforcing the three-programs contract) and
    ``forbid_transfers()`` (``jax.transfer_guard`` proving the jitted
    round performs zero implicit host transfers).

The static pass is deliberately jax-free (pure ``ast``) so it runs in
any environment — only `runtime` imports jax.
"""
from commefficient_tpu.analysis.engine import (  # noqa: F401
    Baseline, LintError, Violation, lint_paths, lint_source,
)
from commefficient_tpu.analysis.rules import ALL_RULES, RULE_DOCS  # noqa: F401

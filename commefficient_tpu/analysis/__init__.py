"""graftlint: trace-safety static analysis + runtime sanitizers.

PRs 1-2 bought the round engine hard guarantees — exactly three traced
round programs (mask-free, dropout, dropout+stragglers), bit-exact
crash->resume, PRNG domain separation between the dropout and straggler
draws — but nothing enforced them except hand-written tests a future
refactor could silently rot. This package makes the invariants
mechanical:

  * `engine` + `rules` — an AST lint pass (``python -m
    commefficient_tpu.analysis <paths>``) with JAX-specific rules
    GL001-GL013: host nondeterminism reachable from traced code, hidden
    host syncs / trace breaks, PRNG key reuse, Python control flow over
    traced values, fault-swallowing broad ``except`` handlers,
    non-atomic file writes, unconstrained shard_map/pjit layouts,
    large exact top-k, PRNG domain tags outside the `domains`
    registry, mesh-axis names outside its MESH_AXES registry,
    wall-clock durations, anonymous threads, and float equality on
    traced values (the exact-zero sparsity test stays legal).
    Per-line ``# graftlint: disable=GLxxx`` suppressions and
    a baseline file grandfather justified hits.
  * `audit` + `costmodel` — the SECOND tier (``graftaudit``, ISSUE 7):
    traces the three round programs per config/backend to ClosedJaxprs
    and walks the program itself — forbidden host-interaction
    primitives, f64, large exact sorts, population-scaling buffers
    (with the named client-state inventory), buffer-donation coverage,
    and a static FLOPs/HBM cost report gated against the committed
    ``audit.baseline.json``.
  * `shardaudit` — the THIRD tier (``graftmesh`` / ``graftaudit
    --mesh``, ISSUE 8): traces the round programs + the scanned span
    under explicit multi-device meshes (the real parallel/mesh.py
    constructors on the simulated 8-device host platform) and checks
    the sharding/collective contracts — replication across the
    clients axis, population-scaling collectives, missing shardings,
    link-class placement (one table-sized DCN reduction per round),
    resharding vs the single-device program — plus a deterministic
    per-link ICI/DCN byte report gated against
    ``meshaudit.baseline.json`` (rules AU007-AU011; exit 1 =
    violations, 2 = baseline drift, shared with graftaudit).
  * `syncaudit` — the FOURTH tier (``graftsync``, ISSUE 14): pure-AST
    over the five host packages, checking the shared-state guard
    registry, the static lock-order graph, queue-ownership transfer,
    blocking-under-lock, thread lifecycle, and the named
    happens-before edges in `domains.ORDERING_EDGES` (rules
    SY001-SY006; empty exact-match ``graftsync.baseline.json``).
  * `numaudit` — the FIFTH tier (``graftnum``, ISSUE 18): re-walks
    every registered ClosedJaxpr with a dtype/finiteness dataflow
    lattice — NaN-unsafe mask arithmetic (the PR-16 ``t * mask``
    class), unregistered precision downcasts vs
    `domains.PRECISION_SEAMS` + sub-f32 error-feedback residuals,
    unguarded division/rsqrt/log/sqrt, replay-nondeterministic
    primitives — and prices cross-shard psum reassociation as a
    worst-case ulp bound per program, gated exact-match in
    ``graftnum.baseline.json`` (rules NU001-NU005; empty violations
    baseline).
  * `domains` — the central registries: PRNG-domain tags (dropout /
    straggler / sampler) whose uniqueness GL009 and an import-time
    assert both enforce, the MESH_AXES axis-name registry GL010
    holds the sharding layer to, the SHARED_STATE guard map and
    ORDERING_EDGES happens-before registry graftsync enforces, and
    the PRECISION_SEAMS lossy-cast registry graftnum enforces.
  * `runtime` — sanitizers armed by tests: ``assert_program_count(n)``
    (a compilation counter enforcing the three-programs contract),
    ``forbid_transfers()`` (``jax.transfer_guard`` proving the jitted
    round performs zero implicit host transfers), the
    ``LockOrderSanitizer`` (observed lock-acquisition graph asserted
    acyclic — graftsync's runtime twin), and the
    ``NumericSanitizer`` (post-dispatch finite guard over exported
    round metrics + the bitwise replay drill — graftnum's runtime
    twin).

The lint pass is deliberately jax-free (pure ``ast``) so it runs in
any environment — only `runtime` and `audit`'s tracing functions
import jax (lazily, with JAX_PLATFORMS pinned to cpu in the CLI so
the auditor never claims an accelerator).
"""
from commefficient_tpu.analysis.engine import (  # noqa: F401
    Baseline, LintError, Violation, lint_paths, lint_source,
)
from commefficient_tpu.analysis.rules import ALL_RULES, RULE_DOCS  # noqa: F401

"""Central registries: PRNG-domain tags, mesh axis names, and the
host-concurrency contracts (shared-state guards + durability-ordering
edges) graftsync enforces.

The engine's determinism story rests on DOMAIN SEPARATION: the dropout,
straggler, and scheduler draws are each a pure function of
``(seed, domain, round_idx)`` on a counter-based generator, so the
three streams never alias each other and a resumed run replays all of
them bit-exactly (utils/faults, scheduler/policy). That only holds
while the domain tags stay DISTINCT — a collision silently correlates
two "independent" failure processes, the exact class of bug that is
invisible at runtime and catastrophic in a convergence study.

Before this registry the tags lived as inline hex literals in the
modules that drew from them; nothing enforced uniqueness, and a new
subsystem picking a tag had to grep for collisions by hand. Now:

  * every domain constant lives HERE, keyed by a name that documents
    its consumer;
  * uniqueness is asserted at import time (and, pure-AST, by graftlint
    rule GL009, which also flags inline hex literals inside
    ``fold_in``/``SeedSequence`` calls anywhere in the tree — new
    draws must route through this registry);
  * consumers import the tag by name, so the registry is the single
    place a reviewer audits the stream layout.

Deliberately dependency-free (stdlib only): `utils/faults` and
`scheduler/policy` import this at module load, and graftlint parses it
without executing anything.
"""
from __future__ import annotations

# name -> domain tag. Tags are arbitrary distinct integers; the hex
# spellings are mnemonic ("0D120" ~ Dropout, "51044" ~ SLOw, "5C4ED" ~
# SChED) and FROZEN — changing a value changes every historical run's
# fault/schedule replay, so tags may be added but never edited.
DOMAINS = {
    "dropout": 0x0D120,    # utils/faults.bernoulli_survivors
    "straggler": 0x51044,  # utils/faults.straggler_work_fractions
    "sampler": 0x5C4ED,    # scheduler/policy.ThroughputAwareSampler
    "poison": 0xBAD0D,     # utils/faults.poison_mask (value faults)
    "byzantine": 0xB42A1,  # utils/faults.byzantine_mask (adversaries)
    "dp": 0xD9A05,         # compress/dp_sketch per-round Gaussian noise
    "powersgd": 0x909D0,   # compress/powersgd fresh-client Q warm start
}

_values = list(DOMAINS.values())
assert len(set(_values)) == len(_values), (
    "PRNG domain collision in analysis/domains.DOMAINS: two streams "
    "sharing a tag are perfectly correlated")


def domain(name: str) -> int:
    """The registered domain tag for `name`; KeyError (with the known
    names listed) on a typo rather than a silent new stream."""
    try:
        return DOMAINS[name]
    except KeyError:
        raise KeyError(
            f"unknown PRNG domain {name!r}; registered: "
            f"{sorted(DOMAINS)} (add new streams to analysis/domains)"
        ) from None


# ---------------------------------------------------------------------------
# mesh-axis registry (ISSUE 8 satellite; enforced by graftlint GL010)
#
# The engine's sharding story names exactly two mesh axes: `clients`
# (the federated parallel axis every round program shards over) and
# `model` (optional tensor parallelism, innermost so its collectives
# ride the fastest ICI). Before this registry the names lived as
# string literals spread across parallel/ and federated/; a typo
# ("cleints") or an unregistered new axis produced a silently
# replicated spec — the layout bug class GSPMD propagation hides
# until a pod run reshards every dispatch. GL010 holds the line: an
# axis-name string literal in a sharding construction under parallel/
# or federated/ that is not a MESH_AXES value is a lint error, and the
# mesh constructors themselves build their axis_names from these
# constants. (ring_attention's `seq` axis is caller-named — it takes
# the axis as a parameter and registers no literal of its own.)

CLIENTS_AXIS = "clients"
MODEL_AXIS = "model"
MESH_AXES = (CLIENTS_AXIS, MODEL_AXIS)

assert len(set(MESH_AXES)) == len(MESH_AXES), (
    "duplicate axis name in analysis/domains.MESH_AXES")


# ---------------------------------------------------------------------------
# shared-state guard registry (ISSUE 14; enforced by graftsync SY001)
#
# The host control plane is thread-rich since PRs 10-13: the journal /
# checkpoint / spill bounded-queue writer threads, pipelined staging,
# and the per-thread trace rings all mutate state that another thread
# reads. The discipline — "this attribute is only touched under that
# lock" — lived in docstrings; this registry is the ONE place it is
# declared, and graftsync SY001 holds the line mechanically: a
# mutation of a registered `Class.attr` outside a `with self.<guard>:`
# block is an audit error, and an attribute the cross-thread scan
# proves shared (mutated both from a thread-entry function and from
# outside one) that is NOT registered is an error too — new shared
# state must be declared here with its guard, exactly like a new PRNG
# stream must be declared in DOMAINS.
#
# "Class.attr" -> guard lock attribute on the same instance.
SHARED_STATE = {
    # telemetry/trace.py — per-thread span rings, appended by every
    # producing thread (incl. the writer threads), drained by the
    # flush path
    "Tracer._rings": "_lock",
    "Tracer._dropped": "_lock",
    # federated/statestore.py — the spill writer commits to the tail
    # and retires pending entries while producers read/restore rows
    "TieredStateStore._tail": "_lock",
    "TieredStateStore._pending": "_lock",
    "TieredStateStore._warm": "_lock",
    # ISSUE 16 checksummed tiers: per-row CRCs are recorded by the
    # spill writer's commit and read/invalidated by the restore path's
    # verification; quarantine events are appended at verification
    # time and drained by the telemetry emitter
    "TieredStateStore._sums": "_lock",
    "TieredStateStore._quarantined": "_lock",
    # utils/checkpoint.py — the deferred writer failure is stored on
    # the writer thread and consumed (cleared) on the caller's thread
    "AsyncCheckpointWriter._exc": "_exc_lock",
}

assert all(g for g in SHARED_STATE.values()), (
    "every SHARED_STATE entry must name its guard lock attribute")


# ---------------------------------------------------------------------------
# durability-ordering registry (ISSUE 14; enforced by graftsync SY006)
#
# The control plane's crash-safety and resume-bit-exactness rest on a
# handful of happens-before edges between host calls — "the write-
# ahead journal flush runs before the dispatch that executes the
# plan", "the spill queue drains before the checkpoint payload reads
# the tail". Each edge below names one such contract as call-order
# DOMINANCE inside one registered function: every call of `after`
# must appear (in source order) after at least one call of `before`,
# and BOTH must be present — so a refactor that deletes or reorders a
# barrier turns the audit red instead of silently shipping a torn
# journal or a stale tail. Names are frozen (tests and README refer
# to them); edges may be added but never weakened in place.
ORDERING_EDGES = {
    # ISSUE 12 write-ahead contract: every sealed RoundPlan of a span
    # is durable before the span's dispatch executes it (the journal
    # is the authoritative decision log a takeover replays).
    "wal-flush-before-dispatch": {
        "path": "commefficient_tpu/federated/api.py",
        "function": "dispatch_rounds",
        "before": "_flush_write_ahead",
        "after": "with_retries",
        "why": "a plan executed before its journal line is durable "
               "cannot be replayed by a coordinator takeover",
    },
    # ISSUE 11 mid-spill contract: the checkpoint payload reads the
    # host tail only after every queued spill has committed to it.
    "spill-drain-before-checkpoint-payload": {
        "path": "commefficient_tpu/federated/statestore.py",
        "function": "checkpoint_rows",
        "before": "flush",
        "after": "get_many",
        "why": "a payload built from a tail with spills still in "
               "flight loses evicted client rows (error-feedback "
               "state) on resume",
    },
    # ISSUE 10 writer contract: the async checkpoint writer drains
    # before any SYNCHRONOUS save so the manifest rotates in order.
    "writer-drain-before-save-final": {
        "path": "commefficient_tpu/training/cv_train.py",
        "function": "main",
        "before": "drain_persistence",
        "after": "save_final",
        "why": "a final save overtaking queued rotating saves rotates "
               "the manifest out of order (resume picks a stale "
               "newest)",
    },
    "writer-drain-before-save-final-gpt2": {
        "path": "commefficient_tpu/training/gpt2_train.py",
        "function": "main",
        "before": "drain_persistence",
        "after": "save_final",
        "why": "same manifest-ordering contract as the CV driver",
    },
    # ISSUE 16 integrity contract: every host tail row is checksum-
    # verified (and, on mismatch, quarantined back to its init value)
    # BEFORE the restore scatter installs it in a device slot — the
    # verified read happens inside _rows_for, so the scatter dispatch
    # must dominate it in source order. A reorder here would feed a
    # bit-rotted memmap row straight into the next round's gather.
    "checksum-verify-before-restore": {
        "path": "commefficient_tpu/federated/statestore.py",
        "function": "_restore_chunk",
        "before": "_rows_for",
        "after": "scatter",
        "why": "a restore that scatters tail rows before their "
               "checksum verification installs silently corrupted "
               "error-feedback state on the device",
    },
    # ISSUE 11 WAR hazard: the spill gather's device barrier must run
    # before its rows are handed to the writer — the donating restore
    # scatter that follows overwrites the gathered slots in place, a
    # write jax does not order against the dependency-free gather.
    "gather-barrier-before-donated-scatter": {
        "path": "commefficient_tpu/federated/statestore.py",
        "function": "_spill_chunk",
        "before": "block_until_ready",
        "after": "submit",
        "why": "without the barrier the donated scatter's in-place "
               "write races the spill gather's read of the same "
               "buffer (observed as heap corruption / garbage rows)",
    },
}

for _name, _edge in ORDERING_EDGES.items():
    assert {"path", "function", "before", "after", "why"} <= set(_edge), (
        f"ORDERING_EDGES[{_name!r}] is missing a required field")
    assert _edge["before"] != _edge["after"], (
        f"ORDERING_EDGES[{_name!r}]: before and after name the same "
        "call — the edge is vacuous")


# ---------------------------------------------------------------------------
# precision-seam registry (ISSUE 18; enforced by graftnum NU002)
#
# The engine's numeric contract is f32 master state end to end; every
# place a value deliberately LOSES precision — the PR-6 sketch-table
# wire quantization, the flash-attention output cast back to the
# activation dtype — is a SEAM the convergence analysis must account
# for (the quantization rounding rides the error-feedback residual,
# PERF.md round 6). Before this registry those seams lived as .astype
# calls spread through ops/; nothing stopped a refactor from adding a
# new silent downcast on a path the analysis assumes exact. graftnum
# NU002 holds the line at the PROGRAM level: every lossy
# `convert_element_type` in a traced round program must match a
# (src, dst) pair registered here, and an unregistered downcast is an
# audit error — new seams must be declared (and their residual story
# told in `why`) before they ship. Upcasts and exact index casts
# (float -> int32/int64) are not seams and need no entry.
#
# Dtype names are the str() of the jax/numpy dtype ("float32",
# "bfloat16", "int8"), kept as strings so this module stays
# stdlib-only.
PRECISION_SEAMS = {
    "sketch-wire-bf16": {
        "src": "float32", "dst": "bfloat16",
        "path": "commefficient_tpu/ops/kernels/quant.py",
        "function": "quantize_table",
        "why": "the bf16 sketch-table wire format (PR 6): the rounding "
               "is bounded per-cell and lands in the error-feedback "
               "residual, which FetchSGD re-transmits",
    },
    "sketch-wire-int8": {
        "src": "float32", "dst": "int8",
        "path": "commefficient_tpu/ops/kernels/quant.py",
        "function": "quantize_table",
        "why": "the int8 symmetric sketch-table wire format (PR 6): "
               "per-row scale rides beside the payload, quantization "
               "noise lands in the error-feedback residual",
    },
    "attention-output-cast": {
        "src": "float32", "dst": "bfloat16",
        "path": "commefficient_tpu/ops/attention.py",
        "function": "flash_attention",
        "why": "the flash-attention f32 accumulator is cast back to "
               "the bf16 activation dtype on exit — the standard "
               "mixed-precision activation seam, outside the "
               "error-feedback loop",
    },
}

for _name, _seam in PRECISION_SEAMS.items():
    assert {"src", "dst", "path", "function", "why"} <= set(_seam), (
        f"PRECISION_SEAMS[{_name!r}] is missing a required field")
    assert _seam["src"] != _seam["dst"], (
        f"PRECISION_SEAMS[{_name!r}]: src and dst name the same dtype "
        "— the seam is vacuous")


def precision_seam_pairs() -> set:
    """The registered (src dtype name, dst dtype name) pairs — what
    graftnum NU002 matches traced convert_element_type eqns against."""
    return {(s["src"], s["dst"]) for s in PRECISION_SEAMS.values()}


# ---------------------------------------------------------------------------
# controller wire-field registry (ISSUE 20; enforced by graftlint GL014)
#
# The control/ subsystem's replay contract rides each controller's
# adjusted value on a named RoundPlan wire field ("controls" payload
# key, see parallel/plantransport.serialize_plan): the journaled plan
# stream is the authoritative adjustment log a takeover replays, so a
# wire-field collision means two controllers silently overwrite each
# other's decisions on the wire — invisible at runtime, catastrophic
# on a resume. This registry is the ONE place wire fields are claimed,
# mirroring the DOMAINS discipline: controller name -> wire field,
# uniqueness asserted at import time and re-proven pure-AST by
# graftlint GL014 (which also flags any `WIRE_FIELD = "..."` class
# attribute in the tree whose literal is not registered here). Names
# and fields are FROZEN once shipped — a renamed field orphans every
# historical journal's plan stream.
CONTROL_FIELDS = {
    "screen_adapt": "screen_mult",      # control/screen (ISSUE 17)
    "speed_match": "speed_ratio",       # control/speed
    "span_cadence": "scan_span",        # control/span
    "staleness_decay": "staleness_decay",  # control/staleness
}

_fields = list(CONTROL_FIELDS.values())
assert len(set(_fields)) == len(_fields), (
    "controller wire-field collision in analysis/domains."
    "CONTROL_FIELDS: two controllers sharing a plan wire field "
    "silently overwrite each other's journaled adjustments")


def control_field(name: str) -> str:
    """The registered plan wire field for controller `name`; KeyError
    (with the known names listed) on a typo rather than a silent new
    wire field."""
    try:
        return CONTROL_FIELDS[name]
    except KeyError:
        raise KeyError(
            f"unknown controller {name!r}; registered: "
            f"{sorted(CONTROL_FIELDS)} (add new controllers to "
            "analysis/domains.CONTROL_FIELDS)"
        ) from None

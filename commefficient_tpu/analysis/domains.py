"""Central registries: PRNG-domain tags and mesh axis names.

The engine's determinism story rests on DOMAIN SEPARATION: the dropout,
straggler, and scheduler draws are each a pure function of
``(seed, domain, round_idx)`` on a counter-based generator, so the
three streams never alias each other and a resumed run replays all of
them bit-exactly (utils/faults, scheduler/policy). That only holds
while the domain tags stay DISTINCT — a collision silently correlates
two "independent" failure processes, the exact class of bug that is
invisible at runtime and catastrophic in a convergence study.

Before this registry the tags lived as inline hex literals in the
modules that drew from them; nothing enforced uniqueness, and a new
subsystem picking a tag had to grep for collisions by hand. Now:

  * every domain constant lives HERE, keyed by a name that documents
    its consumer;
  * uniqueness is asserted at import time (and, pure-AST, by graftlint
    rule GL009, which also flags inline hex literals inside
    ``fold_in``/``SeedSequence`` calls anywhere in the tree — new
    draws must route through this registry);
  * consumers import the tag by name, so the registry is the single
    place a reviewer audits the stream layout.

Deliberately dependency-free (stdlib only): `utils/faults` and
`scheduler/policy` import this at module load, and graftlint parses it
without executing anything.
"""
from __future__ import annotations

# name -> domain tag. Tags are arbitrary distinct integers; the hex
# spellings are mnemonic ("0D120" ~ Dropout, "51044" ~ SLOw, "5C4ED" ~
# SChED) and FROZEN — changing a value changes every historical run's
# fault/schedule replay, so tags may be added but never edited.
DOMAINS = {
    "dropout": 0x0D120,    # utils/faults.bernoulli_survivors
    "straggler": 0x51044,  # utils/faults.straggler_work_fractions
    "sampler": 0x5C4ED,    # scheduler/policy.ThroughputAwareSampler
}

_values = list(DOMAINS.values())
assert len(set(_values)) == len(_values), (
    "PRNG domain collision in analysis/domains.DOMAINS: two streams "
    "sharing a tag are perfectly correlated")


def domain(name: str) -> int:
    """The registered domain tag for `name`; KeyError (with the known
    names listed) on a typo rather than a silent new stream."""
    try:
        return DOMAINS[name]
    except KeyError:
        raise KeyError(
            f"unknown PRNG domain {name!r}; registered: "
            f"{sorted(DOMAINS)} (add new streams to analysis/domains)"
        ) from None


# ---------------------------------------------------------------------------
# mesh-axis registry (ISSUE 8 satellite; enforced by graftlint GL010)
#
# The engine's sharding story names exactly two mesh axes: `clients`
# (the federated parallel axis every round program shards over) and
# `model` (optional tensor parallelism, innermost so its collectives
# ride the fastest ICI). Before this registry the names lived as
# string literals spread across parallel/ and federated/; a typo
# ("cleints") or an unregistered new axis produced a silently
# replicated spec — the layout bug class GSPMD propagation hides
# until a pod run reshards every dispatch. GL010 holds the line: an
# axis-name string literal in a sharding construction under parallel/
# or federated/ that is not a MESH_AXES value is a lint error, and the
# mesh constructors themselves build their axis_names from these
# constants. (ring_attention's `seq` axis is caller-named — it takes
# the axis as a parameter and registers no literal of its own.)

CLIENTS_AXIS = "clients"
MODEL_AXIS = "model"
MESH_AXES = (CLIENTS_AXIS, MODEL_AXIS)

assert len(set(MESH_AXES)) == len(MESH_AXES), (
    "duplicate axis name in analysis/domains.MESH_AXES")

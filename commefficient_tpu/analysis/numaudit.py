"""graftnum: jaxpr-level numerics & determinism auditor (ISSUE 18).

graftaudit prices WHAT the round programs compute (FLOPs/HBM),
graftmesh WHERE the bytes move, graftsync HOW the host threads
interleave. This module is the FIFTH analysis tier: it walks the same
traced ClosedJaxprs with a dtype/finiteness dataflow lattice and
checks the contracts FetchSGD's convergence argument actually rests
on — that error feedback accumulates an exact f32 residual, that a
poisoned client's NaN/inf cannot leak through the admission
arithmetic, that every zero-survivor denominator is guarded, and that
a crash->resume replay re-dispatches bit-identical programs:

  NU001  NaN-unsafe mask arithmetic: a possibly-non-finite value
         combined with a 0/1 mask via MULTIPLY instead of
         select/where — the PR-16 bug class (NaN * 0 == NaN, so
         `t * mask` propagates a poisoned update into the masked-out
         lanes; `where(admitted > 0, t, 0)` does not). Finiteness
         provenance is tracked per value from the in-program
         injection sites (the poison/attack `where(flag, inf, t)`
         selects, the nanmedian NaN sentinel, unproven divisions)
         through aggregation and error feedback.
  NU002  precision-change audit: every lossy `convert_element_type`
         (float narrowing, float -> int8/int16 quantization) must
         match a (src, dst) seam registered in
         analysis/domains.PRECISION_SEAMS — the PR-6 quantize/
         dequantize pair, the flash-attention output cast — so a new
         silent downcast on a path the analysis assumes exact is an
         audit error, not a convergence mystery. The error-feedback
         residual operands themselves (any program input/output whose
         leaf name contains "err") are asserted f32-or-wider.
  NU003  unguarded division/rsqrt/log/sqrt: denominators and
         rsqrt/log arguments must be provably bounded away from zero
         through the lattice, sqrt arguments provably non-negative —
         the eps-max (`maximum(total, 1.0)`), where-guard, and
         survivor-count+1 idioms all prove; a raw data-dependent
         denominator does not. Zero-survivor safety becomes
         mechanical instead of per-PR vigilance.
  NU004  replay-determinism: primitives whose result is not fixed by
         any spec inside programs covered by the crash->resume
         bit-exactness contract — scatters in PROMISE_IN_BOUNDS mode
         (out-of-bounds behavior undefined), `approx_max_k` whose
         recall_target is not the pinned value, unstable sorts (tie
         order unspecified). Cross-shard psum reassociation is NOT
         flagged but PRICED: costmodel.reassociation_ulp_bound gives
         each program a worst-case ulp divergence integer, diffed
         exact-match in graftnum.baseline.json like FLOPs/HBM.
  NU005  ulp-bound drift vs graftnum.baseline.json (new / stale /
         moved program) — the baseline-drift rule, exit code 2.

The lattice is an abstract interpretation over the jaxpr: per value
it tracks {finite, nonneg, nonzero (bounded away from zero), mask
(0/1 indicator)}. Program inputs are assumed finite — non-finiteness
is tracked from where the PROGRAM introduces it (non-finite constants
routed through a select, divisions with unproven denominators).
`select_n` is the sanctioned guard point: its output is
finite-by-contract (that the predicate is semantically sufficient is
the runtime NumericSanitizer's job — the static rule enforces that
the guard IS a select, which is exactly the PR-16 contract), UNLESS a
branch is a non-finite CONSTANT, which marks an injection site (the
poison `where(flag, inf, t)`, the nanmedian sentinel) and starts
provenance instead of laundering it.

Shares graftaudit's machinery end to end: the audit-config registry
and tracers (audit.audit_configs/build_workload/trace_variant/
trace_state_motion, plus the scanned span via round.
stack_batch_for_span), the AuditBaseline exact-match diff (the ulp
block parameterizes COST_KEY/COST_FIELDS exactly like graftmesh's
byte report), the 0 clean / 1 violations / 2 baseline-drift exit
contract, and the journaled sha256 report digest
(`num_audit_digest`, bit-identical across runs).

Import discipline: jax is imported LAZILY inside the functions that
trace; `main` pins JAX_PLATFORMS=cpu first, so importing this module
stays jax-free (console-script resolution, graftlint's pure-AST pass).
"""
from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from commefficient_tpu.analysis.audit import (
    AUDIT_GEOMETRY, AUDIT_POPULATION, AuditBaseline, AuditFinding,
    audit_configs, build_workload, exit_code, iter_eqns,
    trace_state_motion, trace_variant, _dtype_of, _leaf_names,
    _shape_of,
)
from commefficient_tpu.analysis.costmodel import (
    reassociation_ulp_bound, sub_jaxprs,
)
from commefficient_tpu.analysis.domains import precision_seam_pairs

NUM_RULE_DOCS = {
    "NU001": "NaN-unsafe mask arithmetic: possibly-non-finite value "
             "multiplied by a 0/1 mask (NaN*0 == NaN — the PR-16 "
             "class; use jnp.where/select)",
    "NU002": "unregistered precision downcast (not in analysis/"
             "domains.PRECISION_SEAMS), or a sub-f32 error-feedback "
             "residual operand",
    "NU003": "unguarded division/rsqrt/log/sqrt: argument not "
             "provably bounded away from zero (or non-negative, for "
             "sqrt) through the lattice",
    "NU004": "replay-nondeterministic primitive under the "
             "crash->resume bit-exactness contract (promise_in_bounds "
             "scatter, unpinned approx_max_k recall_target, unstable "
             "sort)",
    "NU005": "worst-case ulp-bound drift vs graftnum.baseline.json "
             "(new / stale / moved program)",
}

# NU004: the one recall_target the replay contract pins (jax's
# default; every shipped approx_max_k call site inherits it). A
# different value in a traced program means someone changed the
# selection accuracy without re-pricing the estimate residual.
PINNED_RECALL_TARGETS = (0.95,)

# the participant counts the ulp bound prices cross-shard reassociation
# at: the tier-1 simulated mesh's 8-device clients axis (the audit
# itself traces on a 1-device mesh so per-shard shapes stay
# host-count-independent — the bound prices the DECLARED deployment
# axis, not the tracing mesh). An axis not listed here prices at the
# costmodel default (2) so a new axis is never silently free.
ULP_AXIS_SIZES = {"clients": 8, "model": 2}

# the scanned-span length graftnum traces (matches the mesh tier's
# span: long enough that the scan carry is live, short enough to
# trace in milliseconds)
SPAN_LEN = 2


# ---------------------------------------------------------------------------
# the dtype/finiteness lattice


@dataclasses.dataclass(frozen=True)
class Absval:
    """Abstract value: what the lattice can PROVE about one jaxpr
    value. Each flag is evidence, not truth — False means "not
    proven", never "proven false".

    finite:   cannot be NaN/inf.
    nonneg:   no negative finite values (NaN lanes allowed — squares
              and abs are nonneg even of possibly-NaN inputs, which
              is what the sqrt rule needs).
    nonzero:  finite AND bounded away from zero — safe as a
              denominator / rsqrt / log argument.
    mask:     a {0, 1} indicator (comparison result, is_finite,
              bool cast, product of masks).
    ptrue / pfalse: a predicate provably all-True / all-False —
              abstract constant folding, so a DEFENSIVE NaN select
              (jnp.median's `where(any(x != x), nan, x)` over a
              proven-finite x) resolves to its live branch instead of
              reading as an injection site.
    const_nonfinite: a non-finite CONSTANT (inf/nan literal, possibly
              broadcast/reshaped) — the select_n injection-site
              marker; ordinary computed non-finiteness never sets it.
    src:      human-readable provenance of the first non-finite
              source, carried for NU001 messages.
    """
    finite: bool = True
    nonneg: bool = False
    nonzero: bool = False
    mask: bool = False
    ptrue: bool = False
    pfalse: bool = False
    const_nonfinite: bool = False
    src: str = ""


_DEFAULT = Absval()
_BOOL = Absval(finite=True, nonneg=True, nonzero=False, mask=True)
_INT = Absval(finite=True)


def _join(*vals: Absval) -> Absval:
    """Lattice meet over control-flow joins: a property holds of the
    join only if it holds of every incoming value."""
    if not vals:
        return _DEFAULT
    return Absval(
        finite=all(v.finite for v in vals),
        nonneg=all(v.nonneg for v in vals),
        nonzero=all(v.nonzero for v in vals),
        mask=all(v.mask for v in vals),
        ptrue=all(v.ptrue for v in vals),
        pfalse=all(v.pfalse for v in vals),
        const_nonfinite=any(v.const_nonfinite for v in vals),
        src=next((v.src for v in vals if v.src), ""))


def _const_absval(val) -> Absval:
    """Absval of a concrete constant (jaxpr Literal / closed const)."""
    import numpy as np
    try:
        arr = np.asarray(val)
    except (TypeError, ValueError):
        # an abstract/token const with no concrete value
        return _DEFAULT
    kind = arr.dtype.kind
    if kind == "b":
        return dataclasses.replace(
            _BOOL,
            ptrue=bool(arr.all()) if arr.size else False,
            pfalse=bool((~arr).all()) if arr.size else False)
    if kind in "iu":
        return Absval(
            finite=True,
            nonneg=bool((arr >= 0).all()) if arr.size else True,
            nonzero=bool((arr != 0).all()) if arr.size else False,
            mask=bool(np.isin(arr, (0, 1)).all()) if arr.size else False)
    if kind in "fV":  # V: bfloat16 registers as void on some numpy
        try:
            farr = arr.astype(np.float64)
        except (TypeError, ValueError):
            return _DEFAULT
        if not farr.size:
            return Absval(finite=True)
        fin = bool(np.isfinite(farr).all())
        return Absval(
            finite=fin,
            nonneg=fin and bool((farr >= 0).all()),
            nonzero=fin and bool((np.abs(farr) > 0).all()),
            mask=fin and bool(np.isin(farr, (0.0, 1.0)).all()),
            const_nonfinite=not fin,
            src="" if fin else "a non-finite constant (inf/nan "
                               "literal)")
    return _DEFAULT


def _is_float_dtype(dt) -> bool:
    return str(dt).startswith(("float", "bfloat"))


def _site(eqn) -> str:
    """`path:line (function)` of the deepest in-repo frame that traced
    this eqn — so a finding lands on the source idiom, not the jaxpr.
    Best-effort: tracing through library combinators can leave no
    user frame."""
    tb = getattr(getattr(eqn, "source_info", None), "traceback", None)
    if tb is None:
        return "<no source info>"
    best = None
    for fr in tb.frames:
        fn = fr.file_name.replace("\\", "/")
        if "commefficient_tpu/" in fn and "/analysis/" not in fn:
            best = fr
            break  # frames run innermost-out: first hit is deepest
    if best is None:
        return "<no in-repo frame>"
    short = best.file_name.replace("\\", "/")
    short = short[short.rindex("commefficient_tpu/"):]
    return f"{short}:{best.line_num} ({best.function_name})"


# primitives that only move/reshape data: every lattice property of
# the (single data) operand survives
_SHAPE_ONLY = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "squeeze",
    "expand_dims", "rev", "copy", "stop_gradient", "slice",
    "device_put", "sharding_constraint", "convert_element_type",
    "real", "reduce_precision",
})

# gather-class: output elements are a subset of operand 0's elements
_GATHER_LIKE = frozenset({"gather", "dynamic_slice", "take"})

# bool-producing comparisons / predicates -> mask
_MASK_PRIMS = frozenset({
    "eq", "ne", "gt", "lt", "ge", "le", "is_finite", "and", "or",
    "not", "xor", "reduce_and", "reduce_or", "eq_to", "lt_to",
})

_SCATTER_PRIMS = frozenset({
    "scatter", "scatter-add", "scatter-mul", "scatter-min",
    "scatter-max", "scatter-apply",
})


class _LatticeAuditor:
    """One program's NU001/NU003 walk: abstract-interpret the jaxpr,
    recording findings at the unsafe-combination sites."""

    def __init__(self, program: str):
        self.program = program
        self.findings: List[AuditFinding] = []

    # -------------------- environment ---------------------------------
    def _read(self, env: Dict[int, Absval], v) -> Absval:
        val = getattr(v, "val", None)
        if val is not None and not hasattr(v, "count"):
            # a Literal: carries its concrete value
            return _const_absval(val)
        return env.get(id(v), _DEFAULT)

    # -------------------- drivers -------------------------------------
    def run(self, closed) -> None:
        jaxpr = closed.jaxpr
        env: Dict[int, Absval] = {}
        for cv, const in zip(jaxpr.constvars, closed.consts):
            env[id(cv)] = _const_absval(const)
        # program inputs are assumed finite: non-finiteness is tracked
        # from where the program INTRODUCES it (module docstring)
        for v in jaxpr.invars:
            env[id(v)] = Absval(finite=True)
        self._analyze(jaxpr, env, collect=True)

    def _analyze(self, jx, env: Dict[int, Absval],
                 collect: bool) -> None:
        for eqn in jx.eqns:
            subs = [s for v in eqn.params.values()
                    for s in sub_jaxprs(v)]
            if subs:
                self._container(eqn, subs, env, collect)
            else:
                outs = self._transfer(
                    eqn, [self._read(env, v) for v in eqn.invars],
                    collect)
                for ov, oval in zip(eqn.outvars, outs):
                    env[id(ov)] = oval

    def _container(self, eqn, subs, env: Dict[int, Absval],
                   collect: bool) -> None:
        """Propagate through a sub-jaxpr-bearing eqn (pjit, scan,
        while, cond, shard_map, pallas_call, custom_*): seed inner
        invars by positional tail alignment (audit.population_scan's
        convention — cond's leading predicate and scan's layout both
        align from the end), run each sub, join outvars across subs.
        scan/while bodies run twice so properties that only break on
        the second trip (a carry degrading) are not over-proven;
        findings collect on the final pass only."""
        loops = eqn.primitive.name in ("scan", "while")
        passes = 2 if loops else 1
        out_joined: Dict[int, List[Absval]] = {}
        for p in range(passes):
            final = p == passes - 1
            out_joined.clear()
            for s in subs:
                sub_env: Dict[int, Absval] = dict(env)
                n_in = min(len(eqn.invars), len(s.invars))
                for ev, sv in zip(eqn.invars[-n_in:],
                                  s.invars[-n_in:]):
                    sub_env[id(sv)] = self._read(env, ev)
                self._analyze(s, sub_env, collect and final)
                n_out = min(len(eqn.outvars), len(s.outvars))
                for ev, sv in zip(eqn.outvars[-n_out:],
                                  s.outvars[-n_out:]):
                    out_joined.setdefault(id(ev), []).append(
                        sub_env.get(id(sv), _DEFAULT))
            if loops and passes > 1 and p == 0:
                # feed the first pass's outputs back in as the next
                # pass's carry seeds (joined with the initial values)
                for s in subs:
                    n_out = min(len(eqn.outvars), len(s.outvars))
                    for ev, sv in zip(eqn.outvars[-n_out:],
                                      s.outvars[-n_out:]):
                        prev = env.get(id(ev))
                        joined = _join(*out_joined[id(ev)])
                        env[id(ev)] = (_join(prev, joined)
                                       if prev is not None else joined)
        for ev in eqn.outvars:
            vals = out_joined.get(id(ev))
            # const_nonfinite never crosses a container boundary: the
            # select-injection marker is local to the eqn stream that
            # owns the literal
            joined = (_join(*vals) if vals else _DEFAULT)
            env[id(ev)] = dataclasses.replace(joined,
                                              const_nonfinite=False)

    # -------------------- findings ------------------------------------
    def _hit(self, rule: str, eqn, message: str) -> None:
        self.findings.append(AuditFinding(
            self.program, rule, message + " [at " + _site(eqn) + "]"))

    # -------------------- transfer ------------------------------------
    def _transfer(self, eqn, ins: List[Absval],
                  collect: bool) -> List[Absval]:
        name = eqn.primitive.name
        n_out = len(eqn.outvars)

        if name in _MASK_PRIMS:
            # abstract predicate folding (Absval docstring): enough
            # boolean algebra to prove jnp.median's defensive
            # `any(x != x)` dead over a proven-finite x
            same = (len(eqn.invars) == 2
                    and eqn.invars[0] is eqn.invars[1])
            a, b = (ins + [_BOOL, _BOOL])[:2]
            out = _BOOL
            if name == "ne" and same and a.finite:
                out = dataclasses.replace(_BOOL, pfalse=True)
            elif name == "eq" and same and a.finite:
                out = dataclasses.replace(_BOOL, ptrue=True)
            elif name == "is_finite" and a.finite:
                out = dataclasses.replace(_BOOL, ptrue=True)
            elif name == "not":
                out = dataclasses.replace(_BOOL, ptrue=a.pfalse,
                                          pfalse=a.ptrue)
            elif name == "and":
                out = dataclasses.replace(
                    _BOOL, ptrue=a.ptrue and b.ptrue,
                    pfalse=a.pfalse or b.pfalse)
            elif name == "or":
                out = dataclasses.replace(
                    _BOOL, ptrue=a.ptrue or b.ptrue,
                    pfalse=a.pfalse and b.pfalse)
            elif name in ("reduce_or", "reduce_and"):
                out = dataclasses.replace(_BOOL, ptrue=a.ptrue,
                                          pfalse=a.pfalse)
            return [out] * n_out

        if name in _SHAPE_ONLY:
            base = ins[0] if ins else _DEFAULT
            if name == "convert_element_type":
                src = _dtype_of(eqn.invars[0]) if eqn.invars else None
                dst = eqn.params.get("new_dtype")
                if (src is not None and not _is_float_dtype(src)
                        and _is_float_dtype(dst)):
                    # int/bool -> float: exact, and ints are finite
                    base = dataclasses.replace(base, finite=True)
                if dst is not None and not _is_float_dtype(dst):
                    # -> int/bool: whatever it was, it is finite now
                    base = dataclasses.replace(base, finite=True,
                                               const_nonfinite=False)
            return [base] * n_out

        if name in _GATHER_LIKE:
            # a subset of operand 0's elements (indices are operand 1+)
            base = ins[0] if ins else _DEFAULT
            return [dataclasses.replace(base, const_nonfinite=False)
                    ] * n_out

        if name == "select_n":
            pred, branches = ins[0], ins[1:]
            # predicate folding first: a select whose predicate is
            # proven constant IS its live branch — the defensive
            # library NaN select over proven-finite data resolves
            # here instead of reading as an injection
            if branches and pred.pfalse:
                return [branches[0]] * n_out
            if len(branches) == 2 and pred.ptrue:
                return [branches[1]] * n_out
            injected = [b for b in branches if b.const_nonfinite]
            if injected:
                return [Absval(
                    finite=False,
                    src="a non-finite constant routed through "
                        "select/where (a poison/attack injection or "
                        "NaN-sentinel site)")] * n_out
            # the sanctioned guard point: finite-by-contract (module
            # docstring); the other properties must hold of every
            # branch
            j = _join(*branches) if branches else _DEFAULT
            return [dataclasses.replace(j, finite=True,
                                        const_nonfinite=False)] * n_out

        if name == "mul":
            a, b = (ins + [_DEFAULT, _DEFAULT])[:2]
            if collect:
                pairs = ((a, eqn.invars[0], b), (b, eqn.invars[-1], a))
                for m, mvar, v in pairs:
                    # a scalar {0,1} factor (a literal 1.0 scale, a
                    # traced enable flag) is not mask ARITHMETIC —
                    # the PR-16 class is a per-lane indicator array
                    if (m.mask and not v.finite
                            and len(_shape_of(mvar) or ()) >= 1):
                        self._hit("NU001", eqn, (
                            "possibly-non-finite value ("
                            + (v.src or "unproven finiteness")
                            + ") multiplied by a 0/1 mask at `mul` "
                            "over " + str(_shape_of(eqn.outvars[0]))
                            + ": NaN*0 == NaN, so the masked-OUT "
                            "lanes still propagate the poison — use "
                            "jnp.where(mask > 0, value, 0) (the "
                            "PR-16 admission idiom), which this "
                            "audit treats as the guard point"))
                        break
            same = (len(eqn.invars) == 2
                    and eqn.invars[0] is eqn.invars[1])
            return [Absval(
                finite=a.finite and b.finite,
                nonneg=same or (a.nonneg and b.nonneg),
                nonzero=a.nonzero and b.nonzero,
                mask=a.mask and b.mask,
                src=a.src or b.src)] * n_out

        if name == "div":
            num, den = (ins + [_DEFAULT, _DEFAULT])[:2]
            den_dt = (_dtype_of(eqn.invars[1])
                      if len(eqn.invars) > 1 else None)
            if (collect and den_dt is not None
                    and _is_float_dtype(den_dt) and not den.nonzero):
                self._hit("NU003", eqn, (
                    "`div` denominator over "
                    + str(_shape_of(eqn.invars[1]))
                    + " is not provably bounded away from zero: a "
                    "zero-survivor round (or a poisoned count) makes "
                    "this inf/NaN — guard with jnp.maximum(denom, "
                    "eps), a survivor-count+1, or a where-guard"))
            return [Absval(
                finite=num.finite and den.nonzero,
                nonneg=num.nonneg and den.nonneg,
                nonzero=num.nonzero and den.nonzero,
                src=num.src or den.src
                or ("" if den.nonzero else
                    "a division with an unproven denominator"))
            ] * n_out

        if name == "sqrt":
            a = ins[0] if ins else _DEFAULT
            dt = _dtype_of(eqn.invars[0]) if eqn.invars else None
            if (collect and dt is not None and _is_float_dtype(dt)
                    and not a.nonneg):
                self._hit("NU003", eqn, (
                    "`sqrt` argument over "
                    + str(_shape_of(eqn.invars[0]))
                    + " is not provably non-negative: a negative "
                    "lane is a silent NaN — square/abs the operand "
                    "or clamp at 0"))
            return [Absval(finite=a.finite and a.nonneg, nonneg=True,
                           nonzero=a.nonzero and a.nonneg,
                           src=a.src)] * n_out

        if name in ("rsqrt", "log", "log1p"):
            a = ins[0] if ins else _DEFAULT
            dt = _dtype_of(eqn.invars[0]) if eqn.invars else None
            positive = a.nonneg and a.nonzero
            # log1p's domain is x > -1; a proven-nonneg argument is
            # enough for it
            ok = a.nonneg if name == "log1p" else positive
            if (collect and dt is not None and _is_float_dtype(dt)
                    and not ok):
                self._hit("NU003", eqn, (
                    "`" + name + "` argument over "
                    + str(_shape_of(eqn.invars[0]))
                    + " is not provably bounded away from zero: "
                    "guard with jnp.maximum(x, eps) before the "
                    "reciprocal/log"))
            return [Absval(finite=a.finite and ok,
                           nonneg=name == "rsqrt",
                           nonzero=name == "rsqrt" and ok,
                           src=a.src)] * n_out

        if name == "integer_pow":
            a = ins[0] if ins else _DEFAULT
            y = int(eqn.params.get("y", 1) or 1)
            if y < 0:
                # x**-n is a division: same proof obligation
                dt = _dtype_of(eqn.invars[0]) if eqn.invars else None
                if (collect and dt is not None and _is_float_dtype(dt)
                        and not a.nonzero):
                    self._hit("NU003", eqn, (
                        "`integer_pow` with negative exponent "
                        + str(y) + " over "
                        + str(_shape_of(eqn.invars[0]))
                        + ": a reciprocal of a value not provably "
                        "bounded away from zero"))
                return [Absval(finite=a.finite and a.nonzero,
                               nonneg=y % 2 == 0 or a.nonneg,
                               nonzero=a.nonzero, src=a.src)] * n_out
            return [Absval(finite=a.finite,
                           nonneg=y % 2 == 0 or a.nonneg,
                           nonzero=a.nonzero and y > 0,
                           src=a.src)] * n_out

        if name in ("abs", "square"):
            a = ins[0] if ins else _DEFAULT
            return [Absval(finite=a.finite, nonneg=True,
                           nonzero=a.nonzero, src=a.src)] * n_out

        if name in ("exp", "exp2", "logistic"):
            a = ins[0] if ins else _DEFAULT
            return [Absval(finite=a.finite, nonneg=True,
                           nonzero=a.finite, src=a.src)] * n_out

        if name == "add":
            a, b = (ins + [_DEFAULT, _DEFAULT])[:2]
            fin = a.finite and b.finite
            return [Absval(
                finite=fin, nonneg=a.nonneg and b.nonneg,
                nonzero=fin and ((a.nonzero and a.nonneg and b.nonneg)
                                 or (b.nonzero and b.nonneg
                                     and a.nonneg)),
                src=a.src or b.src)] * n_out

        if name == "sub":
            a, b = (ins + [_DEFAULT, _DEFAULT])[:2]
            return [Absval(finite=a.finite and b.finite,
                           src=a.src or b.src)] * n_out

        if name == "max":
            a, b = (ins + [_DEFAULT, _DEFAULT])[:2]
            fin = a.finite and b.finite
            pos_a = a.nonneg and a.nonzero
            pos_b = b.nonneg and b.nonzero
            return [Absval(
                finite=fin, nonneg=a.nonneg or b.nonneg,
                nonzero=fin and (pos_a or pos_b
                                 or (a.nonzero and b.nonzero)),
                src=a.src or b.src)] * n_out

        if name == "min":
            a, b = (ins + [_DEFAULT, _DEFAULT])[:2]
            fin = a.finite and b.finite
            return [Absval(finite=fin,
                           nonneg=a.nonneg and b.nonneg,
                           nonzero=fin and a.nonzero and b.nonzero,
                           src=a.src or b.src)] * n_out

        if name == "clamp":
            lo, x, hi = (ins + [_DEFAULT] * 3)[:3]
            fin = lo.finite and x.finite and hi.finite
            return [Absval(finite=fin, nonneg=lo.nonneg,
                           nonzero=fin and lo.nonneg and lo.nonzero,
                           src=x.src)] * n_out

        if name == "neg":
            a = ins[0] if ins else _DEFAULT
            return [Absval(finite=a.finite, nonzero=a.nonzero,
                           src=a.src)] * n_out

        if name in ("reduce_sum", "cumsum", "cumlogsumexp"):
            a = ins[0] if ins else _DEFAULT
            return [Absval(finite=a.finite, nonneg=a.nonneg,
                           src=a.src)] * n_out

        if name in ("reduce_max", "reduce_min", "cummax", "cummin"):
            a = ins[0] if ins else _DEFAULT
            return [Absval(finite=a.finite, nonneg=a.nonneg,
                           nonzero=(a.finite and a.nonzero
                                    and a.nonneg),
                           src=a.src)] * n_out

        if name in ("reduce_prod", "cumprod"):
            a = ins[0] if ins else _DEFAULT
            return [Absval(finite=a.finite, nonneg=a.nonneg,
                           src=a.src)] * n_out

        if name in ("psum", "psum2", "psum_invariant"):
            a = _join(*ins) if ins else _DEFAULT
            return [Absval(finite=a.finite, nonneg=a.nonneg,
                           nonzero=(a.finite and a.nonzero
                                    and a.nonneg),
                           src=a.src)] * n_out

        if name in ("all_gather", "ppermute", "all_to_all",
                    "pbroadcast", "pmax", "pmin"):
            a = _join(*ins) if ins else _DEFAULT
            return [dataclasses.replace(a, const_nonfinite=False)
                    ] * n_out

        if name == "pad":
            a, pv = (ins + [_DEFAULT, _DEFAULT])[:2]
            return [_join(a, pv)] * n_out

        if name in ("concatenate", "dynamic_update_slice", "scatter",
                    "scatter-add", "select_and_scatter_add"):
            data = [v for v, iv in zip(ins, eqn.invars)
                    if _is_float_dtype(_dtype_of(iv))
                    or str(_dtype_of(iv)) == "bool"] or ins
            j = _join(*data) if data else _DEFAULT
            return [dataclasses.replace(j, const_nonfinite=False)
                    ] * n_out

        if name == "sort":
            # multi-operand sort: output i is a permutation of input i
            return [dataclasses.replace(v, const_nonfinite=False)
                    for v in (ins + [_DEFAULT] * n_out)[:n_out]]

        if name in ("top_k", "approx_top_k"):
            a = ins[0] if ins else _DEFAULT
            vals = dataclasses.replace(a, const_nonfinite=False)
            out = [vals] * n_out
            if n_out == 2:
                out[1] = _INT  # indices
            return out

        if name in ("iota", "axis_index", "program_id", "argmax",
                    "argmin", "random_fold_in", "random_wrap",
                    "random_unwrap", "random_bits", "random_seed",
                    "shift_left", "shift_right_logical",
                    "shift_right_arithmetic", "population_count",
                    "clz", "rem", "floor", "ceil", "round", "sign",
                    "nextafter"):
            # integer-producing / value-bounded prims: finite; `rem`,
            # `floor`, `ceil`, `round`, `sign`, `nextafter` keep the
            # operand's finiteness instead
            if name in ("rem", "floor", "ceil", "round", "sign",
                        "nextafter"):
                a = ins[0] if ins else _DEFAULT
                return [Absval(finite=a.finite,
                               nonneg=a.nonneg and name != "rem",
                               src=a.src)] * n_out
            return [_INT] * n_out

        # default: finite iff every float operand is proven finite;
        # nothing else survives an unknown primitive
        fin = all(v.finite for v in ins) if ins else True
        src = next((v.src for v in ins if v.src), "")
        return [Absval(finite=fin, src=src)] * n_out


def lattice_findings(program: str, closed) -> List[AuditFinding]:
    """NU001 + NU003 over one traced program."""
    auditor = _LatticeAuditor(program)
    auditor.run(closed)
    # no set-dedup (audit.forbidden_primitive_findings' rationale):
    # each unsafe site must count against the baseline individually
    return sorted(auditor.findings)


# ---------------------------------------------------------------------------
# NU002: precision seams + error-feedback width


def _is_downcast(src, dst) -> bool:
    """A LOSSY conversion: float narrowing, or float -> int8/int16
    quantization. Upcasts are exact; float -> int32/int64 is an index/
    count computation (exact for every magnitude the engine produces),
    not a precision seam."""
    import numpy as np
    try:
        s, d = np.dtype(src), np.dtype(dst)
    except TypeError:
        return False
    if _is_float_dtype(src) and _is_float_dtype(dst):
        return d.itemsize < s.itemsize
    if _is_float_dtype(src) and d.kind in "iu":
        return d.itemsize <= 2
    return False


def precision_findings(program: str, closed,
                       in_names: Sequence[str],
                       out_names: Sequence[str]) -> List[AuditFinding]:
    out: List[AuditFinding] = []
    seams = precision_seam_pairs()
    for eqn in iter_eqns(closed):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = _dtype_of(eqn.invars[0]) if eqn.invars else None
        dst = eqn.params.get("new_dtype")
        if src is None or dst is None:
            continue
        if (_is_downcast(src, dst)
                and (str(src), str(dst)) not in seams):
            out.append(AuditFinding(
                program, "NU002",
                f"unregistered precision downcast {src}->{dst} over "
                f"{_shape_of(eqn.invars[0])}: every lossy seam must "
                "be declared in analysis/domains.PRECISION_SEAMS "
                "with its residual story before it ships (the PR-6 "
                "wire quantization workflow)"))
    # error-feedback residual width: any err-named program operand
    # below f32 silently degrades the exact-residual accumulation
    # FetchSGD's convergence argument needs
    jaxpr = closed.jaxpr
    for vs, names, kind in ((jaxpr.invars, in_names, "input"),
                            (jaxpr.outvars, out_names, "output")):
        for v, name in zip(vs, names):
            if "err" not in name.lower():
                continue
            dt = _dtype_of(v)
            if dt is None or not _is_float_dtype(dt):
                continue
            import numpy as np
            if np.dtype(dt).itemsize < 4:
                out.append(AuditFinding(
                    program, "NU002",
                    f"error-feedback residual {kind} `{name}` is "
                    f"{dt}: the residual accumulation must stay "
                    "f32-or-wider end to end (the quantization "
                    "rounding it absorbs is the convergence "
                    "argument's whole budget)"))
    return sorted(out)


# ---------------------------------------------------------------------------
# NU004: replay-determinism


def determinism_findings(program: str, closed) -> List[AuditFinding]:
    out: List[AuditFinding] = []
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name in _SCATTER_PRIMS:
            mode = str(eqn.params.get("mode", "") or "")
            if "PROMISE_IN_BOUNDS" in mode.upper():
                out.append(AuditFinding(
                    program, "NU004",
                    f"`{name}` in PROMISE_IN_BOUNDS mode: "
                    "out-of-bounds behavior is undefined, so a "
                    "resumed replay may diverge bitwise — use the "
                    "default FILL_OR_DROP (or CLIP) mode inside "
                    "programs under the crash->resume contract"))
        elif name == "approx_top_k":
            rt = float(eqn.params.get("recall_target", 0.0) or 0.0)
            if rt not in PINNED_RECALL_TARGETS:
                out.append(AuditFinding(
                    program, "NU004",
                    f"`approx_top_k` with recall_target={rt}: the "
                    "replay contract pins "
                    f"{PINNED_RECALL_TARGETS} — an unpinned target "
                    "changes the selection (and the estimate "
                    "residual) silently across jax versions; pin it "
                    "at the call site or register the new value in "
                    "numaudit.PINNED_RECALL_TARGETS"))
        elif name == "sort":
            if eqn.params.get("is_stable") is False:
                out.append(AuditFinding(
                    program, "NU004",
                    "unstable `sort`: tie order is unspecified, so "
                    "equal keys (ubiquitous in top-k magnitude "
                    "selection) permute freely across "
                    "compilers/backends — use a stable sort inside "
                    "programs under the crash->resume contract"))
    return sorted(out)


# ---------------------------------------------------------------------------
# baseline: empty violations + the exact-match ulp block


class NumBaseline(AuditBaseline):
    """graftnum.baseline.json: {"violations": [...] (shipped EMPTY —
    real findings are FIXED, per house precedent), "ulp": {program:
    {worst_case_ulp}}}. The whole exact-match diff is inherited from
    AuditBaseline with the cost block re-parameterized, exactly like
    graftmesh's MeshBaseline."""

    COST_KEY = "ulp"
    COST_FIELDS = ("worst_case_ulp",)
    DRIFT_RULE = "NU005"


# ---------------------------------------------------------------------------
# the full audit


def trace_span(handle, server, clients, batch, lr, key,
               span_len: int = SPAN_LEN):
    """(ClosedJaxpr, invar names, outvar names) of the scanned
    `train_rounds` span program over `span_len` stacked copies of
    `batch` — the program a crash->resume drill re-dispatches, so its
    determinism walk is the one the NU004 contract is really about."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.federated.round import stack_batch_for_span
    span = stack_batch_for_span(batch, span_len)
    lrs = jnp.stack([lr] * span_len)
    closed, out_shape = jax.make_jaxpr(
        handle.train_rounds, return_shape=True)(
        server, clients, span, lrs, key)
    in_names = (_leaf_names("server", server)
                + _leaf_names("clients", clients)
                + _leaf_names("span", span)
                + _leaf_names("lr", lrs) + _leaf_names("key", key))
    return closed, in_names, _leaf_names("out", out_shape)


def run_num_audit(backends: Sequence[str] = ("xla", "pallas")
                  ) -> Tuple[dict, List[AuditFinding]]:
    """Trace every audit config x (round variants + the two
    state-motion programs + the scanned span) and run the numerics
    walks; return (report, findings). Findings carry NU001-NU004;
    NU005 (ulp drift) is the caller's baseline diff — the report's
    `ulp` block feeds it."""
    from commefficient_tpu.federated.round import program_variants_for

    by_program: Dict[str, Dict[str, int]] = {}
    ulp: Dict[str, Dict[str, int]] = {}
    findings: List[AuditFinding] = []

    def audit_one(prog, closed, in_names, out_names):
        fs = (lattice_findings(prog, closed)
              + precision_findings(prog, closed, in_names, out_names)
              + determinism_findings(prog, closed))
        findings.extend(fs)
        counts: Dict[str, int] = {}
        for f in fs:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        if counts:
            by_program[prog] = dict(sorted(counts.items()))
        ulp[prog] = {"worst_case_ulp": reassociation_ulp_bound(
            closed, ULP_AXIS_SIZES)}

    for cfg_name, cfg in audit_configs(backends):
        handle, server, clients, variants, lr, key = build_workload(
            cfg)
        for variant in program_variants_for(cfg):
            closed, in_names, out_names = trace_variant(
                handle, server, clients, variants[variant], lr, key)
            audit_one(f"{cfg_name}/{variant}", closed, in_names,
                      out_names)
        motion_batch = variants.get("mask_free",
                                    variants.get("screened"))
        for motion, (closed, in_names, out_names) in \
                trace_state_motion(handle, clients,
                                   motion_batch).items():
            audit_one(f"{cfg_name}/{motion}", closed, in_names,
                      out_names)
        closed, in_names, out_names = trace_span(
            handle, server, clients, motion_batch, lr, key)
        audit_one(f"{cfg_name}/span", closed, in_names, out_names)

    rules = {r: 0 for r in NUM_RULE_DOCS}
    for f in findings:
        rules[f.rule] = rules.get(f.rule, 0) + 1
    report = {
        "version": 1,
        "geometry": dict(AUDIT_GEOMETRY, population=AUDIT_POPULATION,
                         span_len=SPAN_LEN,
                         ulp_axes=dict(ULP_AXIS_SIZES)),
        "rules": rules,
        "by_program": by_program,
        "ulp": {p: ulp[p] for p in sorted(ulp)},
        "registry": {
            "precision_seams": len(precision_seam_pairs()),
            "pinned_recall_targets": list(PINNED_RECALL_TARGETS),
        },
    }
    report["digest"] = report_digest(report)
    # no set-dedup — audit.forbidden_primitive_findings' rationale
    return report, sorted(findings)


def report_digest(report: dict) -> str:
    """sha256 over the canonical rule/ulp blocks — the bit-identical-
    across-runs claim is checked on exactly this value (same contract
    as graftaudit/graftsync)."""
    canon = json.dumps({"geometry": report["geometry"],
                        "rules": report["rules"],
                        "by_program": report["by_program"],
                        "ulp": report["ulp"],
                        "registry": report["registry"]},
                       sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def journal_digest(journal_path: str, report: dict,
                   findings_count: int) -> dict:
    """Append the audit's report to a run journal as a
    `num_audit_digest` event (schema checked by telemetry.journal.
    validate_journal / scripts/journal_summary.py, mirroring
    audit_digest / mesh_audit_digest / sync_audit_digest)."""
    from commefficient_tpu.telemetry.journal import append_event
    return append_event(
        journal_path, "num_audit_digest",
        digest=report["digest"],
        rules=report["rules"],
        ulp={p: d["worst_case_ulp"]
             for p, d in report["ulp"].items()},
        findings=int(findings_count))


# ---------------------------------------------------------------------------
# CLI


def _split(findings: Sequence[AuditFinding]
           ) -> Tuple[List[AuditFinding], List[AuditFinding]]:
    """(rule violations, baseline drift) — NU005 is this tier's drift
    rule (audit.split_findings keys on the *AU006 suffix, which the
    NU rule space deliberately does not reuse)."""
    violations = [f for f in findings if f.rule != "NU005"]
    drift = [f for f in findings if f.rule == "NU005"]
    return violations, drift


def main(argv: Optional[list] = None) -> int:
    # never claim an accelerator: the audit only traces
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from commefficient_tpu.analysis.engine import load_pyproject_tool
    conf = load_pyproject_tool("graftnum")
    ap = argparse.ArgumentParser(
        prog="graftnum",
        description="jaxpr-level numerics & determinism auditor: "
                    "NaN-unsafe mask arithmetic, precision seams, "
                    "zero-guard divisions, replay determinism, and "
                    "the worst-case ulp baseline (rules NU001-NU005; "
                    "see --list-rules). Exit codes: 0 clean, 1 rule "
                    "violations, 2 baseline drift only.")
    ap.add_argument("--baseline", default=conf.get(
        "baseline", "graftnum.baseline.json"),
        help="baseline file (shipped with EMPTY violations — real "
             "findings are fixed, not grandfathered — plus the "
             "exact-match per-program ulp block)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding and skip the ulp diff")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from this audit")
    ap.add_argument("--backends", nargs="*",
                    default=list(conf.get("backends",
                                          ["xla", "pallas"])),
                    help="kernel backends to trace the sketch "
                         "programs on")
    ap.add_argument("--journal", default="",
                    help="append the report to this JSONL run journal "
                         "as a `num_audit_digest` event")
    ap.add_argument("--report", action="store_true",
                    help="print the full JSON report to stdout")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, doc in sorted(NUM_RULE_DOCS.items()):
            print(f"{code}  {doc}")
        return 0

    for b in args.backends:
        if b not in ("xla", "pallas"):
            # 3, not 2: exit 2 is reserved for baseline drift
            print(f"graftnum: unknown backend {b!r}", file=sys.stderr)
            return 3

    report, findings = run_num_audit(args.backends)

    if args.write_baseline:
        counts: Dict[Tuple[str, str], int] = {}
        for f in findings:
            counts[(f.program, f.rule)] = counts.get(
                (f.program, f.rule), 0) + 1
        NumBaseline(
            {k: (n, "TODO: justify or fix") for k, n in counts.items()},
            report["ulp"]).dump(args.baseline)
        print(f"graftnum: wrote {len(findings)} grandfathered "
              f"finding(s) + {len(report['ulp'])} program ulp "
              f"bound(s) to {args.baseline}")
        return 0

    stale: List[str] = []
    if not args.no_baseline:
        baseline = (NumBaseline.load(args.baseline)
                    if os.path.exists(args.baseline) else
                    NumBaseline())
        new, stale = baseline.apply_violations(findings)
        ulp_findings = baseline.apply_costs(report["ulp"],
                                            tolerance=0.0)
        findings = sorted(new + ulp_findings)

    if args.report:
        print(json.dumps(report, indent=2, sort_keys=True))
    if args.journal:
        journal_digest(args.journal, report, len(findings))

    for f in findings:
        print(f.render())
    for msg in stale:
        print(f"graftnum: {msg}")
    # the shared exit-code contract: 1 = rule violations (NU001-NU004),
    # 2 = baseline drift only (NU005 ulp mismatch / stale entries)
    violations, drift = _split(findings)
    rc = exit_code(violations, drift, stale)
    if rc:
        print(f"graftnum: {len(violations)} violation(s), "
              f"{len(drift)} drift finding(s), {len(stale)} stale "
              f"baseline entr(ies)")
        return rc
    print(f"graftnum: clean ({len(report['ulp'])} program(s) "
          f"audited, digest {report['digest'][:12]})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

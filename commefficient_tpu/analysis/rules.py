"""graftlint rules GL001-GL010.

Each rule is a function ``check(module: ModuleInfo) -> Iterator[
Violation]`` over one parsed file. The rules are deliberately
mechanical: they encode the round engine's invariants (see
analysis/__init__ and README "Invariants & graftlint") as syntactic
patterns, erring toward precision over recall — a lint that cries wolf
gets disabled, while a narrow one that holds the line on the contracts
it CAN see stays armed in CI forever.

Traced-code scoping (GL001/GL002/GL004): a function is considered
TRACED when it is (a) decorated with ``jax.jit`` / ``vmap`` / ``pmap``
/ ``shard_map`` / ``checkpoint`` (bare or under ``partial(...)``),
(b) passed by name to ``jax.jit(f)`` / ``jax.vmap(f)`` /
``jax.lax.scan(f, ...)`` / ``jax.lax.cond(p, f, g)`` /
``shard_map(f, ...)`` / ``jax.grad(f)`` and friends anywhere in the
same file, or (c) lexically nested inside a traced function (the round
engine's ``shard_train`` -> ``one_client`` -> closure tower). This is
lexical reachability, not a call graph: a helper called from traced
code but defined at module scope and never registered with a transform
is NOT scanned — the factory idiom this codebase uses everywhere
(make_train_fn closures) keeps traced code lexically nested, which is
exactly what makes the lexical rule strong here.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from commefficient_tpu.analysis.engine import Violation

# ---------------------------------------------------------------------------
# shared AST helpers


def _dotted(node: ast.AST) -> Optional[str]:
    """Dotted source name of a Name/Attribute chain ('jax.random.split'),
    or None when the expression is not a plain chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


class ModuleInfo:
    """One parsed file plus the derived facts every rule shares: parent
    links, the set of traced function/lambda nodes, and source text."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.traced_roots = _find_traced_roots(tree)

    def enclosing_functions(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                yield cur
            cur = self.parents.get(cur)

    def in_traced(self, node: ast.AST) -> bool:
        """True when `node` sits lexically inside a traced function."""
        if node in self.traced_roots:
            return True
        return any(f in self.traced_roots
                   for f in self.enclosing_functions(node))

    def segment(self, node: ast.AST) -> str:
        try:
            return ast.get_source_segment(self.source, node) or ""
        except Exception:  # graftlint: disable=GL005 -- best-effort source echo
            return ""


# transform entry points whose function-valued arguments become traced
# (pallas_call included: a Pallas kernel body is traced code — the
# same host-sync/control-flow hazards apply inside it, plus Mosaic's
# own restrictions)
_TRACE_ENTRY_CALLS = frozenset({
    "jit", "pmap", "vmap", "grad", "value_and_grad", "scan", "cond",
    "while_loop", "fori_loop", "switch", "shard_map", "checkpoint",
    "remat", "associative_scan", "custom_vjp", "custom_jvp",
    "pallas_call",
})
_TRACE_DECORATORS = frozenset({
    "jit", "pmap", "vmap", "shard_map", "checkpoint", "remat",
    "custom_vjp", "custom_jvp",
})


def _decorator_marks_traced(dec: ast.expr) -> bool:
    name = _terminal(_dotted(dec))
    if name in _TRACE_DECORATORS:
        return True
    if isinstance(dec, ast.Call):
        if _terminal(_dotted(dec.func)) in _TRACE_DECORATORS:
            return True
        # @partial(jax.jit, static_argnums=...) and friends
        if _terminal(_dotted(dec.func)) == "partial":
            return any(_terminal(_dotted(a)) in _TRACE_DECORATORS
                       for a in dec.args)
    return False


def _find_traced_roots(tree: ast.Module) -> Set[ast.AST]:
    by_name: Dict[str, List[ast.AST]] = {}
    roots: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
            if any(_decorator_marks_traced(d) for d in node.decorator_list):
                roots.add(node)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _terminal(_dotted(node.func)) not in _TRACE_ENTRY_CALLS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Lambda):
                roots.add(arg)
            name = _dotted(arg)
            if name and "." not in name:
                roots.update(by_name.get(name, ()))
    return roots


def _walk_traced(module: ModuleInfo) -> Iterator[ast.AST]:
    """Every node lexically inside a traced root, visited once."""
    seen: Set[ast.AST] = set()
    for root in module.traced_roots:
        body = root.body if isinstance(root.body, list) else [root.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if node not in seen:
                    seen.add(node)
                    yield node


# ---------------------------------------------------------------------------
# GL001 — host nondeterminism reachable from traced code

_GL001_CLOCKS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.perf_counter",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
})
_NP_GLOBAL_DRAWS = frozenset({
    "rand", "randn", "random", "random_sample", "randint", "choice",
    "permutation", "shuffle", "uniform", "normal", "standard_normal",
    "beta", "binomial", "poisson", "exponential", "bytes",
})
_PY_RANDOM_DRAWS = frozenset({
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "uniform", "gauss", "sample", "betavariate", "getrandbits",
})


def check_gl001(module: ModuleInfo) -> Iterator[Violation]:
    for node in _walk_traced(module):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if not name:
            continue
        what = None
        if name in _GL001_CLOCKS or name.endswith(".datetime.now"):
            what = f"host clock `{name}()`"
        elif (name.startswith(("np.random.", "numpy.random."))
              and _terminal(name) in _NP_GLOBAL_DRAWS):
            what = f"unseeded global-state draw `{name}()`"
        elif (name.startswith("random.")
              and _terminal(name) in _PY_RANDOM_DRAWS):
            what = f"unseeded `{name}()`"
        if what:
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL001",
                f"{what} inside traced code: the value freezes at trace "
                "time (or retraces nondeterministically), breaking the "
                "pure-(state, seed, round) round contract; thread a "
                "seeded generator / jax.random key in as data")


# ---------------------------------------------------------------------------
# GL002 — hidden host syncs / trace breaks in traced code

_NP_ALLOWED = frozenset({
    # dtype constructors and shape introspection are trace-safe
    "float16", "float32", "float64", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_", "dtype", "ndim",
    "shape", "isscalar", "broadcast_shapes",
})


def check_gl002(module: ModuleInfo) -> Iterator[Violation]:
    for node in _walk_traced(module):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name and name.startswith(("np.", "numpy.")):
            if name.startswith(("np.random.", "numpy.random.")):
                continue  # GL001's domain
            if _terminal(name) not in _NP_ALLOWED:
                yield Violation(
                    module.path, node.lineno, node.col_offset, "GL002",
                    f"raw numpy call `{name}(...)` inside traced code: "
                    "on a traced value this breaks the trace (or "
                    "silently bakes in a host constant) and forces a "
                    "device->host sync; use jnp/lax")
            continue
        if name in ("jax.device_get", "device_get"):
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL002",
                "`jax.device_get` inside traced code is a host sync; "
                "return the value and materialize it outside the "
                "traced function")
            continue
        if (isinstance(node.func, ast.Attribute) and node.func.attr == "item"
                and not node.args and not node.keywords):
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL002",
                "`.item()` inside traced code is a trace break / host "
                "sync; keep the value as an array")
            continue
        if (isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and len(node.args) == 1
                and isinstance(node.args[0], (ast.Call, ast.Subscript))):
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL002",
                f"`{node.func.id}(...)` of a computed value inside "
                "traced code concretizes a tracer (host sync / "
                "ConcretizationTypeError); keep it as an array or hoist "
                "it out of the traced function")


# ---------------------------------------------------------------------------
# GL003 — PRNG key reuse across draws

_KEY_NONDRAWS = frozenset({
    "PRNGKey", "key", "split", "fold_in", "key_data", "wrap_key_data",
    "key_impl", "clone",
})


def _jax_random_aliases(tree: ast.Module) -> Set[str]:
    """Local names that refer to the jax.random module: 'jax.random'
    always; plus whatever `from jax import random [as r]` / `import
    jax.random as jr` bind. Plain `import random` (stdlib) never
    qualifies, so stdlib draws don't masquerade as key consumption."""
    aliases = {"jax.random"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "random":
                    aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.random" and a.asname:
                    aliases.add(a.asname)
    return aliases


def _is_jax_random(name: Optional[str], aliases: Set[str]) -> bool:
    if not name or "." not in name:
        return False
    return name.rsplit(".", 1)[0] in aliases


def check_gl003(module: ModuleInfo) -> Iterator[Violation]:
    aliases = _jax_random_aliases(module.tree)
    funcs = [n for n in ast.walk(module.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _owner(node: ast.AST) -> Optional[ast.AST]:
        """The nearest enclosing def, looking THROUGH lambdas (they
        cannot rebind names, and a draw inside `vmap(lambda i: ...)`
        genuinely consumes the enclosing scope's key)."""
        for f in module.enclosing_functions(node):
            if not isinstance(f, ast.Lambda):
                return f
        return None

    for fn in funcs:
        # Per-scope linear scan: only nodes whose owning def is `fn`
        # participate — a nested def is a separate binding scope (its
        # assignments must not clear the outer drawn set, and it gets
        # its own pass from the `funcs` list). Cross-scope reuse
        # (outer draw + closure draw on the same outer key) is out of
        # scope for this rule — precision over recall.
        # events in source order: (lineno, col, kind, varname)
        events: List[Tuple[int, int, str, str]] = []
        for node in ast.walk(fn):
            if node is fn or _owner(node) is not fn:
                continue
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    for name_node in ast.walk(tgt):
                        if isinstance(name_node, ast.Name):
                            events.append((node.lineno, node.col_offset,
                                           "assign", name_node.id))
            elif isinstance(node, ast.Call):
                name = _dotted(node.func)
                if not _is_jax_random(name, aliases):
                    continue
                if _terminal(name) in _KEY_NONDRAWS:
                    continue
                # a draw: jax.random.normal(key, ...) — first positional
                # arg (or key=...) names the consumed key
                key_arg = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "key"), None)
                if isinstance(key_arg, ast.Name):
                    events.append((node.lineno, node.col_offset,
                                   "draw", key_arg.id))
        drawn: Set[str] = set()
        for lineno, col, kind, name in sorted(events):
            if kind == "assign":
                drawn.discard(name)
            elif kind == "draw":
                if name in drawn:
                    yield Violation(
                        module.path, lineno, col, "GL003",
                        f"PRNG key `{name}` consumed by a second draw "
                        "without an intervening split/fold_in: the two "
                        "draws are perfectly correlated. fold_in a "
                        "distinct domain tag (the dropout-vs-straggler "
                        "discipline of utils/faults) or split the key")
                drawn.add(name)


# ---------------------------------------------------------------------------
# GL004 — Python control flow over traced values

_ARRAY_REDUCERS = frozenset({"any", "all", "sum", "mean", "max", "min",
                             "prod", "item"})


def _traced_value_expr(expr: ast.AST) -> Optional[str]:
    """A sub-expression that clearly produces a traced array value:
    a jnp./jax.numpy./jax.lax. call, or an array-reducer method call."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name and name.startswith(("jnp.", "jax.numpy.", "jax.lax.",
                                     "lax.")):
            return name
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _ARRAY_REDUCERS
                and not node.args):
            base = _dotted(node.func.value)
            # cfg.*, self.* etc. are host objects; bare names and
            # computed bases are the array case
            if base is None or "." not in base:
                return f".{node.func.attr}()"
    return None


def check_gl004(module: ModuleInfo) -> Iterator[Violation]:
    for node in _walk_traced(module):
        if isinstance(node, (ast.If, ast.While)):
            hit = _traced_value_expr(node.test)
            if hit:
                kind = "if" if isinstance(node, ast.If) else "while"
                yield Violation(
                    module.path, node.lineno, node.col_offset, "GL004",
                    f"Python `{kind}` over a traced value ({hit}): this "
                    "forces a trace-time concretization (or a silent "
                    "per-value retrace); use lax.cond / lax.select / "
                    "jnp.where" + (" / lax.while_loop"
                                   if kind == "while" else ""))
        elif isinstance(node, ast.For):
            hit = _traced_value_expr(node.iter)
            if hit:
                yield Violation(
                    module.path, node.lineno, node.col_offset, "GL004",
                    f"Python `for` over a traced value ({hit}): the loop "
                    "unrolls at trace time (program size scales with "
                    "the array) or fails to concretize; use lax.scan / "
                    "lax.fori_loop")
        elif (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "bool" and len(node.args) == 1
                and _traced_value_expr(node.args[0])):
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL004",
                "`bool(...)` of a traced value concretizes the tracer; "
                "use lax.cond / jnp.where")


# ---------------------------------------------------------------------------
# GL005 — fault-swallowing broad except handlers

_BROAD = frozenset({"Exception", "BaseException"})


def _names_broad(type_expr: Optional[ast.expr]) -> bool:
    if type_expr is None:
        return True  # bare `except:`
    if isinstance(type_expr, ast.Tuple):
        return any(_names_broad(e) for e in type_expr.elts)
    return _terminal(_dotted(type_expr)) in _BROAD


def _reraises(handler: ast.ExceptHandler) -> bool:
    """True when the handler body contains a bare `raise` (re-raise) at
    any depth — the sanctioned cleanup-then-reraise and
    classify-then-reraise idioms (multihost.initialize, utils/retry)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise) and node.exc is None:
            return True
    return False


def check_gl005(module: ModuleInfo) -> Iterator[Violation]:
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _names_broad(node.type) and not _reraises(node):
            caught = (module.segment(node.type) if node.type is not None
                      else "<bare>")
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL005",
                f"broad `except {caught}` without re-raise would swallow "
                "InjectedFault and defeat the fault harness (and mask "
                "real config errors as transients); catch the specific "
                "expected exceptions, or re-raise")


# ---------------------------------------------------------------------------
# GL006 — non-atomic file writes

_WRITE_MODES = ("w", "a", "x", "+")


def _enclosing_scope_calls_replace(module: ModuleInfo,
                                   node: ast.AST) -> bool:
    scope: ast.AST = module.tree
    for fn in module.enclosing_functions(node):
        scope = fn
        break
    for n in ast.walk(scope):
        if isinstance(n, ast.Call) and _dotted(n.func) in (
                "os.replace", "os.rename"):
            return True
    return False


def _mentions_tmp(module: ModuleInfo, expr: ast.AST) -> bool:
    return "tmp" in module.segment(expr).lower()


def check_gl006(module: ModuleInfo) -> Iterator[Violation]:
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name in ("open", "io.open") or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "open" and name is None):
            mode = None
            if len(node.args) >= 2:
                mode = node.args[1]
            else:
                mode = next((kw.value for kw in node.keywords
                             if kw.arg == "mode"), None)
            if not (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and any(ch in mode.value for ch in _WRITE_MODES)):
                continue
            target = node.args[0] if node.args else None
            if target is None or _mentions_tmp(module, target):
                continue
            if _enclosing_scope_calls_replace(module, node):
                continue
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL006",
                "open-for-write without the atomic `.tmp` + os.replace "
                "pattern (utils/atomic_io): a preemption mid-write "
                "corrupts the previous file in place; write to "
                "`<path>.tmp` and os.replace, or use "
                "atomic_write_text/atomic_savez")
        elif name in ("np.save", "np.savez", "np.savez_compressed",
                      "numpy.save", "numpy.savez",
                      "numpy.savez_compressed"):
            target = node.args[0] if node.args else None
            # a bare Name is typically an open file handle (already
            # routed through the atomic open) or a precomputed tmp path
            if target is None or isinstance(target, ast.Name):
                continue
            if _mentions_tmp(module, target):
                continue
            if _enclosing_scope_calls_replace(module, node):
                continue
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL006",
                f"`{name}` straight to its destination path: a "
                "preemption mid-serialize leaves a torn archive under "
                "the real name; use utils/atomic_io.atomic_savez")


# ---------------------------------------------------------------------------
# GL007 — unconstrained shard_map/pjit output layouts

# transform -> (keyword that pins its output layout, positional arg
# count that reaches the same slot: shard_map(f, mesh, in_specs,
# out_specs) and pjit(f, in_shardings, out_shardings) are both legal
# positional forms)
_GL007_CALLS = {"shard_map": ("out_specs", 4),
                "pjit": ("out_shardings", 3)}


def check_gl007(module: ModuleInfo) -> Iterator[Violation]:
    """A `shard_map(...)` / `pjit(...)` call without an explicit
    `out_specs` / `out_shardings` leaves the output layout to GSPMD's
    propagation: on a partially-manual mesh (the engine's clients-
    manual / model-auto layout) that silently inserts reshards on new
    outputs instead of failing — the layout bug class the PR-3
    ROADMAP opening named. Mechanical and precise: only the literal
    call sites are checked; a call forwarding **kwargs, or passing
    enough positional args to cover the out-spec slot, is left alone
    (the spec may ride there), matching the lint's precision-over-
    recall rule."""
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        term = _terminal(_dotted(node.func))
        spec = _GL007_CALLS.get(term)
        if spec is None:
            continue
        kwname, pos_count = spec
        if any(kw.arg is None for kw in node.keywords):
            continue  # **kwargs forwarding: can't see the spec
        if (len(node.args) >= pos_count
                or any(isinstance(a, ast.Starred) for a in node.args)):
            continue  # positional form (or *args) covers the slot
        kw = next((kw.value for kw in node.keywords
                   if kw.arg == kwname), None)
        if kw is None or (isinstance(kw, ast.Constant)
                          and kw.value is None):
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL007",
                f"`{term}(...)` without an explicit `{kwname}`: the "
                "output layout is left to GSPMD propagation, which "
                "silently reshards new outputs on partially-manual "
                f"meshes; pass `{kwname}` (or pin each output with "
                "with_sharding_constraint before returning)")


# ---------------------------------------------------------------------------
# GL008 — exact large-k top-k inside traced code

# Exact `lax.top_k` lowers to a sorting network on TPU whose cost
# grows with k * d — the ~125 ms/round regression class PERF.md §1
# measured at k=50k (vs ~1 ms for the approx_max_k partial reduce).
# Flag only a STATIC k at or above this bound: small-k exact top-k is
# fine (and is what approx_max_k itself degenerates to), and a
# non-constant k is invisible to a syntactic rule (precision over
# recall, like every rule here).
GL008_MIN_K = 2048


def check_gl008(module: ModuleInfo) -> Iterator[Violation]:
    for node in _walk_traced(module):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if _terminal(name) != "top_k" or not name:
            continue
        # jax.lax.top_k / lax.top_k; jnp has no top_k, and a bare or
        # differently-rooted `top_k` is someone else's function
        root = name.rsplit(".", 1)[0]
        if root not in ("lax", "jax.lax"):
            continue
        k_arg = node.args[1] if len(node.args) >= 2 else next(
            (kw.value for kw in node.keywords if kw.arg == "k"), None)
        if not (isinstance(k_arg, ast.Constant)
                and isinstance(k_arg.value, int)
                and k_arg.value >= GL008_MIN_K):
            continue
        yield Violation(
            module.path, node.lineno, node.col_offset, "GL008",
            f"exact `{name}` with static k={k_arg.value} inside traced "
            "code: exact top-k lowers to a full sorting network on TPU "
            "(the ~125 ms/round regression class in PERF.md); use "
            "`jax.lax.approx_max_k` (error feedback absorbs the ~5% "
            "recall miss) or the fused selection kernel "
            "(ops/kernels/sketch_pallas.pallas_threshold_decode)")


# ---------------------------------------------------------------------------
# GL009 — PRNG-domain constants outside the central registry

# The engine's deterministic-replay story separates the dropout /
# straggler / scheduler streams by counter-based domain tags. Those
# tags live in analysis/domains.DOMAINS — the ONE place uniqueness is
# asserted. This rule holds the line syntactically: an inline hex
# literal fed to `fold_in` / `SeedSequence` is a domain tag that
# bypassed the registry (invisible to the collision assert), and a
# duplicate value inside the registry dict itself is a collision. Both
# apply file-wide, not just in traced scope: the production draws
# (utils/faults, scheduler/policy) are deliberately host-side.

_GL009_SINKS = frozenset({"fold_in", "SeedSequence"})
_GL009_REGISTRY_SUFFIX = "analysis/domains.py"


def _is_hex_literal(module: ModuleInfo, node: ast.AST) -> bool:
    if not (isinstance(node, ast.Constant) and isinstance(node.value, int)
            and not isinstance(node.value, bool)):
        return False
    return module.segment(node).strip().lower().startswith("0x")


def check_gl009(module: ModuleInfo) -> Iterator[Violation]:
    # (a) inline hex domain tags at a key-derivation sink, at any
    # argument depth (SeedSequence takes its entropy as a list)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if _terminal(_dotted(node.func)) not in _GL009_SINKS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if _is_hex_literal(module, sub):
                    yield Violation(
                        module.path, sub.lineno, sub.col_offset, "GL009",
                        f"inline hex domain tag `{module.segment(sub)}` "
                        "in a PRNG key derivation: domain constants "
                        "must come from analysis/domains.DOMAINS (the "
                        "registry asserts stream uniqueness; an inline "
                        "tag can silently collide with an existing "
                        "stream)")
    # (b) collisions inside the registry itself (pure AST — graftlint
    # never executes the tree, so the import-time assert is re-proven
    # syntactically on the literal dict)
    if not module.path.replace(os.sep, "/").endswith(
            _GL009_REGISTRY_SUFFIX):
        return
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "DOMAINS"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            continue
        seen: Dict[int, str] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(v, ast.Constant)
                    and isinstance(v.value, int)):
                continue
            name = (k.value if isinstance(k, ast.Constant) else
                    module.segment(k))
            if v.value in seen:
                yield Violation(
                    module.path, v.lineno, v.col_offset, "GL009",
                    f"PRNG domain collision: {name!r} reuses tag "
                    f"{hex(v.value)} already registered to "
                    f"{seen[v.value]!r} — correlated streams break the "
                    "independent-failure-process model")
            else:
                seen[v.value] = name


# ---------------------------------------------------------------------------
# GL010 — mesh-axis names outside the central registry

# The sharding layer's axis names live in analysis/domains.MESH_AXES
# (`clients`, `model`) — the one place a reviewer audits the mesh
# layout, mirroring the GL009 PRNG-domain discipline. This rule holds
# the line syntactically in the two packages that construct shardings
# (parallel/, federated/): a string literal at an axis-name position —
# a PartitionSpec/P argument, a Mesh axis_names entry, a shard_map
# axis_names member, a psum-family axis argument — that is not a
# registered MESH_AXES value is a typo or an unregistered axis, either
# of which GSPMD would silently absorb as a fully-replicated spec
# (the graftmesh AU007 failure class, caught here before a trace is
# ever needed). Literals that ARE registry values are fine: the rule
# checks by value, so P("clients") and P(CLIENTS_AXIS) are equally
# clean — migration to the constants is hygiene, not a lint gate.

from commefficient_tpu.analysis.domains import MESH_AXES  # noqa: E402

_GL010_SCOPES = ("/parallel/", "/federated/")
# call terminal -> how to find axis-name strings: "args" scans every
# positional/keyword argument expression for string constants;
# "mesh_ctor" scans the axis_names kwarg plus its positional slot
# (Mesh(devs, ("clients",))); "kwarg_only" scans only the kwarg
# (shard_map's positional slot 1 is the MESH argument, whose
# expression may legitimately contain unrelated strings)
_GL010_SINKS = {
    "PartitionSpec": "args",
    "P": "args",
    "Mesh": "mesh_ctor",
    "shard_map": "kwarg_only",
    "psum": "axis_arg",
    "pmax": "axis_arg",
    "pmin": "axis_arg",
    "all_gather": "axis_arg",
    "pbroadcast": "axis_arg",
    "pcast": "axis_arg",
}


def _string_constants(expr: ast.AST) -> Iterator[ast.Constant]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node


def check_gl010(module: ModuleInfo) -> Iterator[Violation]:
    path = "/" + module.path.replace(os.sep, "/")
    if not any(scope in path for scope in _GL010_SCOPES):
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        sink = _GL010_SINKS.get(_terminal(_dotted(node.func)))
        if sink is None:
            continue
        if sink == "args":
            exprs = list(node.args) + [kw.value for kw in node.keywords]
        elif sink == "axis_arg":
            # psum(x, "clients") / all_gather(x, "clients", ...) /
            # pcast(x, "clients", to=...): the axis rides the second
            # positional slot or an axis_name(s) kwarg
            exprs = node.args[1:2] + [kw.value for kw in node.keywords
                                      if kw.arg in ("axis_name",
                                                    "axis_names")]
        else:
            # Mesh(devs, axis_names) / Mesh(devs, axis_names=...) —
            # positional slot only for the constructor, where slot 1
            # IS the axis tuple
            exprs = node.args[1:2] if sink == "mesh_ctor" else []
            exprs += [kw.value for kw in node.keywords
                      if kw.arg == "axis_names"]
        for expr in exprs:
            for const in _string_constants(expr):
                if const.value in MESH_AXES:
                    continue
                yield Violation(
                    module.path, const.lineno, const.col_offset,
                    "GL010",
                    f"axis name {const.value!r} in a sharding "
                    "construction is not in the mesh-axis registry "
                    f"(analysis/domains.MESH_AXES = {MESH_AXES}): a "
                    "typo or unregistered axis becomes a silently "
                    "replicated spec under GSPMD propagation — use a "
                    "registered axis (or register the new one)")


# ---------------------------------------------------------------------------
# GL011 — wall-clock deltas used as durations

# A difference of two time.time() readings is NOT a duration: the wall
# clock steps under NTP correction (and jumps at DST/admin changes),
# so a duration derived from it can come out negative or wildly wrong
# exactly when a long production run crosses a correction — the hazard
# class graftscope (ISSUE 13) exists to measure AROUND. Durations must
# come from time.monotonic()/time.perf_counter(); wall time is for
# timestamps and cross-machine correlation only (the journal records
# both: `ts` wall, `mono` monotonic). The rule is syntactic + local:
# it flags a subtraction where BOTH operands are wall-clock-derived —
# a direct time.time()/time.time_ns() call, or a local name assigned
# from one in the same function scope. Comparing time.time() against
# an offset or a file mtime (checkpoint age GC) subtracts a
# NON-clock operand and is legitimately wall-clock — not flagged.

_GL011_WALL_CALLS = frozenset({"time.time", "time.time_ns"})


def _is_wall_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and (_dotted(node.func) or "") in _GL011_WALL_CALLS)


def _gl011_scopes(tree: ast.Module) -> Iterator[ast.AST]:
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _gl011_scope_nodes(scope: ast.AST) -> Iterator[ast.AST]:
    """Nodes lexically in `scope` ITSELF — nested function bodies are
    pruned (each is its own GL011 scope: a name bound from
    time.time() in one function must not taint the same name used as
    an ordinary parameter in another)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check_gl011(module: ModuleInfo) -> Iterator[Violation]:
    seen: Set[Tuple[int, int]] = set()
    for scope in _gl011_scopes(module.tree):
        # names bound DIRECTLY from a wall-clock call in this scope
        wall_names: Set[str] = set()
        for node in _gl011_scope_nodes(scope):
            if (isinstance(node, ast.Assign)
                    and _is_wall_call(node.value)):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        wall_names.add(tgt.id)
            elif (isinstance(node, ast.AnnAssign)
                    and node.value is not None
                    and _is_wall_call(node.value)
                    and isinstance(node.target, ast.Name)):
                wall_names.add(node.target.id)

        def _wall_derived(expr: ast.AST) -> Optional[str]:
            if _is_wall_call(expr):
                return f"{_dotted(expr.func)}()"
            if isinstance(expr, ast.Name) and expr.id in wall_names:
                return f"`{expr.id}` (assigned from time.time())"
            return None

        for node in _gl011_scope_nodes(scope):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            key = (node.lineno, node.col_offset)
            if key in seen:
                continue
            left = _wall_derived(node.left)
            right = _wall_derived(node.right)
            if left is None or right is None:
                continue
            seen.add(key)
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL011",
                f"wall-clock delta used as a duration: {left} - "
                f"{right}. time.time() steps under NTP correction, "
                "so its differences are not durations — use "
                "time.monotonic()/time.perf_counter() for intervals "
                "(keep time.time() for timestamps and comparisons "
                "against external wall-clock values like file "
                "mtimes)")


# ---------------------------------------------------------------------------
# GL012 — anonymous writer threads

# graftscope (telemetry/trace) stitches writer spans into Perfetto
# rows BY THREAD NAME, and the journal's trace records carry the
# thread name as the correlation key. An anonymous thread gets the
# interpreter's `Thread-N` counter name, which differs across
# restarts (and between two writers started in a different order), so
# a resumed run's spans land on a DIFFERENT Perfetto row than the
# crashed run's — the cross-restart timeline graftscope exists for
# silently splits. Mechanical and precise: every
# `threading.Thread(...)` construction must pass an explicit `name=`
# (the journal's "journal-writer", the checkpoint writer's
# f"{name}-writer"); **kwargs forwarding is left alone (the name may
# ride there).


def check_gl012(module: ModuleInfo) -> Iterator[Violation]:
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if _dotted(node.func) not in ("threading.Thread", "Thread"):
            continue
        if any(kw.arg is None for kw in node.keywords):
            continue  # **kwargs forwarding: can't see the name
        if any(kw.arg == "name" for kw in node.keywords):
            continue
        if (len(node.args) >= 3
                or any(isinstance(a, ast.Starred) for a in node.args)):
            continue  # Thread(group, target, name, ...): the third
            # positional slot IS the name (or *args may cover it)
        yield Violation(
            module.path, node.lineno, node.col_offset, "GL012",
            "`threading.Thread(...)` without an explicit `name=`: the "
            "interpreter's Thread-N fallback differs across restarts, "
            "so graftscope's thread-keyed trace rows (and the "
            "watchdog's writer-naming) break across a resume; name "
            "the thread after its role (journal-writer, "
            "state-spill-writer)")


# ---------------------------------------------------------------------------
# GL013 — float equality comparison on traced values (ISSUE 18)

# The crash->resume contract (graftnum NU004) makes BIT-exactness the
# replay guarantee, and FetchSGD's error feedback leans on one legal
# float-equality idiom: comparison against EXACT ZERO (`update == 0`,
# `vals == 0.0`) — a coordinate is either untouched or was assigned
# 0.0 through a `where`, so the test is a bit test, not an
# approximation. Every OTHER float equality in traced code is a
# rounding hazard: `x == 0.95` is False for the nearest f32 to 0.95
# after one ulp of drift, and `computed == computed'` couples program
# logic to reassociation order (exactly what graftnum's NU005 ulp
# bound prices as nonzero). The rule is AST-level and so heuristic:
# it flags equality against a non-zero FLOAT literal, and equality
# where a side is a clearly-traced jnp/lax expression — int-literal
# comparisons (ids, chunk indices) and bare-name pairs stay quiet.


def _zero_literal(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, (int, float))
            and not isinstance(node.value, bool)
            and float(node.value) == 0.0)


def check_gl013(module: ModuleInfo) -> Iterator[Violation]:
    for node in _walk_traced(module):
        if not isinstance(node, ast.Compare):
            continue
        if not all(isinstance(op, (ast.Eq, ast.NotEq))
                   for op in node.ops):
            continue
        sides = [node.left] + list(node.comparators)
        if any(_zero_literal(s) for s in sides):
            # the sanctioned sparsity/sentinel bit test (`update ==
            # 0` error-feedback masking, `vals == 0.0` unfilled-slot
            # sentinel): exact by construction, replay-stable
            continue
        float_lit = next(
            (s.value for s in sides
             if isinstance(s, ast.Constant)
             and isinstance(s.value, float)), None)
        if float_lit is not None:
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL013",
                f"float equality against {float_lit!r} in traced "
                "code: one ulp of drift (psum reassociation, a "
                "backend change) flips this comparison, breaking the "
                "crash->resume bit-exactness contract — compare "
                "against exact 0 (the sparsity idiom), use an "
                "inequality threshold, or jnp.isclose with an "
                "explicit tolerance")
            continue
        hit = next((h for h in map(_traced_value_expr, sides) if h),
                   None)
        if hit is not None:
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL013",
                f"float `==`/`!=` on a computed traced value ({hit}): "
                "equality between computed floats couples logic to "
                "summation/reassociation order (graftnum prices that "
                "drift as a nonzero ulp bound) — compare against "
                "exact 0, use an inequality threshold, or "
                "jnp.isclose with an explicit tolerance")


# ---------------------------------------------------------------------------
# GL014 — controller plan wire fields outside the central registry

# The control/ subsystem (ISSUE 20) rides every controller's adjusted
# value on a named RoundPlan wire field; the journaled plan stream is
# the authoritative adjustment log a coordinator takeover replays.
# Those fields live in analysis/domains.CONTROL_FIELDS — the one place
# uniqueness is asserted — because two controllers sharing a field
# silently overwrite each other's wire decisions (invisible at
# runtime, catastrophic on a resume). This rule holds the line
# syntactically, mirroring GL009: (a) a `WIRE_FIELD = "..."` class
# attribute anywhere in the tree whose string literal is not a
# registered CONTROL_FIELDS value is a controller that bypassed the
# registry; (b) a duplicate value inside the registry dict itself is a
# collision, re-proven pure-AST on the literal dict.

from commefficient_tpu.analysis.domains import CONTROL_FIELDS  # noqa: E402

_GL014_ATTR = "WIRE_FIELD"
_GL014_REGISTRY_SUFFIX = "analysis/domains.py"


def check_gl014(module: ModuleInfo) -> Iterator[Violation]:
    # (a) unregistered WIRE_FIELD class attributes, tree-wide: the
    # attribute name is the control/ base-class contract, so any
    # assignment to it claims a wire field
    registered = set(CONTROL_FIELDS.values())
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == _GL014_ATTR
                        for t in node.targets)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            continue
        field = node.value.value
        if field and field not in registered:
            yield Violation(
                module.path, node.lineno, node.col_offset, "GL014",
                f"controller wire field {field!r} is not registered "
                "in analysis/domains.CONTROL_FIELDS: the registry is "
                "where wire-field uniqueness is asserted — an "
                "unregistered field can silently collide with an "
                "existing controller's journaled plan stream")
    # (b) collisions inside the registry itself (pure AST — the
    # import-time assert re-proven syntactically on the literal dict)
    if not module.path.replace(os.sep, "/").endswith(
            _GL014_REGISTRY_SUFFIX):
        return
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "CONTROL_FIELDS"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            continue
        seen: Dict[str, str] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                continue
            name = (k.value if isinstance(k, ast.Constant) else
                    module.segment(k))
            if v.value in seen:
                yield Violation(
                    module.path, v.lineno, v.col_offset, "GL014",
                    f"controller wire-field collision: {name!r} "
                    f"reuses field {v.value!r} already registered to "
                    f"{seen[v.value]!r} — two controllers on one wire "
                    "field overwrite each other's plan-carried "
                    "adjustments")
            else:
                seen[v.value] = name


# ---------------------------------------------------------------------------

ALL_RULES = {
    "GL001": check_gl001,
    "GL002": check_gl002,
    "GL003": check_gl003,
    "GL004": check_gl004,
    "GL005": check_gl005,
    "GL006": check_gl006,
    "GL007": check_gl007,
    "GL008": check_gl008,
    "GL009": check_gl009,
    "GL010": check_gl010,
    "GL011": check_gl011,
    "GL012": check_gl012,
    "GL013": check_gl013,
    "GL014": check_gl014,
}

RULE_DOCS = {
    "GL001": "host nondeterminism (clocks, unseeded global RNG) inside "
             "traced code",
    "GL002": "raw numpy / .item() / device_get inside traced code "
             "(hidden sync, trace break)",
    "GL003": "PRNG key consumed by two draws without split/fold_in "
             "domain separation",
    "GL004": "Python if/while/for over traced values where "
             "lax.cond/scan is required",
    "GL005": "broad except handler that would swallow InjectedFault "
             "(no re-raise)",
    "GL006": "file write without the atomic .tmp + os.replace pattern",
    "GL007": "shard_map/pjit output layout left unconstrained (no "
             "out_specs/out_shardings, no with_sharding_constraint)",
    "GL008": "exact lax.top_k with large static k in traced code "
             "(TPU sorting-network cliff; use approx_max_k or the "
             "fused selection kernel)",
    "GL009": "PRNG domain tag outside the analysis/domains registry "
             "(inline hex in fold_in/SeedSequence, or a registry "
             "collision)",
    "GL010": "mesh-axis name in a sharding construction (parallel/, "
             "federated/) outside the analysis/domains MESH_AXES "
             "registry",
    "GL011": "wall-clock delta (time.time() difference) used as a "
             "duration — NTP steps corrupt it; use "
             "time.monotonic()/perf_counter for intervals",
    "GL012": "threading.Thread constructed without an explicit name= "
             "(anonymous Thread-N names break graftscope's "
             "thread-keyed trace rows across restarts)",
    "GL013": "float ==/!= on traced values (non-zero literal or "
             "computed comparand) — one ulp of reassociation drift "
             "flips it; exact-zero sparsity tests stay legal",
    "GL014": "controller plan wire field outside the analysis/domains "
             "CONTROL_FIELDS registry (unregistered WIRE_FIELD class "
             "attribute, or a registry collision)",
}

"""graftaudit: jaxpr-level auditor of the round engine's programs.

graftlint (engine/rules) sees SOURCE — it catches what syntax can
prove and nothing more. This module is the second analysis tier: it
traces the round programs the engine actually dispatches (the three
RoundBatch treedefs of federated/round.PROGRAM_VARIANTS, on both
kernel backends plus a client-state-bearing config) to ClosedJaxprs
and walks the PROGRAM — post-closure, post-fusion, post-dispatch-
gating — for the contracts prose and AST can't check:

  AU001  forbidden host-interaction primitives: callbacks, debug
         prints, infeed/outfeed. Any of these inside a round program
         is a hidden per-round host sync (the cliff GL002 hunts
         syntactically; here it is caught even when smuggled in
         through a library call graftlint never sees).
  AU002  f64/c128 dtypes. The engine's numeric contract is
         f32 master state with bf16/int8 compute/wire options; a
         float64 appearing in a traced program is an accidental
         promotion (usually a Python float in the wrong place) that
         silently doubles state HBM — and TPUs execute it in slow
         emulation.
  AU003  exact `sort`/`top_k` over large static operands — the GL008
         regression class (~125 ms/round on TPU, PERF.md §1), caught
         here AFTER all dispatch gating, so a config routing around
         `approx_max_k`/the fused kernels cannot hide.
  AU004  population scaling. Since ISSUE 9 the rule is STRICT for
         round programs: ANY population-shaped value — input, output,
         intermediate, or baked-in constant — is an error, because
         the cohort-gather/scatter-back state-motion programs are the
         only programs allowed to touch the [population, D] blocks
         and the jitted round operates purely on [num_workers, D]
         CohortState rows. The gather/scatter programs themselves
         (and configs listed in `population_inventory_configs`,
         for workloads that legitimately keep dense in-round state)
         keep the pre-ISSUE-9 INVENTORY semantics: population-shaped
         inputs/outputs are emitted as the named client-state map,
         intermediates/constants still error.
  AU005  dead-but-undonated round inputs: federated/round declares
         which dispatch operands the caller never reads again
         (ROUND_DEAD_ARGNUMS / SPAN_DEAD_ARGNUMS); each must be
         donated so XLA reuses its HBM in place. At population scale
         the client rows are the dominant allocation — an undonated
         dispatch transiently doubles them.
  AU006  static cost drift: every program's FLOPs/HBM-bytes price
         (analysis/costmodel) is diffed against the committed
         `audit.baseline.json` with graftlint-style exact-match
         semantics — a program missing from the baseline, a stale
         baseline entry, and a price drifted beyond the configured
         tolerance all error. The hardware-independent regression
         gate standing in for the TPU-pending bench numbers.

The auditor is config-driven from ``[tool.graftaudit]`` in
pyproject.toml and ships as the ``graftaudit`` console script
(scripts/audit.sh; tier1.sh runs it right after graftlint). Its cost
report is journaled as an ``audit_digest`` event
(telemetry/journal.py) and is bit-identical across runs — tracing is
deterministic, the report is canonical-JSON — which is what lets the
baseline diff be exact.

Import discipline: this module imports jax (and the round engine)
LAZILY, inside the functions that trace — `main` pins
JAX_PLATFORMS=cpu first so the auditor never claims an accelerator,
and importing the module (console-script resolution, graftlint's
pure-AST pass over this file) stays jax-free.
"""
from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from commefficient_tpu.analysis.costmodel import (
    aval_bytes, jaxpr_cost, sub_jaxprs,
)

AUDIT_RULE_DOCS = {
    "AU001": "forbidden host-interaction primitive (callback / debug "
             "print / infeed) inside a round program",
    "AU002": "f64/c128 dtype inside a round program (engine contract "
             "is f32 state, bf16/int8 compute/wire)",
    "AU003": "exact sort/top_k over a large static operand (the GL008 "
             "TPU sorting-network cliff, post-fusion)",
    "AU004": "population-scaling buffer that is not a declared "
             "client-state input/carried output",
    "AU005": "dead-after-dispatch round input not donated "
             "(round.ROUND_DEAD_ARGNUMS / SPAN_DEAD_ARGNUMS)",
    "AU006": "static cost drift vs audit.baseline.json (new / stale / "
             "regressed program)",
}

# AU001: primitive names that interact with the host mid-program
FORBIDDEN_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "debug_print", "host_callback", "outside_call", "infeed",
    "outfeed",
})

# AU003 thresholds: exact top_k at or past GL008's static-k bound, and
# full sorts over operands big enough that the sorting network is the
# round's dominant cost
TOPK_MIN_K = 2048          # == rules.GL008_MIN_K (kept in sync by test)
SORT_MIN_N = 1 << 16

# the population sentinel the audit workload traces with: prime, and
# distinct from every other dimension in the geometry, so a shape
# "scales with num_clients" exactly when it contains this value
AUDIT_POPULATION = 23

# the synthetic workload geometry — small enough to trace in
# milliseconds, structured enough that every audited code path (sketch
# encode/decode, pallas kernels, per-client state gather/scatter) is
# live. Committed baselines price THIS geometry; change it and the
# baseline must be regenerated.
AUDIT_GEOMETRY = dict(D=1024, W=8, B=4, k=64, rows=3, cols=256)

# the tiered config's device working set (ISSUE 11): >= W, divisible
# by every registered mesh clients-axis size (8 and 4) so the mesh
# tier shards the block without padding, and distinct from both the
# population sentinel and every geometry dim
TIER_WORKING_SET = 16


@dataclasses.dataclass(frozen=True, order=True)
class AuditFinding:
    program: str
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.program}: {self.rule} {self.message}"


# ---------------------------------------------------------------------------
# jaxpr walkers (duck-typed like costmodel — sub_jaxprs is shared
# with it, so the auditor and the cost model can never disagree about
# which sub-jaxprs an equation contains)


def iter_eqns(jaxpr):
    """Every equation in `jaxpr` (Closed or raw), recursively."""
    inner = getattr(jaxpr, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        jaxpr = inner
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in sub_jaxprs(v):
                yield from iter_eqns(sub)


def _shape_of(v):
    return tuple(getattr(getattr(v, "aval", None), "shape", ()) or ())


def _dtype_of(v):
    return getattr(getattr(v, "aval", None), "dtype", None)


def forbidden_primitive_findings(program: str, closed
                                 ) -> List[AuditFinding]:
    """AU001 + AU002 + AU003 over one traced program."""
    out: List[AuditFinding] = []
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name in FORBIDDEN_PRIMITIVES:
            out.append(AuditFinding(
                program, "AU001",
                f"primitive `{name}` is a host interaction inside the "
                "round program — a per-round device->host sync; hoist "
                "it out of the traced round (telemetry exports at span "
                "boundaries exist for exactly this)"))
        for v in list(eqn.outvars) + [iv for iv in eqn.invars
                                      if hasattr(iv, "aval")]:
            dt = _dtype_of(v)
            if dt is not None and str(dt) in ("float64", "complex128"):
                out.append(AuditFinding(
                    program, "AU002",
                    f"{str(dt)} value of shape {_shape_of(v)} at "
                    f"primitive `{name}`: the engine's numeric "
                    "contract is f32 state / bf16-int8 compute; a "
                    "float64 is an accidental promotion (slow TPU "
                    "emulation, doubled HBM)"))
                break
        if name == "top_k":
            k = int(eqn.params.get("k", 0) or 0)
            operand = max((_shape_of(v)[-1] for v in eqn.invars
                           if _shape_of(v)), default=0)
            if k >= TOPK_MIN_K:
                out.append(AuditFinding(
                    program, "AU003",
                    f"exact `top_k` with k={k} over a [{operand}] "
                    "operand: lowers to a full sorting network on TPU "
                    "(~125 ms/round class, PERF.md §1); route through "
                    "approx_max_k or the fused threshold decode"))
        elif name == "sort":
            from commefficient_tpu.analysis.costmodel import sort_width
            width = sort_width(eqn)
            if width >= SORT_MIN_N:
                out.append(AuditFinding(
                    program, "AU003",
                    f"exact `sort` along a {width}-wide dimension "
                    "inside the round program: the TPU sorting-network "
                    "cliff; use an approximate selection or a fused "
                    "kernel (a sort along a SHORT dimension — the "
                    "sketch median's r-wide lane sort — is fine and "
                    "not flagged)"))
    # NO set-dedup: two distinct equations can produce identical
    # findings (same primitive, same shape), and each must count —
    # collapsing them would let a second occurrence hide behind a
    # count=1 baseline entry
    return sorted(out)


def population_scan(program: str, closed, population: int,
                    in_names: Sequence[str], out_names: Sequence[str],
                    strict: bool = False
                    ) -> Tuple[dict, List[AuditFinding]]:
    """AU004 + the named client-state inventory.

    strict=False (the state-motion programs; opted-out configs):
    inputs/outputs whose shape carries the population sentinel are
    INVENTORY (the dense per-client-state rows the gather/scatter
    programs legitimately move); any OTHER population-shaped value —
    an intermediate, or a constant baked into the program — is a
    finding.

    strict=True (round programs since ISSUE 9): population-shaped
    inputs/outputs are ERRORS too — the jitted round's operand
    surface is CohortState rows, and a population block reappearing
    there is the exact regression the O(active) refactor exists to
    prevent. The inventory block is still emitted (it must be empty —
    the refactor's mechanical definition of done)."""
    jaxpr = closed.jaxpr
    findings: List[AuditFinding] = []

    def pop_shaped(v):
        return population in _shape_of(v)

    inventory = {"inputs": [], "outputs": []}
    for v, name in zip(jaxpr.invars, in_names):
        if pop_shaped(v):
            inventory["inputs"].append({
                "name": name, "shape": list(_shape_of(v)),
                "dtype": str(_dtype_of(v)),
                "bytes": aval_bytes(v.aval)})
            if strict:
                findings.append(AuditFinding(
                    program, "AU004",
                    f"population-shaped INPUT `{name}` "
                    f"{list(_shape_of(v))}: round programs take only "
                    "cohort-sized operands — population state moves "
                    "through the gather/scatter state-motion programs "
                    "(ISSUE 9 O(active) contract)"))
    for v, name in zip(jaxpr.outvars, out_names):
        if pop_shaped(v):
            inventory["outputs"].append({
                "name": name, "shape": list(_shape_of(v)),
                "dtype": str(_dtype_of(v)),
                "bytes": aval_bytes(getattr(v, "aval", None))})
            if strict:
                findings.append(AuditFinding(
                    program, "AU004",
                    f"population-shaped OUTPUT `{name}` "
                    f"{list(_shape_of(v))}: round programs return only "
                    "cohort-sized results — scatter-back owns the "
                    "population write (ISSUE 9 O(active) contract)"))

    for cv, const in zip(jaxpr.constvars, closed.consts):
        if pop_shaped(cv):
            findings.append(AuditFinding(
                program, "AU004",
                f"population-shaped CONSTANT {list(_shape_of(cv))} "
                "baked into the program: a host-materialized "
                "num_clients-sized buffer rides into every dispatch"))

    # allowed var ids: program inputs and outputs, propagated through
    # container eqns positionally (a scatter under a pjit wrapper whose
    # result IS the program output is carried state, not a leak)
    allowed = {id(v) for v in jaxpr.invars}
    allowed |= {id(v) for v in jaxpr.outvars}

    def scan(jx, allowed):
        for eqn in jx.eqns:
            subs = [s for v in eqn.params.values()
                    for s in sub_jaxprs(v)]
            if subs:
                inner_allowed = set()
                for s in subs:
                    n_in = min(len(eqn.invars), len(s.invars))
                    for ev, sv in zip(eqn.invars[-n_in:],
                                      s.invars[-n_in:]):
                        if id(ev) in allowed:
                            inner_allowed.add(id(sv))
                    n_out = min(len(eqn.outvars), len(s.outvars))
                    for ev, sv in zip(eqn.outvars[-n_out:],
                                      s.outvars[-n_out:]):
                        if id(ev) in allowed:
                            inner_allowed.add(id(sv))
                for ov in eqn.outvars:
                    if pop_shaped(ov) and id(ov) not in allowed:
                        findings.append(AuditFinding(
                            program, "AU004",
                            f"population-shaped intermediate "
                            f"{list(_shape_of(ov))} produced by "
                            f"`{eqn.primitive.name}` is neither a "
                            "client-state input nor a carried output: "
                            "the round program materializes a "
                            "num_clients-scaling buffer per dispatch"))
                for s in subs:
                    scan(s, allowed | inner_allowed
                         | {id(v) for v in s.invars
                            if not pop_shaped(v)})
                continue
            for ov in eqn.outvars:
                if pop_shaped(ov) and id(ov) not in allowed:
                    findings.append(AuditFinding(
                        program, "AU004",
                        f"population-shaped intermediate "
                        f"{list(_shape_of(ov))} produced by "
                        f"`{eqn.primitive.name}` is neither a "
                        "client-state input nor a carried output: the "
                        "round program materializes a num_clients-"
                        "scaling buffer per dispatch"))

    scan(jaxpr, allowed)
    # no set-dedup — see forbidden_primitive_findings
    return inventory, sorted(findings)


def donation_findings(config_name: str, handle) -> List[AuditFinding]:
    """AU005: the dispatch entry points' dead operands vs what their
    jits actually donate (federated/round's registry attributes).
    Three entries since ISSUE 9: the cohort round program (its
    gathered CohortState is dead), the scatter-back state-motion
    program (the full ClientState is dead — at population scale THE
    donation that matters), and the scanned span."""
    from commefficient_tpu.federated.round import (
        ROUND_DEAD_ARGNUMS, SCATTER_DEAD_ARGNUMS, SPAN_DEAD_ARGNUMS,
    )
    out: List[AuditFinding] = []
    for entry, dead, donated, argname in (
            ("per-round", ROUND_DEAD_ARGNUMS,
             getattr(handle, "round_donate_argnums", ()),
             {0: "ServerState", 1: "CohortState"}),
            ("scatter-back", SCATTER_DEAD_ARGNUMS,
             getattr(handle, "scatter_donate_argnums", ()),
             {0: "ClientState"}),
            ("scanned-span", SPAN_DEAD_ARGNUMS,
             getattr(handle, "span_donate_argnums", ()),
             {0: "ServerState", 1: "ClientState"})):
        for argnum in dead:
            if argnum not in tuple(donated):
                out.append(AuditFinding(
                    f"{config_name}/{entry}", "AU005",
                    f"dispatch operand {argnum} "
                    f"({argname.get(argnum, '?')}) is dead after "
                    "dispatch (the caller only assigns state from the "
                    "result) but not donated: XLA cannot reuse its HBM "
                    "in place, transiently doubling the state "
                    "footprint — wire donate_argnums "
                    "(Config.donate_round_state)"))
    return out


# ---------------------------------------------------------------------------
# the audit workload: a synthetic linear model through the REAL round
# factory — make_train_fn is exactly what FedModel dispatches, so the
# traced jaxprs are the production programs at audit geometry


def audit_configs(backends: Sequence[str] = ("xla", "pallas"),
                  population: int = AUDIT_POPULATION):
    """(name, Config) pairs the auditor traces. Two sketch configs pin
    the compression hot path on each kernel backend; `client-state`
    (local_topk + local error + momentum + topk_down) is the config
    whose per-client rows populate the AU004 inventory. `population`
    overrides the num_clients sentinel (the mesh tier,
    analysis/shardaudit, needs one divisible by its clients axes)."""
    from commefficient_tpu.config import Config
    g = AUDIT_GEOMETRY
    base = dict(weight_decay=0.0, num_workers=g["W"],
                microbatch_size=-1, grad_size=g["D"],
                num_clients=population, seed=0)
    out = []
    for b in backends:
        out.append((f"sketch-{b}", Config(
            mode="sketch", error_type="virtual", virtual_momentum=0.9,
            local_momentum=0.0, k=g["k"], num_rows=g["rows"],
            num_cols=g["cols"], num_blocks=1, kernel_backend=b,
            **base).validate()))
    out.append(("client-state", Config(
        mode="local_topk", error_type="local", local_momentum=0.9,
        do_topk_down=True, k=g["k"], down_k=32,
        **base).validate()))
    # tiered cold client state (ISSUE 11): the same client-state
    # workload with a bounded device working set — its gather/scatter
    # trace over the [working_set, D] block (no population-shaped
    # value ANYWHERE, not even in the state-motion inventory: the
    # million-user residency claim as an audited program property).
    # TIER_WORKING_SET divides every registered mesh clients axis so
    # the mesh tier shards the block without padding.
    out.append(("client-state-tiered", Config(
        mode="local_topk", error_type="local", local_momentum=0.9,
        do_topk_down=True, k=g["k"], down_k=32, state_tier="host",
        state_working_set=TIER_WORKING_SET,
        **base).validate()))
    # value-fault screening (ISSUE 16): a sketch config with the norm
    # screen on traces the SCREENED program family — the only family
    # with the poison mask + screen-scalar operands — so the admission
    # arithmetic (finite mask, all_gather'd cohort median, survivor
    # fold) is priced and contract-checked like every other program.
    out.append(("sketch-screened", Config(
        mode="sketch", error_type="virtual", virtual_momentum=0.9,
        local_momentum=0.0, k=g["k"], num_rows=g["rows"],
        num_cols=g["cols"], num_blocks=1, kernel_backend="xla",
        update_screen="norm", **base).validate()))
    # Byzantine-robust aggregation (ISSUE 17): the screened sketch
    # config with a live adversary draw and the beta-trimmed mean —
    # traces the robust reduction (per-client gather, rank
    # computation, trim mask, residual gauge) riding the screened
    # program family, so the order-statistic arithmetic is priced and
    # contract-checked like every other program.
    out.append(("sketch-robust", Config(
        mode="sketch", error_type="virtual", virtual_momentum=0.9,
        local_momentum=0.0, k=g["k"], num_rows=g["rows"],
        num_cols=g["cols"], num_blocks=1, kernel_backend="xla",
        update_screen="norm", byzantine_rate=0.2, attack="sign_flip",
        aggregator="trimmed_mean", **base).validate()))
    # compressor plugins (ISSUE 19): the two new plugin families.
    # powersgd rides the client-state blocks (warm Q factors in the
    # velocities block, EF residual in errors) — its Gram-Schmidt /
    # factor-matmul arithmetic is priced and contract-checked like
    # every other program.
    out.append(("powersgd", Config(
        mode="powersgd", error_type="local", local_momentum=0.0,
        powersgd_rank=2, **base).validate()))
    # dp_sketch: the sketch pipeline plus per-client l2 clipping and
    # one post-aggregation Gaussian noise draw on the registered "dp"
    # PRNG domain — the privacy arithmetic traced in-program.
    out.append(("dp-sketch", Config(
        mode="dp_sketch", error_type="virtual", virtual_momentum=0.9,
        local_momentum=0.0, k=g["k"], num_rows=g["rows"],
        num_cols=g["cols"], num_blocks=1, dp_clip=1.0,
        dp_noise_mult=1.0, **base).validate()))
    return out


def build_workload(cfg):
    """Round handle + abstract operands for one audit config. All data
    is zeros — nothing here ever executes; make_jaxpr only reads
    shapes/dtypes/treedefs."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.federated.round import (
        RoundBatch, audit_batch_variants, client_state_rows,
        init_client_state, init_server_state, make_train_fn,
    )
    from commefficient_tpu.ops.flat import flatten_params
    from commefficient_tpu.parallel.mesh import make_client_mesh

    g = AUDIT_GEOMETRY

    def loss_fn(params, batch, mask):
        x, y = batch
        pred = x @ params["w"]
        per_ex = 0.5 * (pred - y) ** 2
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = (per_ex * mask).sum() / denom
        return loss, (loss,)

    params = {"w": jnp.zeros(g["D"], jnp.float32)}
    vec, unravel = flatten_params(params)
    # the audit mesh is ALWAYS one device: per-shard program shapes are
    # then host-count-independent, so the committed baseline prices the
    # same program on a laptop, in CI, and on a pod host
    mesh = make_client_mesh(1)
    handle = make_train_fn(loss_fn, unravel, cfg, mesh)
    server = init_server_state(cfg, vec)
    # client_state_rows: the tiered config (state_tier=host) allocates
    # its bounded [working_set, D] block — the gather/scatter the
    # auditor walks are then the slot-indexed tiered programs
    clients = init_client_state(
        cfg, client_state_rows(cfg, AUDIT_POPULATION), vec)
    batch = RoundBatch(
        jnp.arange(g["W"], dtype=jnp.int32),
        (jnp.zeros((g["W"], g["B"], g["D"]), jnp.float32),
         jnp.zeros((g["W"], g["B"]), jnp.float32)),
        jnp.ones((g["W"], g["B"]), jnp.float32))
    variants = audit_batch_variants(batch, cfg)
    lr = jnp.float32(0.1)
    key = jax.random.PRNGKey(0)
    return handle, server, clients, variants, lr, key


def _leaf_names(prefix: str, tree) -> List[str]:
    import jax
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [prefix + jax.tree_util.keystr(path)
            for path, _ in leaves]


def trace_variant(handle, server, clients, batch, lr, key):
    """(ClosedJaxpr, invar names, outvar names) of the single-round
    program this handle dispatches for `batch`'s treedef — the COHORT
    round body (round.make_train_fn round_step): the gathered
    CohortState avals come from jax.eval_shape over the gather body,
    so the traced operand surface is exactly what the round jit
    compiles and AU004-strict checks."""
    import jax
    cohort = jax.eval_shape(handle.gather_fn, clients,
                            batch.client_ids)
    closed, out_shape = jax.make_jaxpr(
        handle.round_step, return_shape=True)(
        server, cohort, batch, lr, key)
    in_names = (_leaf_names("server", server)
                + _leaf_names("cohort", cohort)
                + _leaf_names("batch", batch)
                + _leaf_names("lr", lr) + _leaf_names("key", key))
    out_names = _leaf_names("out", out_shape)
    return closed, in_names, out_names


def trace_state_motion(handle, clients, batch):
    """{"gather": (...), "scatter": (...)} — the two state-motion
    programs bracketing every round dispatch (round.
    STATE_MOTION_PROGRAMS), traced like trace_variant. These are the
    only programs ALLOWED to carry population-shaped inputs/outputs;
    their AU004 scan runs in inventory mode and their inventory IS
    the named client-state map the round programs no longer have."""
    import jax
    ids = batch.client_ids
    cohort = jax.eval_shape(handle.gather_fn, clients, ids)
    out = {}
    closed, g_shape = jax.make_jaxpr(
        handle.gather_fn, return_shape=True)(clients, ids)
    out["gather"] = (closed,
                     _leaf_names("clients", clients)
                     + _leaf_names("ids", ids),
                     _leaf_names("cohort", g_shape))
    closed, s_shape = jax.make_jaxpr(
        handle.scatter_fn, return_shape=True)(clients, ids, cohort)
    out["scatter"] = (closed,
                      _leaf_names("clients", clients)
                      + _leaf_names("ids", ids)
                      + _leaf_names("cohort", cohort),
                      _leaf_names("clients", s_shape))
    return out


# ---------------------------------------------------------------------------
# baseline: violations grandfathered graftlint-style + exact costs


class AuditBaseline:
    """audit.baseline.json: {"violations": [{program, rule, count,
    justification}], "costs": {program: {flops, hbm_bytes}}}. Same
    exact-match semantics as graftlint's Baseline: new hits AND stale
    entries both error, so the file can only change deliberately.

    COST_KEY / COST_FIELDS parameterize the per-program cost block so
    the mesh tier (analysis/shardaudit.MeshBaseline) reuses the whole
    diff machinery over its per-link byte report."""

    COST_KEY = "costs"
    COST_FIELDS = ("flops", "hbm_bytes")
    DRIFT_RULE = "AU006"

    def __init__(self, violations=None, costs=None):
        self.violations: Dict[Tuple[str, str], Tuple[int, str]] = dict(
            violations or {})
        self.costs: Dict[str, dict] = dict(costs or {})

    @classmethod
    def load(cls, path: str) -> "AuditBaseline":
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        violations = {}
        for e in raw.get("violations", ()):
            violations[(e["program"], e["rule"])] = (
                int(e["count"]), e.get("justification", ""))
        return cls(violations, raw.get(cls.COST_KEY, {}))

    def dump(self, path: str) -> None:
        doc = {
            "version": 1,
            "violations": [
                {"program": p, "rule": r, "count": n,
                 "justification": j}
                for (p, r), (n, j) in sorted(self.violations.items())
            ],
            self.COST_KEY: {k: self.costs[k] for k in sorted(self.costs)},
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(doc, indent=2) + "\n")
        os.replace(tmp, path)

    def apply_violations(self, findings: Sequence[AuditFinding]
                         ) -> Tuple[List[AuditFinding], List[str]]:
        by_key: Dict[Tuple[str, str], List[AuditFinding]] = {}
        for f in findings:
            by_key.setdefault((f.program, f.rule), []).append(f)
        new: List[AuditFinding] = []
        stale: List[str] = []
        for key, fs in sorted(by_key.items()):
            if len(fs) > self.violations.get(key, (0, ""))[0]:
                new.extend(fs)
        for key, (count, _) in sorted(self.violations.items()):
            have = len(by_key.get(key, ()))
            if have < count:
                stale.append(
                    f"stale baseline entry {key[0]} {key[1]}: "
                    f"grandfathers {count}, audit found {have} — "
                    "regenerate with --write-baseline")
        return new, stale

    def apply_costs(self, costs: Dict[str, dict],
                    tolerance: float) -> List[AuditFinding]:
        out: List[AuditFinding] = []
        for prog in sorted(costs):
            got = costs[prog]
            base = self.costs.get(prog)
            if base is None:
                summary = ", ".join(f"{f}={got[f]}"
                                    for f in self.COST_FIELDS)
                out.append(AuditFinding(
                    prog, self.DRIFT_RULE,
                    f"no cost baseline for this program ({summary}); "
                    "a new program must be priced deliberately — run "
                    "--write-baseline and commit the diff"))
                continue
            for field in self.COST_FIELDS:
                want, have = int(base.get(field, 0)), int(got[field])
                lo = want * (1.0 - tolerance)
                hi = want * (1.0 + tolerance)
                if not (lo <= have <= hi):
                    direction = "regressed" if have > want else "moved"
                    out.append(AuditFinding(
                        prog, self.DRIFT_RULE,
                        f"static {field} {direction}: baseline {want}, "
                        f"traced {have} "
                        f"({(have - want) / max(want, 1):+.1%}, "
                        f"tolerance ±{tolerance:.1%}); if intentional, "
                        "--write-baseline and commit the diff"))
        for prog in sorted(self.costs):
            if prog not in costs:
                out.append(AuditFinding(
                    prog, self.DRIFT_RULE,
                    "stale cost baseline: program no longer traced by "
                    "the audit — regenerate with --write-baseline"))
        return out


# ---------------------------------------------------------------------------
# the shared graftaudit/graftmesh exit-code contract (ISSUE 8
# satellite): 0 clean, 1 rule violations, 2 baseline drift only —
# lives HERE (tier 2) because both CLIs depend on it and the mesh
# tier already imports this module, never the reverse. Drift is every
# *AU006-suffixed finding (AU006 cost drift, graftmesh's MAU006 link
# drift) plus stale baseline entries.


def split_findings(findings: Sequence[AuditFinding]
                   ) -> Tuple[List[AuditFinding], List[AuditFinding]]:
    """(rule violations, baseline drift)."""
    violations = [f for f in findings if not f.rule.endswith("AU006")]
    drift = [f for f in findings if f.rule.endswith("AU006")]
    return violations, drift


def exit_code(violations: Sequence, drift: Sequence,
              stale: Sequence) -> int:
    """0 clean, 1 rule violations (whatever else rode along), 2
    baseline drift only."""
    if violations:
        return 1
    if drift or stale:
        return 2
    return 0


# ---------------------------------------------------------------------------
# the full audit


def run_audit(backends: Sequence[str] = ("xla", "pallas"),
              inventory_configs: Sequence[str] = ()
              ) -> Tuple[dict, List[AuditFinding]]:
    """Trace every audit config x (round program variant + the two
    state-motion programs); return (report, findings). Findings carry
    AU001-AU005; AU006 (cost drift) is the caller's baseline diff —
    the report's `costs` block feeds it.

    Round programs are AU004-STRICT (population-shaped inputs/outputs
    error) unless the config name is in `inventory_configs` — the
    opt-out for workloads that legitimately keep dense in-round state
    ([tool.graftaudit] population_inventory_configs). The gather/
    scatter state-motion programs always run in inventory mode: their
    inventory is the named client-state map."""
    from commefficient_tpu.federated.round import program_variants_for

    programs: Dict[str, dict] = {}
    findings: List[AuditFinding] = []
    for cfg_name, cfg in audit_configs(backends):
        strict = cfg_name not in set(inventory_configs)
        handle, server, clients, variants, lr, key = build_workload(cfg)
        findings.extend(donation_findings(cfg_name, handle))
        # per-config program set: default configs trace the three
        # default variants; screened configs (ISSUE 16) trace the two
        # screened ones instead
        for variant in program_variants_for(cfg):
            prog = f"{cfg_name}/{variant}"
            closed, in_names, out_names = trace_variant(
                handle, server, clients, variants[variant], lr, key)
            findings.extend(
                forbidden_primitive_findings(prog, closed))
            inventory, pop_findings = population_scan(
                prog, closed, AUDIT_POPULATION, in_names, out_names,
                strict=strict)
            findings.extend(pop_findings)
            programs[prog] = {
                "cost": jaxpr_cost(closed).as_dict(),
                "population_inventory": inventory,
            }
        # state motion is variant-independent (gather/scatter only see
        # client_ids) — trace it from whichever variant the config's
        # family provides
        motion_batch = variants.get("mask_free",
                                    variants.get("screened"))
        for motion, (closed, in_names, out_names) in \
                trace_state_motion(handle, clients,
                                   motion_batch).items():
            prog = f"{cfg_name}/{motion}"
            findings.extend(
                forbidden_primitive_findings(prog, closed))
            inventory, pop_findings = population_scan(
                prog, closed, AUDIT_POPULATION, in_names, out_names,
                strict=False)
            findings.extend(pop_findings)
            programs[prog] = {
                "cost": jaxpr_cost(closed).as_dict(),
                "population_inventory": inventory,
            }
    report = {
        "version": 1,
        "geometry": dict(AUDIT_GEOMETRY,
                         population=AUDIT_POPULATION),
        "programs": programs,
        "costs": {p: {"flops": d["cost"]["flops"],
                      "hbm_bytes": d["cost"]["hbm_bytes"]}
                  for p, d in programs.items()},
    }
    report["digest"] = report_digest(report)
    # no set-dedup — see forbidden_primitive_findings
    return report, sorted(findings)


def report_digest(report: dict) -> str:
    """sha256 over the canonical cost block — the bit-identical-across-
    runs claim is checked on exactly this value."""
    canon = json.dumps({"geometry": report["geometry"],
                        "costs": report["costs"]},
                       sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def journal_digest(journal_path: str, report: dict,
                   findings_count: int) -> dict:
    """Append the audit's cost report to a run journal as an
    `audit_digest` event (schema checked by telemetry.journal.
    validate_journal / scripts/journal_summary.py)."""
    from commefficient_tpu.telemetry.journal import append_event
    return append_event(
        journal_path, "audit_digest",
        digest=report["digest"],
        geometry=report["geometry"],
        programs=report["costs"],
        findings=int(findings_count))


# ---------------------------------------------------------------------------
# CLI


def main(argv: Optional[list] = None) -> int:
    # never claim an accelerator: the audit only traces
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    argv = list(sys.argv[1:] if argv is None else argv)
    # third-tier delegation: `graftaudit --mesh [...]` IS the mesh
    # audit (analysis/shardaudit, also shipped as `graftmesh`) — the
    # remaining args are interpreted by graftmesh's own parser, so
    # `graftaudit --mesh --write-baseline` regenerates
    # meshaudit.baseline.json, not audit.baseline.json
    if "--mesh" in argv or "--list-meshes" in argv:
        from commefficient_tpu.analysis import shardaudit
        return shardaudit.main([a for a in argv if a != "--mesh"])

    from commefficient_tpu.analysis.engine import load_pyproject_tool
    conf = load_pyproject_tool("graftaudit")
    ap = argparse.ArgumentParser(
        prog="graftaudit",
        description="jaxpr-level program auditor: forbidden "
                    "primitives, population scaling, buffer donation, "
                    "static cost baselines (rules AU001-AU006; see "
                    "--list-rules). --mesh runs the mesh-aware third "
                    "tier (graftmesh, rules AU007-AU011) instead; "
                    "--list-meshes shows its mesh registry.")
    ap.add_argument("--baseline",
                    default=conf.get("baseline", "audit.baseline.json"),
                    help="baseline file (grandfathered violations + "
                         "committed per-program costs)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding and skip the cost diff")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from this audit")
    ap.add_argument("--cost-tolerance", type=float,
                    default=float(conf.get("cost_tolerance", 0.0)),
                    help="relative cost drift allowed before AU006 "
                         "(default 0.0: exact match)")
    ap.add_argument("--backends", nargs="*",
                    default=list(conf.get("backends",
                                          ["xla", "pallas"])),
                    help="kernel backends to trace the sketch "
                         "programs on")
    ap.add_argument("--inventory-configs", nargs="*",
                    default=list(conf.get(
                        "population_inventory_configs", [])),
                    help="audit-config names whose ROUND programs keep "
                         "the pre-ISSUE-9 AU004 inventory semantics "
                         "(population-shaped inputs/outputs reported, "
                         "not errored) — the opt-out for workloads "
                         "that legitimately keep dense in-round "
                         "client state")
    ap.add_argument("--journal", default="",
                    help="append the cost report to this JSONL run "
                         "journal as an `audit_digest` event")
    ap.add_argument("--report", action="store_true",
                    help="print the full JSON report to stdout")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        from commefficient_tpu.analysis.shardaudit import MESH_RULE_DOCS
        for code, doc in sorted({**AUDIT_RULE_DOCS,
                                 **MESH_RULE_DOCS}.items()):
            print(f"{code}  {doc}")
        return 0

    for b in args.backends:
        if b not in ("xla", "pallas"):
            # 3, not 2: exit 2 is reserved for baseline drift
            print(f"graftaudit: unknown backend {b!r}",
                  file=sys.stderr)
            return 3

    report, findings = run_audit(
        args.backends, inventory_configs=args.inventory_configs)

    if args.write_baseline:
        counts: Dict[Tuple[str, str], int] = {}
        for f in findings:
            counts[(f.program, f.rule)] = counts.get(
                (f.program, f.rule), 0) + 1
        AuditBaseline(
            {k: (n, "TODO: justify or fix") for k, n in counts.items()},
            report["costs"]).dump(args.baseline)
        print(f"graftaudit: wrote {len(findings)} grandfathered "
              f"finding(s) + {len(report['costs'])} program cost(s) "
              f"to {args.baseline}")
        return 0

    stale: List[str] = []
    if not args.no_baseline:
        baseline = (AuditBaseline.load(args.baseline)
                    if os.path.exists(args.baseline) else
                    AuditBaseline())
        new, stale = baseline.apply_violations(findings)
        cost_findings = baseline.apply_costs(
            report["costs"], args.cost_tolerance)
        findings = sorted(new + cost_findings)

    if args.report:
        print(json.dumps(report, indent=2, sort_keys=True))
    if args.journal:
        journal_digest(args.journal, report, len(findings))

    for f in findings:
        print(f.render())
    for msg in stale:
        print(f"graftaudit: {msg}")
    # exit-code contract shared with graftmesh (ISSUE 8 satellite):
    # 1 = rule violations (AU001-AU005), 2 = baseline drift only
    # (AU006 cost mismatch / stale entries) — CI can tell "the program
    # broke a contract" from "re-commit the baseline"
    violations, drift = split_findings(findings)
    rc = exit_code(violations, drift, stale)
    if rc:
        print(f"graftaudit: {len(violations)} violation(s), "
              f"{len(drift)} drift finding(s), {len(stale)} stale "
              f"baseline entr(ies)")
        return rc
    print(f"graftaudit: clean ({len(report['programs'])} program(s) "
          f"audited, digest {report['digest'][:12]})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""graftsync: static concurrency & durability-ordering auditor for the
host control plane (ISSUE 14) — the fourth analysis tier.

graftlint (tier 1) proves trace-safety syntactically; graftaudit /
graftmesh (tiers 2/3) prove the traced PROGRAMS' contracts. Nothing
proved the HOST concurrency contracts those programs ride on: since
PRs 10-13 the control plane runs three bounded-queue writer threads
(journal, checkpoint, state-spill), double-buffered pipelined
dispatch, a write-ahead RoundPlan journal, per-thread trace rings,
and the tiered store's plan/execute split — whose correctness rests
on hand-maintained lock discipline and ordering prose ("WAL flush
before dispatch", "drain the spill queue before the checkpoint
payload"). FetchSGD's error-feedback state makes those contracts
load-bearing for CONVERGENCE, not just crash-safety: a misordered
spill or a plan dispatched before its journal line is durable
silently corrupts the resume-bit-exactness invariant the whole
ROADMAP is anchored on. This module makes them mechanical, pure-AST
(jax-free, like graftlint), over the five host packages
(``telemetry/``, ``utils/``, ``federated/``, ``parallel/``,
``training/``):

  SY001  shared-state guard discipline. The central registry
         (analysis/domains.SHARED_STATE) declares which attributes
         are touched by more than one thread and which lock guards
         each. Every MUTATION of a registered ``Class.attr`` must sit
         lexically inside ``with self.<guard>:``; and an attribute
         the cross-thread scan proves shared — mutated both from a
         thread-entry function (a ``threading.Thread`` target, or a
         closure handed to a writer's ``.submit``, plus everything
         those reach through same-class ``self.*()`` calls) and from
         outside one — that is NOT registered is an error too: new
         shared state must be declared with its guard, exactly like
         a new PRNG stream must be declared in DOMAINS. Reads are
         deliberately out of scope (precision over recall — flagging
         every unguarded read would bury the signal; the mutation
         side is where lost updates and torn containers live).
  SY002  static lock-acquisition-order graph. Nested ``with lock:``
         scopes (and ``.acquire()`` calls under a held lock) define
         acquisition edges; a cycle in the union graph is a latent
         ABBA deadlock, reported with every edge's acquisition site.
         Lock identity is the self-rooted attribute qualified by its
         class (``TieredStateStore._lock``) or the dotted source
         expression otherwise; re-acquiring the SAME identity (the
         RLock idiom) adds no edge.
  SY003  queue-ownership transfer. A value ``put()`` on a writer
         queue (or ``submit()`` to a writer) is OWNED by the
         consumer thread from that line on: a later producer-side
         mutation of the same local is a data race with the drain
         loop — the journal avoids this by serializing records
         producer-side before enqueue, and this rule makes that
         contract mechanical. Rebinding the name releases tracking.
  SY004  blocking call under a held lock — the hung-fsync class
         ``utils/watchdog.py`` exists for, now caught before it
         ships: ``fsync`` / ``os.replace`` / a blocking queue
         ``put`` / ``join`` / ``.acquire()`` / a blocking device
         sync (``block_until_ready``, ``gather_host``) inside a
         ``with lock:`` body turns every other user of that lock
         into a hostage of the slow operation. The condition-variable
         idiom (``x.wait()`` while holding ``x`` — wait releases the
         lock) is recognized and exempt.
  SY005  thread lifecycle. Every constructed ``threading.Thread``
         must have a reachable ``join`` on the same binding
         somewhere in the file (the writers' ``close()`` paths) — a
         daemon thread with no join dies mid-write at interpreter
         exit, which for the spill writer means lost client state.
  SY006  durability-ordering registry. The named happens-before
         edges in analysis/domains.ORDERING_EDGES (WAL flush before
         span dispatch; spill-queue drain before the checkpoint
         payload's tail read; writer drain before the synchronous
         final save; the spill gather's device barrier before rows
         are handed to the writer) are checked as call-order
         dominance inside their registered functions: the `before`
         callee must be present and its first call must precede
         every call of `after` — so a refactor cannot silently drop
         a barrier. A missing function or a missing `after` call is
         an error as well: the edge must be re-registered
         deliberately, never rotted around.

Per-line suppressions use ``# graftsync: disable=SYxxx[,SYyyy]`` with
a justification after ``--`` (graftlint's convention), and the
exact-match JSON baseline (``graftsync.baseline.json``) has
graftlint semantics — new hits AND stale entries both fail, so the
file can only change deliberately. The SHIPPED baseline is EMPTY:
the tree is clean, and the audit's job is to keep it that way.

Exit codes share the graftaudit/graftmesh contract: 0 clean, 1 rule
violations, 2 baseline drift only (stale entries — regenerate with
``--write-baseline`` and commit the diff).

The report digest (sha256 over the canonical rule/file counts +
registry sizes) is bit-identical across runs; ``--journal`` appends
it as a ``sync_audit_digest`` event (schema-checked by
telemetry.journal.validate_journal like the other tiers' digests).

The runtime twin — the LockOrderSanitizer that records REAL
acquisition edges and asserts the graph acyclic at teardown, plus
the interleaving-stress helper — lives in analysis/runtime.py and is
armed over the pipeline/statetier/controlplane suites by
scripts/tier1.sh.
"""
from __future__ import annotations

import argparse
import ast
import hashlib
import json
import os
import re
import sys
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from commefficient_tpu.analysis.domains import (
    ORDERING_EDGES, SHARED_STATE,
)
from commefficient_tpu.analysis.engine import (
    Baseline, Violation, edges_to_graph, find_cycles,
    iter_python_files, load_pyproject_tool,
)
from commefficient_tpu.analysis.rules import _dotted, _terminal

SYNC_RULE_DOCS = {
    "SY001": "mutation of registered shared state outside its guard "
             "lock (analysis/domains.SHARED_STATE), or cross-thread-"
             "mutated state missing from the registry",
    "SY002": "cycle in the static lock-acquisition-order graph "
             "(nested `with lock:` scopes) — a latent ABBA deadlock",
    "SY003": "producer-side mutation of a value after it was put() on "
             "a writer queue / submit()ed to a writer thread",
    "SY004": "blocking call (fsync / os.replace / queue put / join / "
             "acquire / device sync) inside a held-lock body — the "
             "hung-fsync hostage class utils/watchdog exists for",
    "SY005": "threading.Thread constructed without a reachable join "
             "on the same binding (writer close() discipline)",
    "SY006": "durability-ordering edge violated: a registered "
             "happens-before barrier (analysis/domains.ORDERING_"
             "EDGES) is missing or no longer dominates its guarded "
             "call",
}

_SUPPRESS_RE = re.compile(r"#\s*graftsync:\s*disable=([A-Za-z0-9_,\s]+)")

# method calls that mutate their receiver container in place (SY001's
# and SY003's definition of "mutation" beyond assignment/del)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "clear", "update", "add",
    "setdefault", "put", "put_nowait", "move_to_end", "sort",
    "reverse", "write",
})

# SY004's blocking-call sets: plain dotted calls, and method attrs.
# `put` only counts on a queue-shaped receiver (see _queue_like);
# `put_nowait` and condition `.wait()` are deliberately absent (non-
# blocking / the cv idiom).
_BLOCKING_CALLS = frozenset({
    "os.fsync", "fsync", "os.replace", "os.rename", "time.sleep",
})
_BLOCKING_METHODS = frozenset({
    "join", "acquire", "block_until_ready", "gather_host", "drain",
    "drain_queue", "result",
})
_QUEUE_NAME_RE = re.compile(r"(^|_)q(ueue)?s?$|queue", re.IGNORECASE)

# sinks whose callable argument runs on another thread (SY001's
# thread-entry detection): Thread(target=...), and the bounded-queue
# writers' submit(job)
_SUBMIT_METHODS = frozenset({"submit"})


def _suppressions(source: str) -> Dict[int, set]:
    out: Dict[int, set] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip().upper() for r in m.group(1).split(",")
                      if r.strip()}
    return out


class SyncModule:
    """One parsed file plus the derived facts the SY rules share."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def enclosing(self, node: ast.AST, kinds) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                yield cur
            cur = self.parents.get(cur)

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        return next(self.enclosing(node, ast.ClassDef), None)

    def enclosing_function(self, node: ast.AST):
        return next(self.enclosing(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)),
            None)


# ---------------------------------------------------------------------------
# shared chain helpers


def _self_root_attr(expr: ast.AST) -> Optional[str]:
    """`self.a`, `self.a.b`, `self.a[k]`, `self.a[k].c` -> 'a';
    None when the chain is not rooted at `self`."""
    chain: List[Optional[str]] = []
    cur = expr
    while isinstance(cur, (ast.Attribute, ast.Subscript)):
        chain.append(cur.attr if isinstance(cur, ast.Attribute)
                     else None)
        cur = cur.value
    if isinstance(cur, ast.Name) and cur.id == "self":
        for attr in reversed(chain):
            return attr  # the attribute directly on self
    return None


def _root_name(expr: ast.AST) -> Tuple[Optional[str], int]:
    """(root Name id, chain depth) of an attribute/subscript chain:
    `x[k].a` -> ('x', 2); a bare `x` -> ('x', 0)."""
    depth = 0
    cur = expr
    while isinstance(cur, (ast.Attribute, ast.Subscript)):
        depth += 1
        cur = cur.value
    if isinstance(cur, ast.Name):
        return cur.id, depth
    return None, depth


def _mutations(scope: ast.AST) -> Iterator[Tuple[ast.AST, ast.expr]]:
    """(site node, mutated target chain) pairs inside `scope`:
    assignments, augmented assignments, deletes, and in-place mutator
    method calls. The caller classifies the chain (self-rooted vs
    local name)."""
    def _expand(tgt: ast.expr) -> Iterator[ast.expr]:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                yield from _expand(elt)
        elif isinstance(tgt, ast.Starred):
            yield from _expand(tgt.value)
        else:
            yield tgt

    for node in ast.walk(scope):
        if isinstance(node, ast.Assign):
            for raw in node.targets:
                for tgt in _expand(raw):
                    yield node, tgt
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if getattr(node, "value", None) is None:
                continue
            yield node, node.target
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                yield node, tgt
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS):
            yield node, node.func.value


def _with_lock_items(node: ast.AST) -> List[ast.expr]:
    """The lock-like context expressions of a With node (see
    _is_lock_expr), or []."""
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return []
    return [item.context_expr for item in node.items
            if _is_lock_expr(item.context_expr)]


def _is_lock_expr(expr: ast.expr) -> bool:
    """Heuristic lock detection for `with X:` — a plain Name/Attribute
    chain whose terminal contains 'lock' or names a Condition
    (`all_tasks_done`, `*_cv`, `*cond*`). Precision over recall: a
    lock held through an exotic alias is invisible, but everything
    this repo's writers do is covered, and false positives stay
    zero."""
    name = _dotted(expr)
    if not name:
        return False
    term = _terminal(name).lower()
    return ("lock" in term or term == "all_tasks_done"
            or term.endswith("_cv") or "cond" in term)


def _lock_identity(module: SyncModule, expr: ast.expr) -> str:
    """Stable identity for a lock expression: class-qualified for
    self-rooted attributes, the dotted source chain otherwise."""
    attr = _self_root_attr(expr)
    if attr is not None:
        cls = module.enclosing_class(expr)
        return f"{cls.name}.{attr}" if cls else f"self.{attr}"
    return _dotted(expr) or "<lock>"


def _held_locks(module: SyncModule, node: ast.AST) -> List[ast.expr]:
    """Lock expressions held (lexically) at `node`, outermost first —
    every enclosing `with <lock>:` item. The walk stops at function
    boundaries: a nested def's BODY does not run under the
    enclosing with (it merely closes over it)."""
    out: List[ast.expr] = []
    cur = module.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            break
        for item in _with_lock_items(cur):
            out.append(item)
        cur = module.parents.get(cur)
    out.reverse()
    return out


# ---------------------------------------------------------------------------
# SY001 — shared-state guard discipline


def _thread_entry_functions(module: SyncModule) -> Set[ast.AST]:
    """Function/lambda nodes whose body runs on another thread:
    Thread(target=...) targets, closures handed to a writer's
    .submit(), and everything those reach through same-class
    `self.method()` calls."""
    entry_names: Set[str] = set()
    entry_nodes: Set[ast.AST] = set()
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if _terminal(_dotted(node.func)) == "Thread":
            tgt = next((kw.value for kw in node.keywords
                        if kw.arg == "target"), None)
            if tgt is None and node.args:
                tgt = node.args[0]
            if isinstance(tgt, ast.Lambda):
                entry_nodes.add(tgt)
            elif tgt is not None:
                name = _terminal(_dotted(tgt))
                if name:
                    entry_names.add(name)
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr in _SUBMIT_METHODS):
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, ast.Lambda):
                    entry_nodes.add(a)
                elif isinstance(a, ast.Name):
                    entry_names.add(a.id)
    # resolve names to defs (methods or nested functions), then close
    # over the same-class `self.m()` call graph
    by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    work = [fn for name in entry_names for fn in by_name.get(name, ())]
    entry_nodes.update(work)
    while work:
        fn = work.pop()
        cls = module.enclosing_class(fn)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                continue
            for callee in by_name.get(node.func.attr, ()):
                if (callee not in entry_nodes
                        and module.enclosing_class(callee) is cls):
                    entry_nodes.add(callee)
                    work.append(callee)
    return entry_nodes


def _owning_function(module: SyncModule, node: ast.AST):
    return module.enclosing_function(node)


def _in_thread_domain(module: SyncModule, node: ast.AST,
                      entries: Set[ast.AST]) -> bool:
    """True when `node` sits lexically inside a thread-entry function
    (including nested defs of one)."""
    if node in entries:
        return True
    return any(fn in entries for fn in module.enclosing(
        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)))


def _under_guard(module: SyncModule, node: ast.AST,
                 guard: str) -> bool:
    # _held_locks is function-bounded: a nested def's body does not
    # hold the lock its enclosing function's `with` took
    return any(_self_root_attr(expr) == guard
               for expr in _held_locks(module, node))


def check_sy001(module: SyncModule) -> Iterator[Violation]:
    entries = _thread_entry_functions(module)
    for cls in (n for n in ast.walk(module.tree)
                if isinstance(n, ast.ClassDef)):
        # mutation sites per attribute: (site, in __init__?, thread?)
        sites: Dict[str, List[Tuple[ast.AST, bool, bool]]] = {}
        for site, target in _mutations(cls):
            if module.enclosing_class(target) is not cls:
                continue  # a nested class owns its own discipline
            attr = _self_root_attr(target)
            if attr is None:
                continue
            fn = _owning_function(module, site)
            in_init = (isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                       and fn.name == "__init__"
                       and module.enclosing_class(fn) is cls)
            sites.setdefault(attr, []).append(
                (site, in_init, _in_thread_domain(module, site,
                                                 entries)))
        for attr, hits in sorted(sites.items()):
            key = f"{cls.name}.{attr}"
            guard = SHARED_STATE.get(key)
            if guard is not None:
                for site, in_init, _ in hits:
                    if in_init:
                        continue  # construction precedes concurrency
                    if not _under_guard(module, site, guard):
                        yield Violation(
                            module.path, site.lineno, site.col_offset,
                            "SY001",
                            f"`self.{attr}` is registered shared "
                            f"state (SHARED_STATE[{key!r}]) but this "
                            f"mutation is not under `with "
                            f"self.{guard}:` — another thread can "
                            "observe a torn update; take the guard "
                            "or (if provably single-threaded here) "
                            "suppress with a justification")
                continue
            live = [(s, t) for s, init, t in hits if not init]
            if (any(t for _, t in live)
                    and any(not t for _, t in live)):
                for site, _ in live:
                    yield Violation(
                        module.path, site.lineno, site.col_offset,
                        "SY001",
                        f"`self.{attr}` is mutated both from a "
                        "thread-entry function and from outside one "
                        f"but `{key}` is not in the shared-state "
                        "registry: declare it (with its guard lock) "
                        "in analysis/domains.SHARED_STATE so the "
                        "guard discipline is enforced, or move the "
                        "mutation onto one thread")


# ---------------------------------------------------------------------------
# SY002 — static lock-order graph

# edge: (outer identity, inner identity) -> first acquisition site
LockEdges = Dict[Tuple[str, str], Tuple[str, int, int]]


def lock_order_edges(module: SyncModule) -> LockEdges:
    edges: LockEdges = {}
    for node in ast.walk(module.tree):
        inner_locks = _with_lock_items(node)
        explicit = None
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
                and _is_lock_expr(node.func.value)):
            explicit = node.func.value
        if not inner_locks and explicit is None:
            continue
        held = _held_locks(module, node)
        held_ids = [_lock_identity(module, h) for h in held]
        # `with a, b:` — a is held when b is acquired
        acquired = list(inner_locks)
        if explicit is not None:
            acquired.append(explicit)
        for i, expr in enumerate(acquired):
            inner_id = _lock_identity(module, expr)
            outers = held_ids + [_lock_identity(module, e)
                                 for e in inner_locks[:i]]
            for outer_id in outers:
                if outer_id == inner_id:
                    continue  # re-entrant acquire, no ordering edge
                edges.setdefault(
                    (outer_id, inner_id),
                    (module.path, expr.lineno, expr.col_offset))
    return edges


def sy002_findings(all_edges: LockEdges) -> List[Violation]:
    out: List[Violation] = []
    for cyc in find_cycles(edges_to_graph(all_edges)):
        sites = []
        for a, b in zip(cyc, cyc[1:]):
            path, line, col = all_edges[(a, b)]
            sites.append(f"{a} -> {b} at {path}:{line}")
        path, line, col = all_edges[(cyc[0], cyc[1])]
        out.append(Violation(
            path, line, col, "SY002",
            "static lock-acquisition-order cycle "
            f"{' -> '.join(cyc)} — two threads taking these locks in "
            "their written orders deadlock (ABBA); pick ONE global "
            f"order. Acquisition sites: {'; '.join(sites)}"))
    return out


# ---------------------------------------------------------------------------
# SY003 — producer-side mutation after enqueue


def check_sy003(module: SyncModule) -> Iterator[Violation]:
    funcs = [n for n in ast.walk(module.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in funcs:
        # events in source order within THIS function (nested defs
        # excluded: they are their own scope and typically ARE the
        # enqueued job)
        events: List[Tuple[int, int, str, str, ast.AST]] = []
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("put", "put_nowait",
                                           "submit")
                    and node.args
                    and isinstance(node.args[0], ast.Name)):
                events.append((node.lineno, node.col_offset, "enq",
                               node.args[0].id, node))
            elif isinstance(node, ast.Assign):
                flat: List[ast.expr] = []
                work = list(node.targets)
                while work:
                    tgt = work.pop()
                    if isinstance(tgt, (ast.Tuple, ast.List)):
                        work.extend(tgt.elts)
                    elif isinstance(tgt, ast.Starred):
                        work.append(tgt.value)
                    else:
                        flat.append(tgt)
                for tgt in flat:
                    if isinstance(tgt, ast.Name):
                        events.append((node.lineno, node.col_offset,
                                       "rebind", tgt.id, node))
                    else:
                        name, depth = _root_name(tgt)
                        if name and depth:
                            events.append((node.lineno,
                                           node.col_offset, "mut",
                                           name, node))
            elif isinstance(node, ast.AugAssign):
                name, depth = _root_name(node.target)
                if name:
                    events.append((node.lineno, node.col_offset,
                                   "mut", name, node))
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    name, depth = _root_name(tgt)
                    if name and depth:
                        events.append((node.lineno, node.col_offset,
                                       "mut", name, node))
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS):
                name, _ = _root_name(node.func.value)
                if name:
                    events.append((node.lineno, node.col_offset,
                                   "mut", name, node))
        enqueued: Dict[str, int] = {}
        for lineno, col, kind, name, node in sorted(
                events, key=lambda e: (e[0], e[1])):
            if kind == "enq":
                enqueued[name] = lineno
            elif kind == "rebind":
                enqueued.pop(name, None)
            elif kind == "mut" and name in enqueued:
                yield Violation(
                    module.path, lineno, col, "SY003",
                    f"`{name}` was handed to a writer queue at line "
                    f"{enqueued[name]} and is mutated afterwards on "
                    "the producer side: the drain loop may be "
                    "reading it concurrently (torn record). "
                    "Serialize/copy before enqueue (the journal's "
                    "producer-side-serialize contract) or rebind a "
                    "fresh value")


# ---------------------------------------------------------------------------
# SY004 — blocking call under a held lock


def _queue_like(expr: ast.AST) -> bool:
    name = _dotted(expr)
    if not name:
        return False
    return bool(_QUEUE_NAME_RE.search(_terminal(name)))


def check_sy004(module: SyncModule) -> Iterator[Violation]:
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        held = _held_locks(module, node)
        if not held:
            continue
        name = _dotted(node.func)
        what = None
        if name in _BLOCKING_CALLS:
            what = f"`{name}()`"
        elif isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _BLOCKING_METHODS:
                # the condition-variable idiom: waiting/acquiring ON
                # the very object you hold is how Condition works
                recv = _dotted(node.func.value)
                held_names = {_dotted(h) for h in held}
                if not (attr == "acquire" and recv in held_names):
                    what = f"`.{attr}()`"
            elif attr == "put" and _queue_like(node.func.value):
                what = "a blocking queue `.put()`"
        if what is None:
            continue
        locks = ", ".join(_lock_identity(module, h) for h in held)
        yield Violation(
            module.path, node.lineno, node.col_offset, "SY004",
            f"{what} while holding {locks}: a slow or hung operation "
            "(dead NFS fsync, a full bounded queue) here blocks every "
            "other user of the lock — the hostage class "
            "utils/watchdog exists for. Move the blocking work "
            "outside the critical section (capture under the lock, "
            "write outside it)")


# ---------------------------------------------------------------------------
# SY005 — thread lifecycle (construct => join)


def check_sy005(module: SyncModule) -> Iterator[Violation]:
    joins: Set[str] = set()
    for node in ast.walk(module.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            name = _dotted(node.func.value)
            if name:
                joins.add(_terminal(name))
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call)
                and _dotted(node.func) in ("threading.Thread",
                                           "Thread")):
            continue
        parent = module.parents.get(node)
        binding = None
        targets: List[ast.expr] = []
        if isinstance(parent, ast.Assign):
            targets = list(parent.targets)
        elif isinstance(parent, ast.AnnAssign):
            targets = [parent.target]
        for tgt in targets:
            attr = _self_root_attr(tgt)
            if attr is not None:
                binding = attr
            elif isinstance(tgt, ast.Name):
                binding = tgt.id
        if binding is not None and binding in joins:
            continue
        where = (f"binding `{binding}` is never .join()ed"
                 if binding is not None
                 else "the Thread is never bound, so it can never be "
                      "joined")
        yield Violation(
            module.path, node.lineno, node.col_offset, "SY005",
            f"threading.Thread constructed but {where} in this file: "
            "without a close()-path join the thread dies mid-write at "
            "interpreter exit (for a writer queue that is LOST "
            "state); keep the handle and join it on the close/finally "
            "path (the AsyncCheckpointWriter.close discipline)")


# ---------------------------------------------------------------------------
# SY006 — durability-ordering dominance


def _function_named(tree: ast.Module, name: str):
    for node in ast.walk(tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == name):
            return node
    return None


def _own_body_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Nodes in `fn`'s OWN body — nested def/lambda bodies pruned. A
    barrier moved into a closure (called conditionally, or not at
    all) does not dominate anything at runtime, so SY006 must not
    count it; same scoping rule as SY003."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def ordering_findings(files: Dict[str, Tuple[str, ast.Module]],
                      edges: Optional[dict] = None
                      ) -> List[Violation]:
    """SY006 over a {normalized path: (source, tree)} map. Exposed
    separately so tests can prove the delete-a-barrier-turns-red
    property on SCRATCH COPIES of the registered functions (fixture
    source) without mutating the tree."""
    edges = ORDERING_EDGES if edges is None else edges
    out: List[Violation] = []
    for name, edge in sorted(edges.items()):
        target = edge["path"].replace(os.sep, "/")
        match = next((p for p in sorted(files)
                      if p.endswith(target) or target.endswith(p)),
                     None)
        if match is None:
            out.append(Violation(
                target, 1, 0, "SY006",
                f"ordering edge `{name}`: registered file {target!r} "
                "was not scanned — the audit paths no longer cover "
                "it, so the contract is unenforced (fix the paths or "
                "re-register the edge)"))
            continue
        source, tree = files[match]
        fn = _function_named(tree, edge["function"])
        if fn is None:
            out.append(Violation(
                match, 1, 0, "SY006",
                f"ordering edge `{name}`: function "
                f"`{edge['function']}` no longer exists in {target} — "
                "the happens-before contract "
                f"(`{edge['before']}` before `{edge['after']}`: "
                f"{edge['why']}) must be re-registered on its new "
                "home, not dropped"))
            continue
        befores: List[int] = []
        afters: List[Tuple[int, int]] = []
        for node in _own_body_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            term = _terminal(_dotted(node.func))
            if term == edge["before"]:
                befores.append(node.lineno)
            elif term == edge["after"]:
                afters.append((node.lineno, node.col_offset))
        if not afters:
            out.append(Violation(
                match, fn.lineno, fn.col_offset, "SY006",
                f"ordering edge `{name}`: `{edge['function']}` no "
                f"longer calls `{edge['after']}` — the guarded "
                "operation moved; move the registered edge with it "
                f"(contract: {edge['why']})"))
            continue
        if not befores:
            out.append(Violation(
                match, fn.lineno, fn.col_offset, "SY006",
                f"ordering edge `{name}`: the `{edge['before']}` "
                f"barrier is GONE from `{edge['function']}` but "
                f"`{edge['after']}` still runs — {edge['why']}"))
            continue
        first_before = min(befores)
        for lineno, col in sorted(afters):
            if lineno < first_before:
                out.append(Violation(
                    match, lineno, col, "SY006",
                    f"ordering edge `{name}`: `{edge['after']}` at "
                    f"line {lineno} runs BEFORE the first "
                    f"`{edge['before']}` barrier (line "
                    f"{first_before}) — {edge['why']}"))
    return out


# ---------------------------------------------------------------------------
# per-file driver + whole-tree audit

_PER_FILE_RULES = {
    "SY001": check_sy001,
    "SY003": check_sy003,
    "SY004": check_sy004,
    "SY005": check_sy005,
}


class SyncLintError(RuntimeError):
    """A file could not be parsed."""


def sync_source(path: str, source: str,
                edges: Optional[dict] = None) -> List[Violation]:
    """Audit ONE file's source (per-file rules SY001/SY003/SY004/
    SY005, the file's own SY002 lock graph, and — when `edges` is
    given — SY006 against just this file). Suppressions applied.
    The test-suite entry point; the CLI uses run_sync_audit."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        raise SyncLintError(f"{path}: syntax error: {e}") from e
    module = SyncModule(path, source, tree)
    suppressed = _suppressions(source)
    out: List[Violation] = []
    for rule, check in _PER_FILE_RULES.items():
        out.extend(check(module))
    out.extend(sy002_findings(lock_order_edges(module)))
    if edges is not None:
        out.extend(ordering_findings(
            {path.replace(os.sep, "/"): (source, tree)}, edges))
    return sorted(set(
        v for v in out if v.rule not in suppressed.get(v.line, ())))


def run_sync_audit(paths: Sequence[str], exclude: Sequence[str] = ()
                   ) -> Tuple[dict, List[Violation]]:
    """(report, findings) over the configured host packages: per-file
    rules + the UNION lock-order graph (SY002 across files — an ABBA
    pair may live in two modules) + the SY006 ordering registry."""
    findings: List[Violation] = []
    all_edges: LockEdges = {}
    parsed: Dict[str, Tuple[str, ast.Module]] = {}
    suppressed_by_path: Dict[str, Dict[int, set]] = {}
    for path in iter_python_files(paths, exclude):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        rel = os.path.relpath(path).replace(os.sep, "/")
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            raise SyncLintError(f"{rel}: syntax error: {e}") from e
        module = SyncModule(rel, source, tree)
        suppressed_by_path[rel] = _suppressions(source)
        parsed[rel] = (source, tree)
        for rule, check in _PER_FILE_RULES.items():
            findings.extend(check(module))
        for key, site in lock_order_edges(module).items():
            all_edges.setdefault(key, site)
    findings.extend(sy002_findings(all_edges))
    findings.extend(ordering_findings(parsed))
    findings = sorted(set(
        v for v in findings
        if v.rule not in suppressed_by_path.get(v.path, {}).get(
            v.line, ())))
    by_file: Dict[str, Dict[str, int]] = {}
    rules: Dict[str, int] = {r: 0 for r in SYNC_RULE_DOCS}
    for v in findings:
        rules[v.rule] = rules.get(v.rule, 0) + 1
        by_file.setdefault(v.path, {}).setdefault(v.rule, 0)
        by_file[v.path][v.rule] += 1
    report = {
        "version": 1,
        "files_scanned": len(parsed),
        "rules": rules,
        "by_file": {p: dict(sorted(c.items()))
                    for p, c in sorted(by_file.items())},
        "registry": {"shared_state": len(SHARED_STATE),
                     "ordering_edges": len(ORDERING_EDGES),
                     "lock_order_edges": len(all_edges)},
    }
    report["digest"] = report_digest(report)
    return report, findings


def report_digest(report: dict) -> str:
    """sha256 over the canonical finding/registry counts — the
    bit-identical-across-runs claim is checked on exactly this value
    (same contract as graftaudit's report_digest)."""
    canon = json.dumps({"rules": report["rules"],
                        "by_file": report["by_file"],
                        "registry": report["registry"]},
                       sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def journal_digest(journal_path: str, report: dict,
                   findings_count: int) -> dict:
    """Append the audit's report to a run journal as a
    `sync_audit_digest` event (schema checked by telemetry.journal.
    validate_journal / scripts/journal_summary.py, mirroring
    audit_digest / mesh_audit_digest)."""
    from commefficient_tpu.telemetry.journal import append_event
    return append_event(
        journal_path, "sync_audit_digest",
        digest=report["digest"],
        rules=report["rules"],
        registry=report["registry"],
        findings=int(findings_count))


# ---------------------------------------------------------------------------
# CLI

DEFAULT_PATHS = [
    "commefficient_tpu/telemetry",
    "commefficient_tpu/utils",
    "commefficient_tpu/federated",
    "commefficient_tpu/parallel",
    "commefficient_tpu/training",
]


def main(argv: Optional[list] = None) -> int:
    from commefficient_tpu.analysis.audit import exit_code
    conf = load_pyproject_tool("graftsync")
    ap = argparse.ArgumentParser(
        prog="graftsync",
        description="static concurrency & durability-ordering auditor "
                    "for the host control plane (rules SY001-SY006; "
                    "see --list-rules). Exit codes: 0 clean, 1 rule "
                    "violations, 2 baseline drift only.")
    ap.add_argument("paths", nargs="*",
                    default=conf.get("paths", DEFAULT_PATHS),
                    help="files/directories to audit")
    ap.add_argument("--baseline", default=conf.get(
        "baseline", "graftsync.baseline.json"),
        help="baseline file of grandfathered hits (shipped EMPTY: "
             "the tree is clean)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every hit, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the current "
                         "tree")
    ap.add_argument("--journal", default="",
                    help="append the report to this JSONL run journal "
                         "as a `sync_audit_digest` event")
    ap.add_argument("--report", action="store_true",
                    help="print the full JSON report to stdout")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, doc in sorted(SYNC_RULE_DOCS.items()):
            print(f"{code}  {doc}")
        return 0

    for p in args.paths:
        if not os.path.exists(p):
            print(f"graftsync: no such path: {p}", file=sys.stderr)
            return 3  # 2 is reserved for baseline drift

    try:
        report, findings = run_sync_audit(
            args.paths, exclude=conf.get("exclude", ()))
    except SyncLintError as e:
        print(f"graftsync: {e}", file=sys.stderr)
        return 1

    if args.write_baseline:
        Baseline.from_violations(findings).dump(args.baseline)
        print(f"graftsync: wrote {len(findings)} grandfathered "
              f"hit(s) to {args.baseline}")
        return 0

    baseline = Baseline()
    if not args.no_baseline and os.path.exists(args.baseline):
        baseline = Baseline.load(args.baseline)
    new, stale = baseline.apply(findings)

    if args.report:
        print(json.dumps(report, indent=2, sort_keys=True))
    if args.journal:
        journal_digest(args.journal, report, len(new))

    for v in new:
        print(v.render())
    for msg in stale:
        print(f"graftsync: {msg}")
    # shared graftaudit/graftmesh exit-code contract: 1 = rule
    # violations, 2 = baseline drift only (stale entries)
    rc = exit_code(new, [], stale)
    if rc:
        print(f"graftsync: {len(new)} violation(s), {len(stale)} "
              f"stale baseline entr(ies)")
        return rc
    grandfathered = len(findings)
    print(f"graftsync: clean ({report['files_scanned']} file(s), "
          f"{report['registry']['shared_state']} guarded attr(s), "
          f"{report['registry']['ordering_edges']} ordering edge(s), "
          f"digest {report['digest'][:12]})"
          + (f" — {grandfathered} grandfathered hit(s), see "
             f"{args.baseline}" if grandfathered else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
